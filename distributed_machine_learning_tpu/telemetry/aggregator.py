"""Gang-wide metric aggregation — the cross-rank half of telemetry.

PR 2 gave every process its own registry, JSONL stream, and trace; PRs
3/5 turned training into an elastic multi-rank gang.  What neither
layer shows is the *relation* between ranks: a straggler is invisible
in its own stream (every step it completes looks normal — it just
completes them late), and a lock-step gang converts one slow rank into
N blocked ones, so per-rank dashboards show everyone equally idle.
"Massively Distributed SGD" (PAPERS.md, arxiv 1811.05233) attributes
its wins to exactly this cross-replica accounting: you cannot run
backup workers — or even pick a sane batch size — without knowing the
per-step spread across ranks.

This module is the reader/rollup side of that story, deliberately
stdlib-only (no jax, no numpy) so the ``tools/`` layer can run it on a
bare host against a dead run's directory:

- :func:`discover_rank_streams` — find the per-rank artifacts under a
  gang telemetry dir, in either layout: rank-suffixed files
  (``metrics.rank<r>.jsonl``, the collision-safe default the gang
  worker writes) or per-rank subdirectories (``rank<r>/metrics.jsonl``).
- :func:`aggregate_gang_metrics` — per-step cross-rank rollups:
  min/median/p95/max across ranks for step time and every per-phase
  duration (``data_wait_s``/``place_s``/``dispatch_s``/``block_s`` from
  the train loop; ``barrier_wait_s``/``compute_s`` from the gang
  worker), per-rank examples/s, and a per-step **skew ratio**
  (slowest rank / median rank).
- :class:`StragglerDetector` — flags ranks whose rolling step time
  exceeds a configurable multiple of the gang median for K consecutive
  observations.  Used offline over the metrics streams (here) and live
  over heartbeat snapshots (``runtime/supervisor.py::gang_supervise``).
- :class:`HeartbeatSampler` — effective per-rank step times from the
  beat files ``runtime/coordinator.py`` writes, on the same
  locally-observed-change staleness basis as the coordinator's own
  peer checks (never cross-host mtime/wall-clock comparison).

File-name constants here mirror the *writer* modules (the payloads are
read tolerantly, so a torn final line — the artifact of the crash being
diagnosed — never kills the diagnosis): ``beat_rank<r>.json`` and
``gang_health.jsonl`` are written by ``runtime/coordinator.py``,
``faults_fired.jsonl`` by ``runtime/faults.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time

from distributed_machine_learning_tpu.telemetry.sink import read_jsonl
from distributed_machine_learning_tpu.utils.timing import percentile

# Writer-side names, mirrored so the stdlib tools can read a gang dir
# without importing the (jax-heavy) runtime package.
BEAT_PREFIX = "beat_rank"             # runtime/coordinator.py heartbeats
GANG_HEALTH_FILE = "gang_health.jsonl"  # supervisor advisory ledger
FAULT_LEDGER_FILE = "faults_fired.jsonl"  # runtime/faults.py firings
CONSUMED_PREFIX = "consumed_rank"     # gang worker consumption ledgers

# Keys every metrics row may carry; any other numeric key ending in
# "_s" is treated as a per-phase duration (so the train loop's
# data_wait_s/place_s/... and the gang worker's barrier_wait_s/... are
# aggregated by one rule, and new phases need no registry here).
# Rates ("*_per_s") and the whole-step time are not phases.
_STEP_KEY = "step"
_ITER_KEY = "iter_s"
_NON_PHASE_KEYS = {_ITER_KEY}


def _is_phase_key(k: str) -> bool:
    return (k.endswith("_s") and not k.endswith("_per_s")
            and k not in _NON_PHASE_KEYS)

_RANK_FILE_RE = re.compile(r"^metrics\.rank(\d+)\.jsonl$")
_RANK_DIR_RE = re.compile(r"^rank(\d+)$")


def median(values) -> float:
    """Exact median (midpoint of the two central order statistics for
    even counts) — public: the supervisor and the status tool share it,
    so "the gang median" means one thing everywhere."""
    xs = sorted(values)
    if not xs:
        return 0.0
    mid = len(xs) // 2
    if len(xs) % 2:
        return float(xs[mid])
    return (xs[mid - 1] + xs[mid]) / 2.0


def _spread(values: list[float]) -> dict:
    """The cross-rank rollup block: min/median/p95/max over one step's
    per-rank values (p95 interpolates order statistics — with a handful
    of ranks it tracks the max, which is the honest reading)."""
    return {
        "min": min(values),
        "median": median(values),
        "p95": percentile(values, 0.95),
        "max": max(values),
    }


def discover_rank_streams(root: str | os.PathLike) -> dict[int, dict]:
    """rank -> {"metrics": path, "trace": path|None, "registry":
    path|None, "dir": path} for every per-rank stream under ``root``.

    Two layouts are recognized (both appear in practice):

    - **suffix layout** (the gang default): ``metrics.rank<r>.jsonl`` /
      ``trace.rank<r>.json`` directly under ``root`` — N processes
      sharing one directory with collision-safe names;
    - **subdir layout**: ``rank<r>/metrics.jsonl`` — each rank pointed
      at its own ``--telemetry-dir``.

    When both exist for a rank, the suffix layout wins (it is the one
    the current worker writes; a subdir is a leftover of an older
    launcher).  Ranks are ORIGINAL-numbering identities: a renumbered
    survivor keeps appending to its original stream, so one rank maps
    to one stream across shrinks.
    """
    root = os.fspath(root)
    out: dict[int, dict] = {}
    if not os.path.isdir(root):
        return out

    def entry(rank: int, metrics: str, trace: str, registry: str,
              base: str) -> None:
        if rank in out:
            return
        out[rank] = {
            "metrics": metrics if os.path.isfile(metrics) else None,
            "trace": trace if os.path.isfile(trace) else None,
            "registry": registry if os.path.isfile(registry) else None,
            "dir": base,
        }

    names = sorted(os.listdir(root))
    for name in names:
        m = _RANK_FILE_RE.match(name)
        if m:
            r = int(m.group(1))
            entry(
                r,
                os.path.join(root, name),
                os.path.join(root, f"trace.rank{r}.json"),
                os.path.join(root, f"registry.rank{r}.json"),
                root,
            )
    for name in names:
        m = _RANK_DIR_RE.match(name)
        if m and os.path.isdir(os.path.join(root, name)):
            r = int(m.group(1))
            base = os.path.join(root, name)
            entry(
                r,
                os.path.join(base, "metrics.jsonl"),
                os.path.join(base, "trace.json"),
                os.path.join(base, "registry.json"),
                base,
            )
    # Drop ranks with no readable metrics stream at all (an empty
    # rank<r>/ dir from a worker that died pre-first-row still shows up
    # in the trace discovery of tools/trace_merge.py, not here).
    return {r: e for r, e in out.items() if e["metrics"] is not None}


def _rank_step_rows(streams: dict[int, dict]
                    ) -> dict[int, dict[int, dict]]:
    """rank -> step -> the authoritative metrics row for that step.

    Restarted attempts replay steps, so one (rank, step) can have many
    rows; the LAST row of the HIGHEST attempt wins — it belongs to the
    attempt that actually carried the run past this step.  Warm-up
    rows (compile steps, timer-excluded) are skipped the same way
    ``tools/trace_summary.py`` skips them: a compile belongs on the
    timeline, not in a skew ratio.
    """
    out: dict[int, dict[int, dict]] = {}
    for rank, entry in sorted(streams.items()):
        best: dict[int, tuple[int, int, dict]] = {}
        try:
            rows = read_jsonl(entry["metrics"])
        except OSError:
            continue
        for order, row in enumerate(rows):
            if not isinstance(row, dict) or row.get("warmup"):
                continue
            step = row.get(_STEP_KEY)
            if not isinstance(step, int) or _ITER_KEY not in row:
                continue
            key = (int(row.get("attempt", 0)), order)
            cur = best.get(step)
            if cur is None or key >= cur[:2]:
                best[step] = (*key, row)
        out[rank] = {s: r for s, (_, _, r) in best.items()}
    return out


def _phase_keys(rows: list[dict]) -> list[str]:
    keys: set[str] = set()
    for row in rows:
        for k, v in row.items():
            if _is_phase_key(k) and isinstance(v, (int, float)):
                keys.add(k)
    return sorted(keys)


@dataclasses.dataclass
class StragglerVerdict:
    """One flagged rank: its rolling step time ``value_s`` held above
    ``multiple`` x the gang median ``median_s`` for ``streak``
    consecutive observations."""

    rank: int
    ratio: float
    value_s: float
    median_s: float
    streak: int
    step: int | None = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class StragglerDetector:
    """Flags ranks whose step time runs away from the gang median.

    Feed :meth:`update` one sample per rank per observation window (a
    completed step offline; a supervisor poll live).  A rank is flagged
    when its value exceeds ``multiple`` x the median across ranks for
    ``consecutive`` observations in a row — one flag per episode: the
    rank must drop back under the threshold (which also resets its
    streak) before it can be flagged again.  ``None`` samples (rank has
    published no timing yet) are ignored; fewer than ``min_ranks``
    usable samples means no judgement at all — a median of one is not a
    gang.

    The detector is advisory by design (this PR detects; a later
    elastic-grow/backup-worker policy consumes): it never aborts
    anything, it only produces verdicts for counters, the health
    ledger, and the supervisor log.
    """

    def __init__(self, multiple: float = 4.0, consecutive: int = 3,
                 min_ranks: int = 2):
        if multiple <= 1.0:
            raise ValueError(
                f"multiple must be > 1 (a rank at the median is not a "
                f"straggler), got {multiple}"
            )
        if consecutive < 1:
            raise ValueError(
                f"consecutive must be >= 1, got {consecutive}"
            )
        if min_ranks < 2:
            raise ValueError(f"min_ranks must be >= 2, got {min_ranks}")
        self.multiple = multiple
        self.consecutive = consecutive
        self.min_ranks = min_ranks
        self.flagged: set[int] = set()
        self.flags_total = 0
        self.skew_ratio = 0.0
        self._streak: dict[int, int] = {}

    def update(self, samples: dict[int, float | None],
               step: int | None = None) -> list[StragglerVerdict]:
        clean = {r: float(v) for r, v in samples.items() if v is not None}
        if len(clean) < self.min_ranks:
            return []
        med = median(clean.values())
        self.skew_ratio = max(clean.values()) / med if med > 0 else 0.0
        if med <= 0:
            return []
        verdicts = []
        for rank in sorted(clean):
            v = clean[rank]
            if v > self.multiple * med:
                self._streak[rank] = self._streak.get(rank, 0) + 1
                if (self._streak[rank] >= self.consecutive
                        and rank not in self.flagged):
                    self.flagged.add(rank)
                    self.flags_total += 1
                    verdicts.append(StragglerVerdict(
                        rank=rank, ratio=v / med, value_s=v, median_s=med,
                        streak=self._streak[rank], step=step,
                    ))
            else:
                self._streak[rank] = 0
                self.flagged.discard(rank)  # recovery re-arms the flag
        return verdicts

    def reset_rank(self, rank: int) -> None:
        """Forget ``rank``'s episode state — the replace-policy hook:
        when a flagged rank is evicted and a new incarnation admitted
        (serving re-promotion, gang replace), the new one must be
        judged fresh, not inherit the old flag."""
        self._streak.pop(rank, None)
        self.flagged.discard(rank)


_SERVING_BY_RE = re.compile(r"^replica(\d+)$")


def serving_stage_samples(events, stage: str = "computed"
                          ) -> dict[int, float]:
    """Per-replica duration samples for one stage out of a request's
    stage-event record (ISSUE 17) — ``{rank: dt_seconds}`` for every
    ``stage`` event stamped by a ``replica<r>`` actor with a rank-local
    delta attached (``dt`` is None when the prior stamp crossed a
    process boundary; those carry no duration and are skipped).

    This is the serving feed for :class:`StragglerDetector`: the
    ``computed`` event's ``dt`` is exactly the replica's compute
    interval (``computed`` − ``bound`` on that replica's own monotonic
    clock), so serving eviction and training straggler detection judge
    through one detector code path instead of the router keeping its
    own service-time bookkeeping off the beat channel.  When a request
    was attempted on several replicas (requeue after a death), the last
    sample per rank wins — the freshest observation of that replica.
    """
    out: dict[int, float] = {}
    for ev in events or ():
        if not isinstance(ev, dict) or ev.get("stage") != stage:
            continue
        dt = ev.get("dt")
        if not isinstance(dt, (int, float)):
            continue
        m = _SERVING_BY_RE.match(str(ev.get("by", "")))
        if m:
            out[int(m.group(1))] = float(dt)
    return out


@dataclasses.dataclass
class GangRollup:
    """Everything :func:`aggregate_gang_metrics` derives from a gang's
    per-rank streams — JSON-ready via :meth:`as_dict`."""

    ranks: list[int]
    steps: list[dict]          # per-step cross-rank rollups, step order
    per_rank: dict[int, dict]  # per-rank totals (rows, means, attempts)
    skew: dict                 # spread of the per-step skew ratios
    stragglers: list[dict]     # offline StragglerVerdicts, as dicts
    phases: list[str]          # every phase key seen in any stream

    def as_dict(self) -> dict:
        return {
            "ranks": self.ranks,
            "steps": self.steps,
            "per_rank": {str(r): v for r, v in self.per_rank.items()},
            "skew": self.skew,
            "stragglers": self.stragglers,
            "phases": self.phases,
        }


def aggregate_gang_metrics(root: str | os.PathLike, *, window: int = 4,
                           multiple: float = 4.0, consecutive: int = 3
                           ) -> GangRollup:
    """Cross-rank rollups over every per-rank metrics stream under
    ``root``.

    Per step (only ranks that recorded the step contribute — an
    elastic gang's lost rank simply stops contributing): the
    min/median/p95/max spread of ``iter_s`` and of every phase
    duration, per-rank examples/s, and ``skew`` = slowest/median
    ``iter_s``.  The offline straggler pass runs the same
    :class:`StragglerDetector` the live supervisor uses, over a
    ``window``-step rolling mean per rank.
    """
    streams = discover_rank_streams(root)
    by_rank = _rank_step_rows(streams)
    ranks = sorted(by_rank)
    all_steps = sorted({s for rows in by_rank.values() for s in rows})
    all_rows = [row for rows in by_rank.values() for row in rows.values()]
    phases = _phase_keys(all_rows)

    detector = StragglerDetector(multiple=multiple,
                                 consecutive=consecutive)
    rolling: dict[int, list[float]] = {r: [] for r in ranks}
    steps_out: list[dict] = []
    skews: list[float] = []
    verdicts: list[dict] = []
    for step in all_steps:
        present = {r: by_rank[r][step] for r in ranks
                   if step in by_rank[r]}
        iters = {r: float(row[_ITER_KEY]) for r, row in present.items()}
        entry: dict = {
            "step": step,
            "ranks": sorted(present),
            "iter_s": _spread(list(iters.values())),
        }
        med = median(iters.values())
        skew = max(iters.values()) / med if med > 0 else 0.0
        entry["skew"] = skew
        if skew:
            skews.append(skew)
        phase_block = {}
        for key in phases:
            vals = [float(row[key]) for row in present.values()
                    if isinstance(row.get(key), (int, float))]
            if vals:
                phase_block[key] = _spread(vals)
        if phase_block:
            entry["phases"] = phase_block
        eps = {r: float(row["examples_per_s"])
               for r, row in present.items()
               if isinstance(row.get("examples_per_s"), (int, float))}
        if eps:
            entry["examples_per_s"] = {str(r): v for r, v in eps.items()}
        steps_out.append(entry)
        # Offline straggler pass: rolling mean per rank, judged at the
        # step granularity — the same detector the supervisor feeds
        # live heartbeat samples.
        feed = {}
        for r, v in iters.items():
            win = rolling[r]
            win.append(v)
            del win[:-window]
            feed[r] = sum(win) / len(win)
        for v in detector.update(feed, step=step):
            verdicts.append(v.as_dict())

    per_rank: dict[int, dict] = {}
    for r in ranks:
        rows = list(by_rank[r].values())
        iters = [float(row[_ITER_KEY]) for row in rows]
        eps = [float(row["examples_per_s"]) for row in rows
               if isinstance(row.get("examples_per_s"), (int, float))]
        per_rank[r] = {
            "rows": len(rows),
            "attempts": sorted({int(row.get("attempt", 0))
                                for row in rows}),
            "last_step": max(by_rank[r]) if by_rank[r] else None,
            "iter_s_mean": sum(iters) / len(iters) if iters else 0.0,
            "examples_per_s_mean": (sum(eps) / len(eps)) if eps else None,
        }
    skew_block = _spread(skews) if skews else {
        "min": 0.0, "median": 0.0, "p95": 0.0, "max": 0.0
    }
    skew_block["last"] = skews[-1] if skews else 0.0
    return GangRollup(ranks=ranks, steps=steps_out, per_rank=per_rank,
                      skew=skew_block, stragglers=verdicts, phases=phases)


def publish_rollup(rollup: GangRollup, registry) -> None:
    """Mirror a rollup's verdicts into a metrics registry —
    ``gang_skew_ratio`` gauge (the run's latest per-step skew) and one
    ``gang_straggler{rank=...}`` count per offline verdict.  For
    post-mortem use into a FRESH registry; the live supervisor
    publishes its own verdicts as they happen (double-publishing both
    into one registry would double-count)."""
    registry.gauge("gang_skew_ratio").set(rollup.skew.get("last", 0.0))
    for v in rollup.stragglers:
        registry.counter("gang_straggler", rank=str(v["rank"])).inc()


# -- live sampling over the beat directory --------------------------------


@dataclasses.dataclass
class RankSample:
    """One rank's health at a sampling instant, from its heartbeat."""

    rank: int
    step: int
    age_s: float                   # progress age (see HeartbeatSampler)
    step_time_s: float | None      # published rolling mean, if any
    eff_step_time_s: float | None  # step_time_s, inflated by in-flight
    suspended: bool                # time when this rank holds the gang
    done: bool
    phases: dict


def read_beats(gang_dir: str | os.PathLike) -> dict[int, dict]:
    """rank -> latest heartbeat payload under ``gang_dir`` (torn writes
    and non-beat files skipped — the same tolerance every other gang
    reader applies)."""
    gang_dir = os.fspath(gang_dir)
    out: dict[int, dict] = {}
    try:
        names = os.listdir(gang_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(BEAT_PREFIX) and name.endswith(".json")):
            continue
        rank_s = name[len(BEAT_PREFIX):-len(".json")]
        if not rank_s.isdigit():
            continue
        try:
            with open(os.path.join(gang_dir, name)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue  # mid-replace torn read: next sample sees it whole
        if isinstance(payload, dict):
            out[int(rank_s)] = payload
    return out


def read_health_events(gang_dir: str | os.PathLike) -> list[dict]:
    """Every advisory event the supervisor recorded in the gang health
    ledger (straggler verdicts, restarts, shrinks), oldest first; a
    torn final line is dropped."""
    path = os.path.join(os.fspath(gang_dir), GANG_HEALTH_FILE)
    try:
        return [e for e in read_jsonl(path) if isinstance(e, dict)]
    except OSError:
        return []


class HeartbeatSampler:
    """Effective per-rank step times from the beat files, suitable for
    feeding :class:`StragglerDetector` live.

    Progress age uses the coordinator's own skew-free basis: staleness
    is *locally observed no-change time* — when did THIS sampler last
    see the rank's ``seq`` advance, on this host's monotonic clock —
    plus the ``beat_age`` the rank itself published.  Cross-host
    mtime/wall-clock comparison is never used (shared-mount skew of a
    minute is routine).

    Attribution rule: only ranks at the gang's MINIMUM published step
    have their in-flight time counted (``eff = max(rolling mean,
    progress age)``) — they are the ranks the lock-step barrier is
    actually waiting on.  Every rank ahead of the minimum is blocked on
    someone else, so its published rolling mean stands; without this
    rule one stalled rank starves the whole gang of progress and every
    rank's age grows in sympathy, which would push the median up and
    hide the true straggler.  Suspended ranks (checkpoint save, eval,
    compile) keep their rolling mean too: the coordinator already
    exempts declared non-step phases from progress judgement.
    """

    def __init__(self):
        # rank -> (last seen seq, monotonic time that seq first seen)
        self._seen: dict[int, tuple[int, float]] = {}

    def sample(self, gang_dir: str | os.PathLike | None,
               now: float | None = None,
               beats: dict[int, dict] | None = None
               ) -> dict[int, RankSample]:
        """``beats`` (ISSUE 12): pre-read payloads from a
        ``GangTransport`` snapshot — the supervisor samples through its
        transport's batched read instead of globbing beat files; the
        offline tools keep passing a directory."""
        if beats is None:
            beats = read_beats(gang_dir)
        now = time.monotonic() if now is None else now
        live_steps = [int(p.get("step", 0)) for p in beats.values()
                      if not p.get("done")]
        min_step = min(live_steps) if live_steps else None
        out: dict[int, RankSample] = {}
        for rank, p in sorted(beats.items()):
            seq = int(p.get("seq", 0))
            seen = self._seen.get(rank)
            if seen is None or seen[0] != seq:
                self._seen[rank] = (seq, now)
                staleness = 0.0
            else:
                staleness = now - seen[1]
            age = staleness + float(p.get("beat_age", 0.0))
            metrics = p.get("metrics")
            stime = None
            phases = {}
            modeled = False
            if isinstance(metrics, dict):
                st = metrics.get("step_time_s")
                if isinstance(st, (int, float)):
                    stime = float(st)
                if isinstance(metrics.get("phases"), dict):
                    phases = metrics["phases"]
                modeled = bool(metrics.get("modeled"))
            step = int(p.get("step", 0))
            done = bool(p.get("done"))
            suspended = bool(p.get("suspended"))
            if stime is None or done or suspended or modeled:
                # ``modeled`` (the digital twin): step times are
                # VIRTUAL seconds — inflating them by real-clock
                # progress age would mix clocks and flag every rank a
                # busy CI core descheduled.  Liveness still rides the
                # real heartbeat (peer timeout), so an actually-dead
                # rank is caught by the monitor, not this rule.
                eff = stime
            elif min_step is not None and step <= min_step:
                eff = max(stime, age)
            else:
                eff = stime
            out[rank] = RankSample(
                rank=rank, step=step, age_s=age, step_time_s=stime,
                eff_step_time_s=eff, suspended=suspended, done=done,
                phases=phases,
            )
        return out
