"""The real-data parity harness (cli/parity.py), smoke-tested on the
synthetic stand-in: the one-command runner must drive a part through
the reference protocol, parse its print surface, and emit the
side-by-side rows — so the harness is proven now and real numbers land
whenever a host with cifar-10-batches-py exists (VERDICT r02 item 6).
"""

import json

import pytest


def test_parity_harness_part1(tmp_path, capsys):
    from distributed_machine_learning_tpu.cli.parity import main

    out_json = tmp_path / "parity.json"
    main([
        "--parts", "part1", "--max-iters", "3", "--batch-size", "4",
        "--eval-batches", "1", "--eval-batch-size", "16",
        "--model", "vggtest", "--data-root", str(tmp_path),
        "--json", str(out_json),
    ])
    out = capsys.readouterr().out
    assert "part1" in out and "ref/ours" in out
    assert "synthetic" in out  # no dataset in this environment
    rows = json.loads(out_json.read_text())
    assert rows[0]["part"] == "part1"
    got = rows[0]["measured"]
    # The protocol surface parsed: times AND the part1 eval numbers.
    assert {"total_s", "avg_iter_s", "avg_test_loss", "accuracy_pct"} <= set(got)
    assert rows[0]["reference"]["avg_test_loss"] == 2.3031


def test_parity_harness_rejects_unknown_part(tmp_path):
    from distributed_machine_learning_tpu.cli.parity import (
        make_parser,
        run_parity,
    )

    args = make_parser().parse_args(
        ["--parts", "part9", "--data-root", str(tmp_path)]
    )
    with pytest.raises(ValueError, match="part9"):
        run_parity(args)


def test_equivalence_mode_checks_pass(capsys):
    """--equivalence machine-checks the report's p.5-6 argument: 2a==2b
    (bitwise-ish), SUM parts == part1 at world x LR, ring mean == part1
    (VERDICT r03 item 7).  Short run — the full 40-iter table runs in
    the slow/driver path."""
    from distributed_machine_learning_tpu.cli.parity import (
        make_parser,
        run_equivalence,
    )

    args = make_parser().parse_args(
        ["--equivalence", "--model", "vggtest", "--batch-size", "4",
         "--max-iters", "6"]
    )
    result = run_equivalence(args)
    assert result["ok"], result["checks"]
    assert result["checks"]["part2a==part2b"]["max_abs_dev"] <= 1e-5
    out = capsys.readouterr().out
    assert "PASS" in out and "FAIL" not in out


def test_equivalence_cli_exit_code():
    """main() returns cleanly on PASS (exit path is covered; the FAIL
    branch raises SystemExit(1) by construction)."""
    from distributed_machine_learning_tpu.cli.parity import main

    main(["--equivalence", "--model", "vggtest", "--batch-size", "4",
          "--max-iters", "4"])


def test_equivalence_refuses_vacuous_world():
    """A single-device 'equivalence check' is five identical runs that
    pass by construction — it must refuse, not certify."""
    import jax

    from distributed_machine_learning_tpu.cli.parity import (
        make_parser,
        run_equivalence,
    )

    args = make_parser().parse_args(["--equivalence", "--model", "vggtest"])
    with pytest.raises(ValueError, match="vacuous"):
        run_equivalence(args, devices=jax.devices()[:1])
