"""Numerical sanitizers — the SPMD answer to SURVEY.md §5's
"race detection / sanitizers" row.

Under jit+SPMD data races are structurally impossible (no shared mutable
state; collectives are the only cross-device edges), so the failure mode
that actually bites is *numerical*: a NaN/inf born in some fused kernel
surfaces dozens of ops later as a garbage loss.  Two tools:

- :func:`checked` — wrap any jittable fn with ``jax.experimental.checkify``
  float checks: every op that produces a NaN/±inf is annotated with its
  source location, and the wrapper raises at the first offender instead
  of propagating garbage.  Debug-only: the checks block fusion, so use it
  to localize, not to train.
- :func:`find_nonfinite` — scan a pytree (params, grads, activations)
  and report the path, count, and first index of every non-finite leaf —
  the fast post-mortem for a checkpoint or a captured gradient.

Example::

    step_dbg = checked(make_train_step(model, jit=False))
    state, loss = step_dbg(state, x, y)   # raises with op provenance

    bad = find_nonfinite(grads)
    # {'block_0/attn/qkv/kernel': 'nan x3 (first at (0, 1, 0, 7))'}
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import checkify


def checked(fn, *, jit: bool = True):
    """Wrap ``fn`` so any NaN/inf produced inside raises a
    ``checkify.JaxRuntimeError`` with the originating op's source line.

    ``fn`` must be jit-compatible (pure, traceable).  The returned
    wrapper has the same signature and return value.
    """
    checked_fn = checkify.checkify(fn, errors=checkify.float_checks)
    if jit:
        checked_fn = jax.jit(checked_fn)

    def wrapper(*args, **kwargs):
        err, out = checked_fn(*args, **kwargs)
        checkify.check_error(err)  # no-op if clean; raises with provenance
        return out

    return wrapper


def find_nonfinite(tree) -> dict[str, str]:
    """Report every non-finite leaf of a pytree.

    Returns ``{path: "nan x<count> (first at <index>)"}`` — empty dict
    means the tree is clean.  Pulls values to host; debug-only.
    """
    report: dict[str, str] = {}

    def visit(path, leaf):
        arr = np.asarray(jax.device_get(leaf))
        if not np.issubdtype(arr.dtype, np.floating):
            return
        bad = ~np.isfinite(arr)
        if bad.any():
            first = np.unravel_index(int(np.argmax(bad)), arr.shape)
            kinds = []
            if np.isnan(arr).any():
                kinds.append("nan")
            if np.isposinf(arr).any():
                kinds.append("+inf")
            if np.isneginf(arr).any():
                kinds.append("-inf")
            key = "/".join(
                str(getattr(k, "key", getattr(k, "idx", k))) for k in path
            )
            report[key] = (
                f"{'/'.join(kinds)} x{int(bad.sum())} (first at {first})"
            )

    jax.tree_util.tree_map_with_path(visit, tree)
    return report


def assert_all_finite(tree, what: str = "tree") -> None:
    """Raise ``ValueError`` with the full report if ``tree`` has any
    non-finite leaf (a pytree-wide ``torch.autograd.set_detect_anomaly``
    substitute for the post-hoc case)."""
    report = find_nonfinite(tree)
    if report:
        lines = "\n".join(f"  {k}: {v}" for k, v in sorted(report.items()))
        raise ValueError(f"non-finite values in {what}:\n{lines}")


def all_devices_identical(x) -> bool:
    """True iff every device's copy of a (supposedly) replicated array is
    bit-identical — the reference's cross-rank accuracy check
    (group25.pdf p.5, SURVEY.md §4) as a direct assertion on state."""
    arrs = [np.asarray(s.data) for s in x.addressable_shards]
    return all(np.array_equal(arrs[0], a, equal_nan=True) for a in arrs[1:])
