"""Round-13 fused-kernel A/B benches: ring codec and AdamW update.

Two interleaved A/B instruments (``bench/harness.py::interleaved_ab``
— one iteration of each config per round on the same batch stream, so
the 1-core host's sequential drift cancels the same way it does for
the round-11 selector A/B):

- **codec**: the part3 ring train step, int8 + error feedback, XLA
  codec vs the fused Pallas codec (``--ring-codec-impl``).  The two
  builds are BITWISE-identical in trajectory (the exact-product parity
  contract of ``ops/pallas/ring_codec.py``), so the final-loss column
  is an identity check, not a tolerance.
- **update**: the ZeRO-1 overlap train step (the build whose update
  program the round-9 spans put on the critical path) with AdamW,
  reference XLA update vs the fused one-pass kernel
  (``--fused-update``).

Honest-reporting note (the PERF.md round-13 protocol): on the 1-core
CPU CI host the kernels run under the Pallas INTERPRETER — a scan
over grid steps with functionalized state — so "fused" rows measure
interpreter overhead, not the in-register dataflow; the pod claim is
the kernels' dataflow (no dequantized partial / one-pass update in
HBM), exactly as PR 9's pp_gpipe rows claimed the overlap, not the
CPU numbers.  A TPU-backed run of this same file produces the
on-chip rows.

Run:  python -m distributed_machine_learning_tpu.bench.fused_kernels \\
          [--world 8] [--iters 40] [--model vggtest] [--json out]
"""

from __future__ import annotations

import argparse
import json


def bench_codec_ab(world: int = 8, iters: int = 40,
                   per_device_batch: int = 16,
                   model_name: str = "vggtest") -> list[dict]:
    """Interleaved A/B: int8+EF ring step, XLA codec vs fused Pallas
    codec.  Returns one row per config with p50/p95 and the final-loss
    identity column."""
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.bench.harness import (
        interleaved_ab,
    )
    from distributed_machine_learning_tpu.cli.common import (
        SEED,
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )
    from distributed_machine_learning_tpu.utils.timing import (
        percentile_stats,
    )

    mesh = make_mesh(world)
    model = get_model(model_name, use_bn=False)
    rng = np.random.default_rng(SEED)
    B = per_device_batch * world
    batches = [
        (rng.integers(0, 256, (B, 32, 32, 3), dtype=np.uint8),
         rng.integers(0, 10, B).astype(np.int32))
        for _ in range(4)
    ]
    configs = {
        "int8_xla": get_strategy("ring", compress="int8"),
        "int8_pallas": get_strategy("ring", compress="int8",
                                    codec_impl="pallas"),
    }
    steps, states, last_loss = {}, {}, {}
    for k, strat in configs.items():
        states[k] = init_model_and_state(
            model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
        )
        steps[k] = make_train_step(model, strat, mesh=mesh, augment=False)

    def one_iter(k):
        def run(rep):
            xs, ys = shard_batch(mesh, *batches[rep % len(batches)])
            states[k], loss = steps[k](states[k], xs, ys)
            last_loss[k] = float(jax.block_until_ready(loss))
        return run

    times = interleaved_ab({k: one_iter(k) for k in configs}, iters,
                           warmup=1)
    rows = []
    base_p50 = percentile_stats(times["int8_xla"])["p50"]
    for k, ts in times.items():
        stats = percentile_stats(ts)
        rows.append({
            "bench": "fused_codec_ab",
            "world": world,
            "config": k,
            "codec_impl": k.split("_", 1)[1],
            "iter_p50_s": stats["p50"],
            "iter_p95_s": stats["p95"],
            "p50_vs_xla": stats["p50"] / base_p50 - 1.0,
            "final_loss": last_loss[k],
            # The parity contract: identical trajectories, bit for bit.
            "loss_bitwise_equal": last_loss[k] == last_loss["int8_xla"],
        })
        print(json.dumps(rows[-1]))
    return rows


def bench_update_ab(world: int = 4, iters: int = 40,
                    per_device_batch: int = 16,
                    model_name: str = "vggtest") -> list[dict]:
    """Interleaved A/B: ZeRO-1 OVERLAP step with AdamW, reference
    update vs the fused one-pass kernel."""
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.bench.harness import (
        interleaved_ab,
    )
    from distributed_machine_learning_tpu.cli.common import (
        SEED,
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.zero1 import (
        make_zero1_train_step,
        shard_zero1_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig
    from distributed_machine_learning_tpu.train.step import shard_batch
    from distributed_machine_learning_tpu.utils.timing import (
        percentile_stats,
    )

    mesh = make_mesh(world)
    model = get_model(model_name, use_bn=False)
    rng = np.random.default_rng(SEED)
    B = per_device_batch * world
    batches = [
        (rng.integers(0, 256, (B, 32, 32, 3), dtype=np.uint8),
         rng.integers(0, 10, B).astype(np.int32))
        for _ in range(4)
    ]
    steps, states, last_loss = {}, {}, {}
    for k, fused in (("adamw_reference", False), ("adamw_fused", True)):
        st = init_model_and_state(model, config=AdamWConfig(fused=fused))
        z1, unravel, n_elems = shard_zero1_state(st, mesh)
        states[k] = z1
        steps[k] = make_zero1_train_step(model, mesh, unravel, n_elems,
                                         augment=False, overlap=True)

    def one_iter(k):
        def run(rep):
            xs, ys = shard_batch(mesh, *batches[rep % len(batches)])
            states[k], loss = steps[k](states[k], xs, ys)
            last_loss[k] = float(jax.block_until_ready(loss))
        return run

    times = interleaved_ab({k: one_iter(k) for k in steps}, iters,
                           warmup=1)
    rows = []
    base_p50 = percentile_stats(times["adamw_reference"])["p50"]
    for k, ts in times.items():
        stats = percentile_stats(ts)
        rows.append({
            "bench": "fused_update_ab",
            "world": world,
            "config": k,
            "fused": k == "adamw_fused",
            "iter_p50_s": stats["p50"],
            "iter_p95_s": stats["p95"],
            "p50_vs_reference": stats["p50"] / base_p50 - 1.0,
            "final_loss": last_loss[k],
            # Documented-ulp contract, NOT bitwise: report the delta.
            "final_loss_rel_delta_vs_reference": (
                abs(last_loss[k] - last_loss["adamw_reference"])
                / max(abs(last_loss["adamw_reference"]), 1e-30)
            ),
        })
        print(json.dumps(rows[-1]))
    return rows


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--world", default=8, type=int,
                        help="codec A/B world (the update A/B runs at "
                             "min(world, 4): zero1's compile cost on the "
                             "1-core host scales with world)")
    parser.add_argument("--iters", default=40, type=int)
    parser.add_argument("--batch-size", default=16, type=int,
                        help="PER-DEVICE batch")
    parser.add_argument("--model", default="vggtest")
    parser.add_argument("--json", dest="json_out", default=None)
    args = parser.parse_args(argv)
    rows = bench_codec_ab(world=args.world, iters=args.iters,
                          per_device_batch=args.batch_size,
                          model_name=args.model)
    rows += bench_update_ab(world=min(args.world, 4), iters=args.iters,
                            per_device_batch=args.batch_size,
                            model_name=args.model)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=2)
        print(f"wrote {args.json_out}")


if __name__ == "__main__":
    main()
