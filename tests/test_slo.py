"""SLO engine (ISSUE 17): spec parsing, multi-window burn-rate
alerting, and the end-of-run verdict ``cli/serve.py`` prints.

Every timestamp below is injected (``now=``) — the engine never reads a
clock in these tests, which is what makes the burn-rate assertions
deterministic (and is the DML001-compliant mode ``tools/serve_status.py``
replays dead runs in).  The keystone pair is
``test_stall_flips_alert_and_verdict`` /
``test_same_load_without_stall_passes``: identical synthetic load, one
with an injected stall window, one without — the acceptance proof that
the alert and the failing verdict are caused by the stall and nothing
else.
"""

import pytest

from distributed_machine_learning_tpu.telemetry.slo import (
    SLOEngine,
    SLOSpec,
    format_verdict,
    parse_slo,
)

# ---------------------------------------------------------------------------
# parse_slo
# ---------------------------------------------------------------------------


def test_parse_latency_objectives():
    spec = parse_slo("p99<=250ms")
    assert spec.kind == "latency"
    assert spec.threshold == pytest.approx(0.25)
    assert spec.budget == pytest.approx(0.01)

    assert parse_slo("p95<=0.1").threshold == pytest.approx(0.1)
    assert parse_slo("p95<=0.1").budget == pytest.approx(0.05)
    assert parse_slo("p50<=1s").threshold == pytest.approx(1.0)
    assert parse_slo("p99.9<=1s").budget == pytest.approx(0.001)
    assert parse_slo("p90<=500us").threshold == pytest.approx(5e-4)


def test_parse_ratio_objectives():
    spec = parse_slo("reject_ratio<=5%")
    assert spec.kind == "reject_ratio"
    assert spec.threshold == pytest.approx(0.05)
    assert spec.budget == pytest.approx(0.05)
    assert parse_slo("error_ratio<=0.01").budget == pytest.approx(0.01)


@pytest.mark.parametrize("bad", [
    "p99=250ms",          # no <=
    "p0<=1ms",            # quantile out of range
    "p100<=1ms",          # not a valid pNN
    "latency<=250ms",     # unknown objective
    "error_ratio<=1.5",   # ratio out of (0, 1)
    "reject_ratio<=0",    # ratio out of (0, 1)
    "p99<=-5ms",          # non-positive bound
])
def test_parse_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        parse_slo(bad)


def test_engine_accepts_specs_and_strings():
    engine = SLOEngine([parse_slo("p99<=250ms"), "error_ratio<=1%"])
    assert [o.kind for o in engine.objectives] == ["latency",
                                                   "error_ratio"]
    assert all(isinstance(o, SLOSpec) for o in engine.objectives)


def test_engine_validates_windows_and_threshold():
    with pytest.raises(ValueError):
        SLOEngine(["p99<=1s"], short_window_s=10.0, long_window_s=5.0)
    with pytest.raises(ValueError):
        SLOEngine(["p99<=1s"], short_window_s=0.0)
    with pytest.raises(ValueError):
        SLOEngine(["p99<=1s"], burn_threshold=0.0)


# ---------------------------------------------------------------------------
# Burn-rate alerting — the acceptance pair
# ---------------------------------------------------------------------------

def _run_load(engine, *, stall=None, n=400, dt=0.25, good_s=0.02,
              stall_s=2.0):
    """n requests, one every ``dt`` seconds of injected time; requests
    inside the ``stall`` interval (t0, t1) take ``stall_s`` instead of
    ``good_s``.  Returns all alerts fired during the run."""
    fired = []
    for i in range(n):
        t = i * dt
        lat = good_s
        if stall is not None and stall[0] <= t < stall[1]:
            lat = stall_s
        fired.extend(engine.observe(latency_s=lat, now=t))
    return fired


def test_stall_flips_alert_and_verdict():
    engine = SLOEngine(["p99<=250ms"], short_window_s=5.0,
                       long_window_s=60.0, burn_threshold=2.0)
    fired = _run_load(engine, stall=(40.0, 55.0))
    assert fired, "sustained stall did not fire a burn-rate alert"
    alert = fired[0]
    assert alert["slo"] == "p99<=250ms"
    assert 40.0 <= alert["at"] <= 60.0
    assert alert["short_burn"] > 2.0 and alert["long_burn"] > 2.0
    verdict = engine.verdict()
    assert verdict["ok"] is False
    (row,) = verdict["objectives"]
    assert row["ok"] is False and row["alerts"] >= 1
    assert "FAIL" in format_verdict(verdict)


def test_same_load_without_stall_passes():
    engine = SLOEngine(["p99<=250ms"], short_window_s=5.0,
                       long_window_s=60.0, burn_threshold=2.0)
    fired = _run_load(engine, stall=None)
    assert fired == []
    verdict = engine.verdict()
    assert verdict["ok"] is True
    (row,) = verdict["objectives"]
    assert row["bad"] == 0 and row["relevant"] == 400
    assert "slo verdict: PASS" in format_verdict(verdict)


def test_quiet_tail_does_not_erase_a_mid_run_alert():
    """The documented semantics: a sustained mid-run breach fails the
    run even when a long good tail pulls the whole-run bad fraction
    back under budget."""
    engine = SLOEngine(["p95<=250ms"], short_window_s=5.0,
                       long_window_s=60.0, burn_threshold=2.0)
    _run_load(engine, stall=(40.0, 50.0), n=4000)
    assert engine.alerts
    verdict = engine.verdict()
    (row,) = verdict["objectives"]
    assert row["bad_ratio"] <= row["budget"], "tail should dilute ratio"
    assert verdict["ok"] is False, "alert must still fail the verdict"


def test_short_burst_does_not_page():
    """The multi-window rule's whole point: a burst that is over before
    the long window burns never alerts — the short window alone is not
    evidence of a sustained problem."""
    engine = SLOEngine(["error_ratio<=5%"], short_window_s=5.0,
                       long_window_s=60.0, burn_threshold=2.0)
    for i in range(120):                       # 60 s of good history
        engine.observe(latency_s=0.01, now=i * 0.5)
    fired = []
    for j in range(2):                         # 2-outcome burst
        fired.extend(engine.observe(latency_s=0.01, error=True,
                                    now=60.0 + j * 0.1))
    assert fired == [], "ended burst paged despite a cold long window"
    # ...but the SAME failure rate sustained does alert.
    for j in range(40):
        fired.extend(engine.observe(latency_s=0.01, error=True,
                                    now=61.0 + j * 0.5))
    assert fired, "sustained failures never alerted"


def test_recovery_rearms_the_alert_episode():
    engine = SLOEngine(["error_ratio<=10%"], short_window_s=5.0,
                       long_window_s=20.0, burn_threshold=2.0)

    def episode(t0):
        out = []
        for j in range(20):
            out.extend(engine.observe(error=True, now=t0 + j * 0.5))
        return out

    def recover(t0):
        out = []
        for j in range(60):
            out.extend(engine.observe(error=False, now=t0 + j * 0.5))
        return out

    first = episode(0.0)
    assert len(first) == 1, "episode must alert exactly once"
    assert episode(10.0) == [], "same episode must not re-alert"
    recover(20.0)
    second = episode(60.0)
    assert len(second) == 1, "recovery must re-arm the alert"
    assert len(engine.alerts) == 2


# ---------------------------------------------------------------------------
# Outcome-kind relevance
# ---------------------------------------------------------------------------

def test_rejections_are_invisible_to_latency_objectives():
    engine = SLOEngine(["p99<=250ms", "reject_ratio<=10%"],
                       short_window_s=5.0, long_window_s=20.0,
                       burn_threshold=2.0)
    for i in range(50):
        engine.observe(rejected=True, now=i * 0.1)
    verdict = engine.verdict()
    by_slo = {r["slo"]: r for r in verdict["objectives"]}
    assert by_slo["p99<=250ms"]["relevant"] == 0
    assert by_slo["p99<=250ms"]["ok"] is True       # no evidence
    assert by_slo["reject_ratio<=10%"]["relevant"] == 50
    assert by_slo["reject_ratio<=10%"]["ok"] is False
    assert any(a["slo"] == "reject_ratio<=10%" for a in engine.alerts)


def test_errors_count_against_error_ratio_not_rejects():
    engine = SLOEngine(["error_ratio<=50%", "reject_ratio<=50%"],
                       short_window_s=5.0, long_window_s=20.0,
                       burn_threshold=2.0)
    engine.observe(latency_s=0.01, error=True, now=0.0)
    engine.observe(rejected=True, now=0.1)
    engine.observe(latency_s=0.01, now=0.2)
    by_slo = {r["slo"]: r for r in engine.verdict()["objectives"]}
    # error_ratio judges admitted requests only: 1 error of 2 admitted.
    assert by_slo["error_ratio<=50%"]["relevant"] == 2
    assert by_slo["error_ratio<=50%"]["bad"] == 1
    # reject_ratio judges every admission attempt: 1 reject of 3.
    assert by_slo["reject_ratio<=50%"]["relevant"] == 3
    assert by_slo["reject_ratio<=50%"]["bad"] == 1


def test_format_verdict_names_every_objective():
    engine = SLOEngine(["p99<=250ms", "error_ratio<=1%"])
    engine.observe(latency_s=0.01, now=0.0)
    text = format_verdict(engine.verdict())
    assert "slo p99<=250ms: PASS" in text
    assert "slo error_ratio<=1%: PASS" in text
    assert text.endswith("(0 alert(s) fired)")
