# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/checkpoint.py
"""DML007 clean case: None-default construction, deterministic manifest
payload (content digests only — every rank writes identical bytes)."""


def gather_leaves(tree, out=None):
    out = [] if out is None else out
    out.append(tree)
    return out


def build_manifest(leaves, digests):
    return {"leaves": leaves, "digests": digests}
