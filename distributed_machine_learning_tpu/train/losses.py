"""Loss and metric functions.

The reference uses ``torch.nn.CrossEntropyLoss()`` with default mean
reduction (``part1/main.py:115``) for both training and eval, and top-1
accuracy via argmax (``part1/main.py:71-72``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over all leading axes (CrossEntropyLoss
    parity; handles [B, C] classification and [B, L, C] token logits)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)
    return nll.mean()


def count_correct(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 correct-prediction count (part1/main.py:71-72)."""
    return (logits.argmax(axis=-1) == labels).sum()


def lm_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy over [B, L] targets.

    Caller supplies already-shifted targets (under sequence sharding the
    shift crosses chunk boundaries, so shifting belongs to the host data
    pipeline, not the sharded step).  Equal chunk sizes make the global
    mean equal the pmean of local means.
    """
    return cross_entropy_loss(logits, targets)
