"""Finding + baseline machinery shared by both dmlcheck layers.

Stdlib-only by construction (the Layer-1 fast path must never import
jax).  A finding is one rule violation at one source location; the
baseline is the checked-in list of JUSTIFIED suppressions
(``dmlcheck_baseline.json``) — the escape hatch for sites where the
flagged idiom is deliberate (e.g. the reference measurement protocol's
``block_until_ready`` in the train loop).

Baseline matching is line-number-free on purpose: an entry matches on
``(rule, file, match-substring-of-the-flagged-source-line)``, so edits
above a suppressed site don't churn the baseline.  Every entry MUST
carry a non-empty ``justification`` — a suppression nobody can defend
is a bug report, not a baseline entry — and unused entries are surfaced
so the baseline can only shrink as findings get fixed.
"""

from __future__ import annotations

import dataclasses
import json
import os


class BaselineError(ValueError):
    """The baseline file is malformed or carries unjustified entries."""


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str              # "DML004" (layer 1) / "DML102" (layer 2)
    file: str              # repo-relative posix path (or an audit label)
    line: int              # 1-based; 0 for whole-program audits
    message: str           # what is wrong and why it matters
    snippet: str = ""      # the flagged source line, stripped
    severity: str = "error"   # "error" | "advisory"
    layer: int = 1

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def location(self) -> str:
        return f"{self.file}:{self.line}" if self.line else self.file


def load_baseline(path: str | os.PathLike) -> list[dict]:
    """Load + validate ``dmlcheck_baseline.json``; [] when absent.

    Raises :class:`BaselineError` on malformed entries or a missing /
    empty ``justification`` — an unjustified suppression must fail the
    run louder than the finding it hides.
    """
    try:
        with open(os.fspath(path)) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return []
    except json.JSONDecodeError as e:
        raise BaselineError(f"baseline {path}: invalid JSON ({e})") from e
    entries = payload.get("suppressions", payload) if isinstance(
        payload, dict) else payload
    if not isinstance(entries, list):
        raise BaselineError(
            f"baseline {path}: expected a list (or {{'suppressions': "
            f"[...]}}), got {type(entries).__name__}")
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise BaselineError(f"baseline {path}: entry {i} is not a dict")
        for key in ("rule", "file", "match"):
            if not isinstance(e.get(key), str) or not e[key]:
                raise BaselineError(
                    f"baseline {path}: entry {i} needs a non-empty "
                    f"{key!r} string")
        just = e.get("justification")
        if not isinstance(just, str) or len(just.strip()) < 10:
            raise BaselineError(
                f"baseline {path}: entry {i} ({e['rule']} {e['file']}) "
                "has no written justification — every suppression must "
                "say WHY the flagged idiom is deliberate")
    return entries


def _entry_matches(entry: dict, f: Finding) -> bool:
    return (entry["rule"] == f.rule
            and entry["file"] == f.file
            and entry["match"] in (f.snippet or f.message))


def apply_baseline(
    findings: list[Finding], baseline: list[dict],
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """Split ``findings`` against the baseline.

    Returns ``(new, suppressed, unused_entries)``: findings no entry
    matches, findings an entry matches, and entries that matched
    nothing (stale — the violation was fixed; drop the entry).
    """
    new: list[Finding] = []
    suppressed: list[Finding] = []
    used = [False] * len(baseline)
    for f in findings:
        hit = False
        for i, entry in enumerate(baseline):
            if _entry_matches(entry, f):
                used[i] = True
                hit = True
        (suppressed if hit else new).append(f)
    unused = [e for e, u in zip(baseline, used) if not u]
    return new, suppressed, unused


def findings_to_json(
    new: list[Finding], suppressed: list[Finding],
    unused_baseline: list[dict], *, rules_run: list[str] | None = None,
) -> dict:
    """The machine-readable verdict (``tools/dmlcheck.py --json``) —
    same shape philosophy as ``ckpt_verify --json``: one top-level dict
    with the per-item records plus the counts a CI gate keys on."""
    return {
        "findings": [f.as_dict() for f in new],
        "suppressed": [f.as_dict() for f in suppressed],
        "baseline_unused": unused_baseline,
        "total": len(new) + len(suppressed),
        "new": len(new),
        "clean": not new and not unused_baseline,
        **({"rules_run": rules_run} if rules_run is not None else {}),
    }
