"""Model summary banner — parameter table + totals.

The reference prints a torchsummary table for part1 (``part1/main.py:118``)
whose ~9.2M-parameter total the report leans on (group25.pdf p.2).  This
is the pytree-native equivalent: per-module parameter counts from the
params tree itself, plus the totals line.
"""

from __future__ import annotations

import numpy as np


def _count(tree) -> int:
    import jax

    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


def model_summary(params, title: str = "Model") -> str:
    """A torchsummary-style table: one row per top-level module with its
    parameter shapes and count, then total params and fp32 size in MB."""
    import jax

    rows = []
    width = 24
    for name in sorted(params):
        sub = params[name]
        shapes = " ".join(
            "x".join(str(d) for d in leaf.shape) or "scalar"
            for leaf in jax.tree_util.tree_leaves(sub)
        )
        rows.append(f"  {name:<{width}} {_count(sub):>12,}  [{shapes}]")
    total = _count(params)
    lines = [
        f"{title} summary",
        "-" * 64,
        *rows,
        "-" * 64,
        f"  {'Total params':<{width}} {total:>12,}",
        f"  {'Size (fp32)':<{width}} {total * 4 / 2**20:>10.2f} MB",
        "-" * 64,
    ]
    return "\n".join(lines)
