"""Weight-only int8 serving: kernel parity, converter structure, and
token-exact generation vs the dequantized reference (ops/quant.py,
ops/pallas/quant_matmul.py — interpret mode on the CPU harness)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.inference.generate import (
    generate,
    make_generate_fn,
)
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.ops.pallas.quant_matmul import (
    int8_matmul,
    quantize_int8,
)
from distributed_machine_learning_tpu.ops.quant import quantize_lm_params


def test_quantize_int8_roundtrip_error_bound():
    w = jnp.asarray(
        np.random.default_rng(0).standard_normal((64, 96)), jnp.float32
    ) * 0.02
    q, s = quantize_int8(w)
    assert q.dtype == jnp.int8 and s.shape == (96,)
    back = q.astype(jnp.float32) * s[None, :]
    # Symmetric 8-bit: error <= scale/2 per element, elementwise.
    assert float(jnp.abs(back - w).max()) <= float(s.max()) / 2 + 1e-8
    # All-zero columns quantize cleanly (scale 1, values 0).
    q0, s0 = quantize_int8(jnp.zeros((8, 4)))
    assert float(jnp.abs(q0).max()) == 0 and float(s0.min()) == 1.0


def test_int8_matmul_matches_dequant_reference():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((24, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 256)), jnp.float32) * 0.05
    q, s = quantize_int8(w)
    ref = x.astype(jnp.bfloat16) @ (
        q.astype(jnp.bfloat16) * s[None, :].astype(jnp.bfloat16)
    )
    out = int8_matmul(x, q, s)
    assert out.dtype == x.dtype
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_int8_matmul_pads_awkward_row_counts():
    """An odd prefill row count (> 8, no multiple-of-8 divisor) is
    zero-padded to tile rather than falling back to one whole-array
    tile (the VMEM blowup the caps exist to prevent)."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((13, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32) * 0.05
    q, s = quantize_int8(w)
    out = int8_matmul(x, q, s)
    assert out.shape == (13, 128)
    ref = x.astype(jnp.bfloat16) @ (
        q.astype(jnp.bfloat16) * s[None, :].astype(jnp.bfloat16)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_int8_matmul_shape_guards():
    with pytest.raises(ValueError, match="shape mismatch"):
        int8_matmul(jnp.ones((8, 32)), *quantize_int8(jnp.ones((64, 128))))
    # An explicit block_k that does not tile still refuses loudly (the
    # auto path pads instead — test_int8_matmul_pads_awkward_widths).
    q, s = quantize_int8(jnp.ones((64, 1000)))
    with pytest.raises(ValueError, match="tile"):
        int8_matmul(jnp.ones((8, 64)), q, s, block_k=384)


def _dequant_tree(params, qparams):
    """Quantized tree → kernel-shaped full-precision tree (the reference
    a correct int8 path must reproduce through the kernel)."""

    def walk(ref, node):
        if isinstance(ref, dict):
            if "w_q" in node:
                w = node["w_q"].astype(jnp.float32) * node["scale"][None, :]
                out = {"kernel": w.reshape(ref["kernel"].shape)}
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            return {k: walk(ref[k], node[k]) for k in ref}
        return node

    return walk(params, qparams)


@pytest.mark.parametrize("kv", [None, 2], ids=["mha", "gqa"])
def test_quantized_generate_token_exact_vs_dequant(kv):
    model = TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=kv
    )
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    qparams = quantize_lm_params(params)
    # Converter structure: every projection quantized, embed untouched.
    blk = qparams["block_0"]["attn"]
    assert ("qkv" if kv is None else "q") in blk
    for leaf in jax.tree_util.tree_leaves(
        blk[("qkv" if kv is None else "q")]["w_q"]
    ):
        assert leaf.dtype == jnp.int8
    assert "embedding" in qparams["embed"]

    prompt = np.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32)
    ref = generate(model, _dequant_tree(params, qparams), prompt, 12)
    fn = make_generate_fn(model, 12, quantize="int8")
    out = fn(qparams, jnp.asarray(prompt), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_weight_quant_requires_decode():
    model = TransformerLM(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, weight_quant="int8"
    )
    with pytest.raises(ValueError, match="decode"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="int8"):
        make_generate_fn(
            TransformerLM(vocab_size=64, d_model=32, n_layers=1, n_heads=4),
            4,
            quantize="int4",
        )


def test_tp_int8_decode_token_exact(rng):
    """--quant int8 composes with TP (VERDICT r03 item 5): the tp=2
    head-sharded int8 decode (permuted fused w_q column blocks, sharded
    scales, pre-divided row-parallel biases) generates the same greedy
    tokens as single-device int8 decode — both read the SAME quantized
    values, so any layout slip would show immediately."""
    from distributed_machine_learning_tpu.inference.generate import (
        generate,
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    mesh = make_mesh(2, axis_names=("model",))
    prompt = jnp.asarray(rng.integers(0, 32, (2, 4)), jnp.int32)
    for n_kv in (None, 2):  # fused-qkv MHA and GQA layouts
        model = TransformerLM(
            vocab_size=32, d_model=32, n_layers=2, n_heads=4,
            n_kv_heads=n_kv,
        )
        params = init_lm_state(model).params
        qparams = quantize_lm_params(params)
        ref = generate(model, params, prompt, max_new_tokens=6,
                       quantize="int8")
        fn = make_tp_generate_fn(model, 6, mesh, quantize="int8")
        out = fn(tp_decode_params(qparams, 2), prompt,
                 jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_int8_matmul_pads_awkward_widths():
    """K with no 128-multiple divisor under the cap (e.g. 960 from a
    d_model=320 fused qkv) zero-pads to the next 128 multiple and
    slices back instead of raising (ADVICE r03)."""
    from distributed_machine_learning_tpu.ops.pallas.quant_matmul import (
        int8_matmul,
        quantize_int8,
    )

    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.standard_normal((64, 960)), jnp.float32) * 0.05
    x = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
    q, scale = quantize_int8(w)
    out = int8_matmul(x, q, scale)
    assert out.shape == (8, 960)
    ref = x.astype(jnp.bfloat16) @ (
        q.astype(jnp.bfloat16) * scale[None, :].astype(jnp.bfloat16)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_quantize_lm_params_rejects_misshaped_out_module():
    """The name-keyed two-axis flatten validates the kernel rank it
    assumes (ADVICE r03): a rank-2 kernel under a module named 'out'
    raises instead of silently mis-flattening."""
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params

    bad = {"blk": {"out": {"kernel": jnp.zeros((8, 4)),
                           "bias": jnp.zeros((4,))}}}
    with pytest.raises(ValueError, match="rank"):
        quantize_lm_params(bad)


def test_tp_decode_with_int8_kv_cache_token_exact(rng):
    """TP decode composes with the int8 KV cache: per-(head, slot)
    quantization is local to each device's cache shard, so the tp=2
    run matches single-device int8-KV decode token-for-token."""
    from distributed_machine_learning_tpu.inference.generate import (
        generate,
        make_tp_generate_fn,
    )
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        tp_decode_params,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    mesh = make_mesh(2, axis_names=("model",))
    model = TransformerLM(
        vocab_size=32, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        kv_cache_dtype=jnp.int8,
    )
    params = init_lm_state(model).params
    prompt = jnp.asarray(rng.integers(0, 32, (2, 4)), jnp.int32)
    ref = generate(model, params, prompt, max_new_tokens=6)
    fn = make_tp_generate_fn(model, 6, mesh)
    out = fn(tp_decode_params(params, 2), prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_int8_tiered_dispatch_token_exact(rng):
    """The gated two-tier int8-cache dispatch (bench/int8_tier.py;
    models/transformer.py::_INT8_TIERED_DISPATCH) must be semantics-
    neutral: same greedy stream as the default einsum-only dispatch,
    with the generation crossing the break-even so BOTH branches run.

    Exact token equality is a property of THIS suite's platform (CPU,
    interpret-mode kernel, f32 softmax in both paths); the kernel-vs-
    einsum ulp differences that could flip a near-tied argmax on other
    backends are the same shape-dependent ties the speculative
    docstring documents — if this ever flakes off-CPU, compare
    prefix-agreement rates instead of pinning bitwise."""
    import distributed_machine_learning_tpu.models.transformer as tmod
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    model = TransformerLM(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
        kv_cache_dtype=jnp.int8,
    )
    params = init_lm_state(model).params
    prompt = jnp.asarray(rng.integers(0, 64, (1, 6)), jnp.int32)
    # 320 new tokens in a 512-slot cache: pos/S runs 0..0.64, crossing
    # the 0.36 break-even — the lax.cond takes the kernel branch early
    # and the einsum branch late.
    ref = make_generate_fn(model, 320)(params, prompt, jax.random.PRNGKey(0))
    tmod._INT8_TIERED_DISPATCH = True
    try:
        out = make_generate_fn(model, 320)(
            params, prompt, jax.random.PRNGKey(0)
        )
    finally:
        tmod._INT8_TIERED_DISPATCH = False
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
