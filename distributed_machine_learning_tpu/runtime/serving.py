"""Elastic serving fleet — the router side (ISSUE 16).

Five PRs of gang machinery (heartbeats + health ledger, warm spares,
straggler detection, the pluggable transport with op-id exactly-once,
the layer-3 race detector) served *training only*.  This module
re-aims that control plane at a replicated inference tier:

- :class:`ServingRouter` owns a **bounded request queue with admission
  control**: past ``max_queue`` open requests, :meth:`submit` raises
  :class:`Overloaded` — an explicit, counted rejection, never a silent
  drop.
- Admitted requests are dispatched in **micro-batches** to N live
  replica ranks (each a ``runtime/serving_worker.py`` loop driving the
  batch-static ``inference/generate.py`` decode step through the
  step-callable seam).
- Replica lifecycle reuses the gang primitives: **liveness** from the
  beat channel (change-signatures + the router's monotonic clock —
  never cross-host wall time, DML001); **eviction of slow replicas**
  via the PR 6 :class:`StragglerDetector` fed per-replica service
  times, with ``--straggler-policy=replace`` semantics (demote, then
  promote a warm spare in its place); **elastic grow** under sustained
  queue pressure by promoting spares that announced on the join
  channel with prefetched verified checkpoints (promotion is
  O(restore), PR 10); **graceful drain** for redeploy — stop
  dispatching, finish in-flight, then demote to spare.
- The drain/demote handoff is **epoch-fenced** at the transport
  (``retire_replica`` bumps the replica's serving epoch atomically
  with reclaiming its queue; a late ``post_result`` from the old epoch
  is discarded at the hub) — the protocol dmlcheck layer 3 explores as
  ``drain_promote``.  On top of the fence the router keeps a request
  ledger with **first-result-wins** per ``rid``: a request completed
  by a dying replica *and* re-dispatched to a survivor delivers
  exactly once, with the duplicate counted, never returned.

Telemetry: per-request latency lands in a ``serving_request_latency_s``
histogram built on the ISSUE 16 latency bucket preset
(``default_latency_buckets`` — the train-step buckets flattened
millisecond p99s into one bucket); fleet gauges ``serving_replicas`` /
``serving_queue_depth`` and counters ``serving_evictions`` /
``serving_rejects`` flow through the same registry, mirrored into
``FaultEvents.replica_evictions`` / ``drains`` / ``request_rejects``
for the ``resilience_summary`` rows.  Lifecycle edges append
``serve_promote`` / ``serve_evict`` / ``serve_demote`` health-ledger
events and :meth:`close` appends a final ``serving`` summary record —
what ``tools/gang_status.py`` renders as the serving view.

Request-scoped tracing (ISSUE 17): every admitted request carries a
stage-event record (see ``runtime/transport.py::SERVING_STAGES``) the
router opens at admission and closes at completion; worker-side stamps
merge back in with the posted result.  At completion the rank-local
stage deltas feed ``serving_stage_latency_s{stage=...}`` histograms,
the shared :class:`StragglerDetector` (via
``telemetry.aggregator.serving_stage_samples`` — the ``computed``
deltas ARE the per-replica service times, replacing the old beat-borne
copy), an optional :class:`~..telemetry.slo.SLOEngine`, and — when
``record_requests`` is on — a ``serve_request`` health-ledger record
``tools/serve_status.py --postmortem RID`` reconstructs timelines
from.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

from distributed_machine_learning_tpu.runtime.faults import FaultEvents
from distributed_machine_learning_tpu.runtime.transport import (
    stamp_stage,
)
from distributed_machine_learning_tpu.telemetry import get_telemetry
from distributed_machine_learning_tpu.telemetry.aggregator import (
    StragglerDetector,
    serving_stage_samples,
)
from distributed_machine_learning_tpu.telemetry.registry import (
    Histogram,
    default_latency_buckets,
)

# Stages whose rank-local deltas are observed into the per-stage
# latency histograms at completion.  On the happy path that is the
# full journey decomposition: ``queued`` (admission → queue append,
# router clock), ``dispatched`` (queue wait, router clock), ``bound``
# (fence check after take, replica clock), ``computed`` (the compute
# interval, replica clock), ``posted`` (result append, replica clock),
# ``completed`` (dispatch → collection round trip, router clock).
# ``requeued`` rides along on the failure path — the time a request
# sat on a replica that died under it.  ``admitted``/``taken`` open
# each actor's local chain (dt is None by construction: the prior
# stamp crossed a process boundary) and ``fenced``/``dropped`` record
# discards, so none of those carry durations.  ``prefill``/``decode``
# are the continuous-batching engine's split of the compute interval
# (ISSUE 19): engine replicas stamp those two instead of ``computed``,
# so per-request prefill/decode latency quantiles fall out of the same
# serving_stage_latency_s family.
_HISTOGRAM_STAGES = frozenset(
    {"queued", "dispatched", "bound", "prefill", "decode", "computed",
     "posted", "completed", "requeued"})


class Overloaded(RuntimeError):
    """Admission control rejected the request: the bounded queue is at
    capacity.  Explicit back-pressure the caller can act on (shed,
    retry with backoff) — the router never silently drops an admitted
    request, so it must never silently absorb an unadmittable one."""


@dataclasses.dataclass
class ServingConfig:
    """Router policy knobs.  Defaults suit the in-proc chaos campaigns;
    ``cli/serve.py`` maps its flags onto these."""

    replicas: int = 2           # target live replicas (heal up to this)
    max_replicas: int | None = None  # pressure-grow ceiling (None: +spares)
    max_queue: int = 64         # admission bound on OPEN requests
    micro_batch: int = 4        # requests per dispatch to one replica
    max_outstanding: int = 8    # per-replica in-flight cap (backpressure)
    poll_s: float = 0.005       # run() pump cadence
    replica_timeout_s: float = 2.0   # beat-staleness eviction threshold
    straggler_multiple: float = 4.0  # PR 6 detector: x median
    straggler_consecutive: int = 3
    grow_watermark: float = 0.75     # queue fraction that counts as pressure
    grow_patience: int = 5           # consecutive pressured pumps to grow
    retain_done: int = 1024          # completed entries kept in the ledger
    record_requests: bool = True     # serve_request ledger records (ISSUE 17)


@dataclasses.dataclass
class _Replica:
    """Router-side record of one live replica."""

    epoch: int
    sig: object = None            # last beat change-signature seen
    sig_mono: float = 0.0         # router monotonic time sig last changed
    in_flight: set = dataclasses.field(default_factory=set)  # rids
    served: int = 0
    service_s: float | None = None  # last reported micro-batch service time
    draining: bool = False
    wv: int = 0                   # weights version the router believes


class ServingRouter:
    """The fleet control plane: admission, dispatch, collection,
    liveness, eviction, promotion, drain.  Thread-safe: ``submit`` may
    be called from any number of client threads while one owner drives
    :meth:`pump` (or :meth:`run` on its own thread)."""

    def __init__(self, transport, config: ServingConfig | None = None,
                 events: FaultEvents | None = None, *,
                 telemetry=None, slo=None, scheduler=None):
        self.tx = transport
        self.cfg = config or ServingConfig()
        self.events = events if events is not None else FaultEvents()
        self.slo = slo  # an SLOEngine fed one observe() per outcome
        # Regime-aware dispatch (ISSUE 19): a RegimeScheduler observed
        # once per pump with the FLEET-wide load (queue depth + total
        # in-flight).  The chosen lever is stamped onto every dispatched
        # request so each replica's engine follows one coherent regime
        # instead of N local views drifting at the boundary.
        self.scheduler = scheduler
        self._lock = threading.RLock()
        self._queue: collections.deque[str] = collections.deque()
        self._ledger: dict[str, dict] = {}
        # The ledger holds at most ``retain_done`` completed entries: a
        # long-running router would otherwise retain every prompt +
        # result forever.  Compacted entries survive as counters plus a
        # bounded rid tombstone set (so a dead replica's very late
        # duplicate still classifies as a duplicate, not "unknown").
        self._done_fifo: collections.deque[str] = collections.deque()
        self._compacted = 0
        self._tombstones: collections.OrderedDict[str, None] = \
            collections.OrderedDict()
        self._tombstone_cap = max(1024, 4 * self.cfg.retain_done)
        self._replicas: dict[int, _Replica] = {}
        self._rid_seq = 0
        self._open = 0            # admitted, not yet completed
        self._closed = False
        self._pressure = 0
        self.rejected = 0
        self.completed = 0
        self.duplicates_discarded = 0
        self.unknown_results = 0
        self.redispatches = 0
        self.promotions = 0
        self.evictions = 0
        self.drains_done = 0
        self._ever_evicted: set[int] = set()
        # Continuous deployment (ISSUE 18): the deploy controller tells
        # the router which ranks carry canary weights and what slice of
        # traffic to steer at them (deterministic, counter-based — no
        # randomness, so campaigns replay).  ``on_complete`` is the
        # controller's per-outcome feed: called outside the lock with
        # {rid, replica, wv, version, latency_s, prompt, output} so the
        # canary judgement sees every completion's weights version.
        self._canary: set[int] = set()
        self._canary_every = 0
        self._canary_seq = 0
        self.on_complete = None
        self._detector = StragglerDetector(
            multiple=self.cfg.straggler_multiple,
            consecutive=self.cfg.straggler_consecutive,
            min_ranks=2,
        )
        # The latency histogram exists even with no telemetry sink
        # configured (quantiles feed the SLO assertions directly); with
        # a sink it is the registry's own instrument, so it streams.
        # An explicit ``telemetry=`` beats the process-wide install —
        # the router may be one of several instances sharing a process
        # (in-proc fleets), each with its own instance-tagged artifacts.
        tel = telemetry if telemetry is not None else get_telemetry()
        self._tel = tel
        self._stage_hist: dict[str, Histogram] = {}
        if tel is not None:
            self.latency = tel.registry.histogram(
                "serving_request_latency_s",
                buckets=default_latency_buckets())
            self._g_replicas = tel.registry.gauge("serving_replicas")
            self._g_depth = tel.registry.gauge("serving_queue_depth")
            self._g_inflight = tel.registry.gauge("serving_inflight")
            self._c_evict = tel.registry.counter("serving_evictions")
            self._c_reject = tel.registry.counter("serving_rejects")
        else:
            self.latency = Histogram(
                "serving_request_latency_s", (),
                buckets=default_latency_buckets())
            self._g_replicas = self._g_depth = self._g_inflight = None
            self._c_evict = self._c_reject = None

    def _stage_latency(self, stage: str) -> Histogram:
        """Get-or-create the ``serving_stage_latency_s{stage=...}``
        histogram — a registry instrument when telemetry is on (it
        streams into registry.json), a local one otherwise (quantiles
        still feed audits and tests)."""
        h = self._stage_hist.get(stage)
        if h is None:
            if self._tel is not None:
                h = self._tel.registry.histogram(
                    "serving_stage_latency_s",
                    buckets=default_latency_buckets(), stage=stage)
            else:
                h = Histogram("serving_stage_latency_s",
                              (("stage", stage),),
                              buckets=default_latency_buckets())
            self._stage_hist[stage] = h
        return h

    # -- admission -------------------------------------------------------
    def submit(self, prompt, rid: str | None = None) -> str:
        """Admit one request (or raise :class:`Overloaded`).  Returns
        the request id; poll :meth:`result` or :meth:`wait_idle` for
        completion."""
        with self._lock:
            if self._closed:
                raise Overloaded("router is closed to new requests")
            if self._open >= self.cfg.max_queue:
                self.rejected += 1
                self.events.request_rejects += 1
                if self._c_reject is not None:
                    self._c_reject.inc()
                if self.slo is not None:
                    self.slo.observe(rejected=True)
                raise Overloaded(
                    f"queue full ({self._open}/{self.cfg.max_queue} "
                    "open requests)")
            if rid is None:
                self._rid_seq += 1
                rid = f"r{self._rid_seq}"
            if rid in self._ledger:
                raise ValueError(f"duplicate rid {rid!r}")
            entry = {
                "rid": rid, "prompt": prompt, "state": "queued",
                "replica": None, "epoch": None, "wv": None,
                "dispatches": 0,
                "submit_mono": time.monotonic(), "result": None,
                "latency_s": None, "events": [],
            }
            stamp_stage(entry, "admitted", "router")
            stamp_stage(entry, "queued", "router")
            self._ledger[rid] = entry
            self._queue.append(rid)
            self._open += 1
            return rid

    def result(self, rid: str) -> dict | None:
        """The ledger entry for ``rid`` (a copy), or None if unknown —
        including a completed entry the ledger already compacted away
        (``retain_done`` bounds how long results are retained)."""
        with self._lock:
            entry = self._ledger.get(rid)
            return dict(entry) if entry is not None else None

    # -- lifecycle edges -------------------------------------------------
    def _promote_locked(self, rank: int, now: float) -> None:
        self.tx.set_serving_role(rank, "live")
        srv = self.tx.read_serving(rank)
        epoch = srv["epoch"]
        wv = int((srv.get("weights") or {}).get("version", 0) or 0)
        self._replicas[rank] = _Replica(epoch=epoch, sig_mono=now,
                                        wv=wv)
        self._detector.reset_rank(rank)  # fresh straggler episode
        self.tx.consume_join(rank)
        self.promotions += 1
        self.events.spare_promotions += 1
        self.tx.append_health_event("serve_promote", rank=rank,
                                    epoch=epoch)

    def _retire_locked(self, rank: int) -> int:
        """The epoch-fenced handoff: ``retire_replica`` bumps the fence
        and reclaims the queued requests in one atomic transport op;
        everything that was admitted but not completed goes back on the
        queue for survivors."""
        rep = self._replicas.pop(rank)
        undelivered = self.tx.retire_replica(rank)
        requeue = {r.get("rid") for r in undelivered}
        requeue.update(rep.in_flight)
        n = 0
        for rid in sorted(requeue, key=self._submit_order):
            entry = self._ledger.get(rid)
            # Only "dispatched" entries go back on the queue: "done"
            # was already delivered, and "queued" is already IN the
            # queue — appending it twice would double-dispatch.
            if entry is None or entry["state"] != "dispatched":
                continue
            entry["state"] = "queued"
            # dt here is dispatched -> requeued on the router clock:
            # how long the request sat on the replica that just died
            # (or drained) under it.
            stamp_stage(entry, "requeued", "router",
                        replica=entry["replica"])
            entry["replica"] = None
            self._queue.append(rid)
            self.redispatches += 1
            n += 1
        self.events.spare_demotions += 1
        return n

    def _submit_order(self, rid: str) -> float:
        entry = self._ledger.get(rid)
        return entry["submit_mono"] if entry else float("inf")

    def _evict_locked(self, rank: int, why: str, now: float) -> None:
        n = self._retire_locked(rank)
        self.evictions += 1
        self._ever_evicted.add(rank)
        self.events.replica_evictions += 1
        if self._c_evict is not None:
            self._c_evict.inc()
        self.tx.append_health_event("serve_evict", rank=rank, why=why,
                                    requeued=n)

    def drain(self, rank: int) -> bool:
        """Begin a graceful drain: stop dispatching to ``rank``, let it
        finish in-flight work, then demote it to spare (completed by a
        later :meth:`pump` once its in-flight set empties)."""
        with self._lock:
            rep = self._replicas.get(rank)
            if rep is None or rep.draining:
                return False
            rep.draining = True
        self.tx.set_drain(rank, True)
        self.tx.append_health_event("serve_drain", rank=rank)
        return True

    # -- continuous deployment (ISSUE 18) --------------------------------
    def note_weights(self, rank: int, version: int) -> None:
        """The deploy controller observed ``rank`` commit ``version``:
        record it so dispatches stamp the weights version the request
        is expected to be answered under (``entry["wv"]``)."""
        with self._lock:
            rep = self._replicas.get(rank)
            if rep is not None:
                rep.wv = int(version)

    def set_canary(self, ranks, every_n: int) -> None:
        """Steer a deterministic traffic slice at the canary ranks:
        every ``every_n``-th replica pick routes to a canary (when one
        has dispatch room), the rest to the stable pool.  Counter-based
        — identical request streams produce identical routing, so the
        chaos campaigns replay.  ``every_n=0`` (or no ranks) clears the
        slice and dispatch falls back to pure least-loaded."""
        with self._lock:
            self._canary = {int(r) for r in ranks}
            self._canary_every = max(0, int(every_n))
            self._canary_seq = 0

    def clear_canary(self) -> None:
        self.set_canary((), 0)

    # -- the pump --------------------------------------------------------
    def pump(self) -> None:
        """One control iteration: collect results, judge liveness and
        stragglers, complete drains, dispatch, grow."""
        now = time.monotonic()
        # 1. Collect first: a dying replica's last posts must be
        # credited before its eviction re-queues their rids.
        for res in self.tx.take_results(64):
            self._complete(res, now)
        beats = self.tx.read_beats()
        with self._lock:
            self._observe_beats_locked(beats, now)
            self._judge_stragglers_locked(now)
            self._finish_drains_locked(now)
            self._dispatch_locked()
            self._grow_locked(now)
            if self._g_replicas is not None:
                self._g_replicas.set(len(self._replicas))
                self._g_depth.set(len(self._queue))
                self._g_inflight.set(sum(
                    len(rep.in_flight)
                    for rep in self._replicas.values()))

    def _observe_beats_locked(self, beats: dict, now: float) -> None:
        for rank, rep in list(self._replicas.items()):
            entry = beats.get(rank)
            if entry is not None and entry[0] != rep.sig:
                rep.sig = entry[0]
                rep.sig_mono = now
                # Beats carry LIVENESS only: per-replica service times
                # now flow from the request event stream (the
                # ``computed`` stage deltas, see _complete) — one
                # detector feed shared with training instead of a
                # second bookkeeping path off the beat channel.
            if now - rep.sig_mono > self.cfg.replica_timeout_s:
                self._evict_locked(rank, "dead (beat stale)", now)

    def _judge_stragglers_locked(self, now: float) -> None:
        samples = {rank: rep.service_s
                   for rank, rep in self._replicas.items()
                   if not rep.draining}
        for verdict in self._detector.update(samples):
            if verdict.rank in self._replicas:
                self._evict_locked(
                    verdict.rank,
                    f"straggler {verdict.ratio:.1f}x median", now)

    def _finish_drains_locked(self, now: float) -> None:
        for rank, rep in list(self._replicas.items()):
            if rep.draining and not rep.in_flight:
                n = self._retire_locked(rank)
                self.drains_done += 1
                self.events.drains += 1
                self.tx.append_health_event("serve_demote", rank=rank,
                                            why="drained", requeued=n)

    def _pick_replica_locked(self, ready: list) -> int:
        """Choose the next dispatch target from ``ready`` (a list of
        ``(in_flight, rank)``).  With a canary slice active, every
        ``every_n``-th pick prefers the canary pool (least-loaded
        within it), the rest the stable pool; an empty preferred pool
        falls back to the other so neither side ever starves."""
        if self._canary and self._canary_every:
            canary = [t for t in ready if t[1] in self._canary]
            stable = [t for t in ready if t[1] not in self._canary]
            self._canary_seq += 1
            if self._canary_seq % self._canary_every == 0:
                pool = canary or stable
            else:
                pool = stable or canary
            return min(pool)[1]
        return min(ready)[1]

    def _dispatch_locked(self) -> None:
        # One regime observation per pump — NOT per request: the
        # scheduler's dwell counts observations, and a burst of N
        # dispatches is one load sample, not N votes to flip.
        lever = None
        if self.scheduler is not None:
            lever = self.scheduler.observe(
                len(self._queue),
                sum(len(rep.in_flight)
                    for rep in self._replicas.values()))
        while self._queue:
            ready = [(len(rep.in_flight), rank)
                     for rank, rep in self._replicas.items()
                     if not rep.draining
                     and len(rep.in_flight) < self.cfg.max_outstanding]
            if not ready:
                return
            rank = self._pick_replica_locked(ready)
            rep = self._replicas[rank]
            room = self.cfg.max_outstanding - len(rep.in_flight)
            for _ in range(min(self.cfg.micro_batch, room,
                               len(self._queue))):
                rid = self._queue.popleft()
                entry = self._ledger.get(rid)
                if entry is None or entry["state"] != "queued":
                    # Stale queue entry: an eviction requeued the rid,
                    # then the dead replica's late result completed it
                    # (or compaction dropped it) while it still sat in
                    # the queue.  Re-dispatching a done rid would reset
                    # it to "dispatched" and let the survivor's answer
                    # drive _open negative — exactly-once demands one
                    # completion per rid, ever.
                    continue
                entry["state"] = "dispatched"
                entry["replica"] = rank
                entry["epoch"] = rep.epoch
                entry["wv"] = rep.wv
                entry["dispatches"] += 1
                # dt here is queued -> dispatched on the router clock:
                # the queue wait.
                stamp_stage(entry, "dispatched", "router",
                            disp=entry["dispatches"], replica=rank)
                rep.in_flight.add(rid)
                payload = {
                    "rid": rid, "prompt": entry["prompt"],
                    "epoch": rep.epoch,
                    "dispatch": entry["dispatches"],
                    "events": entry["events"],
                }
                if lever is not None:
                    payload["lever"] = lever
                self.tx.push_request(rank, payload)

    def _grow_locked(self, now: float) -> None:
        live = sum(1 for rep in self._replicas.values()
                   if not rep.draining)
        deficit = max(0, self.cfg.replicas - live)
        if len(self._queue) >= self.cfg.grow_watermark * self.cfg.max_queue:
            self._pressure += 1
        else:
            self._pressure = 0
        want = deficit
        if self._pressure >= self.cfg.grow_patience:
            ceiling = self.cfg.max_replicas
            if ceiling is None or live + deficit < ceiling:
                want += 1
                self._pressure = 0
        if want <= 0:
            return
        joins = self.tx.read_joins()
        # Prefer spares that were never evicted: an evicted-then-
        # re-announced rank only comes back when nobody cleaner exists.
        spares = sorted(
            (r for r, p in joins.items()
             if p.get("spare") and r not in self._replicas),
            key=lambda r: (r in self._ever_evicted, r))
        for rank in spares[:want]:
            self._promote_locked(rank, now)

    def _complete(self, res: dict, now: float) -> None:
        record = None
        outcome = None
        with self._lock:
            rid = res.get("rid")
            entry = self._ledger.get(rid)
            if entry is None:
                if rid in self._tombstones:
                    self.duplicates_discarded += 1
                else:
                    self.unknown_results += 1
                return
            if entry["state"] == "done":
                # First-result-wins: the replica died AFTER posting but
                # before the router observed it, so the rid was
                # re-dispatched and a survivor answered too.  One
                # delivery, one counted duplicate — recorded on the
                # winner's timeline so a postmortem shows the race.
                self.duplicates_discarded += 1
                stamp_stage(entry, "dropped", "router", why="duplicate")
                return
            owner = self._replicas.get(entry.get("replica"))
            if owner is not None:
                owner.in_flight.discard(rid)
                owner.served += 1
            entry["state"] = "done"
            entry["result"] = res.get("output")
            # The hub-stamped weights version that produced this
            # answer (ISSUE 18) — what a postmortem ties a served
            # output back to.
            entry["version"] = res.get("version")
            entry["latency_s"] = now - entry["submit_mono"]
            # Merge the worker-side journey (taken/bound/computed/
            # posted, stamped on the replica's own clock) into the
            # authoritative ledger record, then close it.  Router
            # stamps in the posted copy would be duplicates of what
            # the ledger already holds.
            for ev in res.get("events") or ():
                if isinstance(ev, dict) and ev.get("by") != "router":
                    entry["events"].append(dict(ev))
            # dt here is dispatched -> completed on the router clock:
            # the full dispatch round trip (the worker stages nest
            # inside it — summing them alongside would double-count).
            stamp_stage(entry, "completed", "router")
            self.latency.observe(entry["latency_s"])
            for ev in entry["events"]:
                dt = ev.get("dt")
                if dt is not None and ev["stage"] in _HISTOGRAM_STAGES:
                    self._stage_latency(ev["stage"]).observe(dt)
            # The straggler feed (shared detector code path): the
            # ``computed`` deltas are per-replica compute intervals.
            # Engine replicas (ISSUE 19) stamp ``decode`` instead —
            # the per-request decode interval is their service sample.
            samples = serving_stage_samples(
                entry["events"], stage="computed")
            if not samples:
                samples = serving_stage_samples(
                    entry["events"], stage="decode")
            for rank, dur in samples.items():
                rep = self._replicas.get(rank)
                if rep is not None:
                    rep.service_s = dur
            if self.slo is not None:
                self.slo.observe(latency_s=entry["latency_s"])
            if self._tel is not None:
                tr = self._tel.tracer
                t1 = tr.now()
                tr.complete("request", t1 - entry["latency_s"], t1,
                            rid=rid, dispatches=entry["dispatches"],
                            replica=entry.get("replica"))
            if self.cfg.record_requests:
                record = {
                    "rid": rid, "state": "done",
                    "latency_s": entry["latency_s"],
                    "dispatches": entry["dispatches"],
                    "version": res.get("version"),
                    "events": [dict(ev) for ev in entry["events"]],
                }
            if self.on_complete is not None:
                # The deploy controller's per-outcome feed: the posted
                # ``version`` is authoritative (the hub's fence stamped
                # it), ``wv`` is what the router expected at dispatch.
                outcome = {
                    "rid": rid, "replica": entry.get("replica"),
                    "wv": entry.get("wv"),
                    "version": res.get("version"),
                    "latency_s": entry["latency_s"],
                    "prompt": entry.get("prompt"),
                    "output": entry.get("result"),
                }
            self.completed += 1
            self._open -= 1
            self._done_fifo.append(rid)
            while len(self._done_fifo) > self.cfg.retain_done:
                old = self._done_fifo.popleft()
                self._ledger.pop(old, None)
                self._compacted += 1
                self._tombstones[old] = None
                while len(self._tombstones) > self._tombstone_cap:
                    self._tombstones.popitem(last=False)
        if outcome is not None:
            # Outside the lock: the controller's hook may read router
            # state (audit) or talk to the transport.
            self.on_complete(outcome)
        if record is not None:
            # Outside the lock: on tcp this is a network round trip,
            # and submit() from client threads must not block on it.
            self.tx.append_health_event("serve_request", **record)

    # -- driving ---------------------------------------------------------
    def run(self, stop_event: threading.Event) -> None:
        """Pump until ``stop_event`` — the router's own thread target."""
        while not stop_event.is_set():
            self.pump()
            stop_event.wait(self.cfg.poll_s)

    def wait_idle(self, timeout_s: float,
                  stop_event: threading.Event | None = None) -> bool:
        """Block until every admitted request completed (True) or the
        deadline passed (False).  Safe from a client thread while a
        router thread pumps."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._open == 0:
                    return True
            if stop_event is not None and stop_event.is_set():
                return False
            time.sleep(0.005)
        with self._lock:
            return self._open == 0

    # -- audit / shutdown ------------------------------------------------
    def audit(self) -> dict:
        """The exactly-once verdict the chaos campaigns assert on: every
        admitted request must be completed exactly once — duplicates
        discarded and rejects are *counted*, loss is a failure."""
        with self._lock:
            states = collections.Counter(
                e["state"] for e in self._ledger.values())
            # Compacted entries were all "done" — they left the ledger
            # but still count toward the exactly-once arithmetic.
            if self._compacted:
                states["done"] += self._compacted
            admitted = len(self._ledger) + self._compacted
            q = self.latency.quantiles()
            return {
                "admitted": admitted,
                "completed": self.completed,
                "open": self._open,
                "compacted": self._compacted,
                "states": dict(states),
                "rejected": self.rejected,
                "duplicates_discarded": self.duplicates_discarded,
                "unknown_results": self.unknown_results,
                "redispatches": self.redispatches,
                "promotions": self.promotions,
                "evictions": self.evictions,
                "drains": self.drains_done,
                "exactly_once": (self._open == 0
                                 and states.get("done", 0) == admitted),
                "weight_versions": {
                    rank: rep.wv
                    for rank, rep in sorted(self._replicas.items())},
                "canary": sorted(self._canary),
                "latency": q,
                "stage_latency": {
                    s: h.quantiles()
                    for s, h in sorted(self._stage_hist.items())},
            }

    def close(self) -> dict:
        """Stop admitting, append the ``serving`` summary health record
        (the ``tools/gang_status.py`` serving view), and return the
        final audit."""
        with self._lock:
            self._closed = True
        verdict = self.audit()
        with self._lock:
            live = len(self._replicas)
            depth = len(self._queue)
        self.tx.append_health_event(
            "serving", replicas=live, queue_depth=depth,
            completed=verdict["completed"],
            admitted=verdict["admitted"],
            rejected=verdict["rejected"],
            duplicates_discarded=verdict["duplicates_discarded"],
            evictions=verdict["evictions"], drains=verdict["drains"],
            promotions=verdict["promotions"],
            exactly_once=verdict["exactly_once"],
            p50=verdict["latency"].get("p50"),
            p95=verdict["latency"].get("p95"),
            p99=verdict["latency"].get("p99"),
        )
        return verdict
