"""part3 — bucketed ring all-reduce (reference ``part3/main.py``).

The reference wraps the model in DDP with 25 MB buckets
(``part3/main.py:137``) — bucketed ring all-reduce with averaging, BN
enabled (``part3/model.py:24``).  Here: the hand-rolled explicit
``lax.ppermute`` ring (the north-star), 25 MB buckets, mean semantics,
VGG-11 with BatchNorm.

Gradient wire compression (``--ring-compress {none,bf16,int8,topk}``,
``--ring-topk-frac``): compress each ring hop's payload — int8 with
per-chunk fp32 scales or magnitude top-k sparsification, both carrying
an error-feedback residual across steps (EF-SGD), or a cast-only bf16
wire.  ~4x fewer bytes on the wire for int8/topk at loss-curve parity
(docs/PERF.md "Compressed ring all-reduce"); ``--wire-dtype bfloat16``
is the deprecated spelling of ``--ring-compress bf16``.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.cli.common import make_flag_parser, parse_flags, run_part
from distributed_machine_learning_tpu.ops.ring import DEFAULT_BUCKET_BYTES

BATCH_SIZE = 64  # per worker — part3/main.py:31


def main(argv=None) -> None:
    parser = make_flag_parser(__doc__)
    parser.add_argument("--bucket-mb", default=25, type=int,
                        help="ring all-reduce bucket size (part3/main.py:137)")
    args = parse_flags(parser, argv)
    run_part(
        "ring",
        per_rank_batch=BATCH_SIZE,
        use_bn=True,
        args=args,
        strategy_kwargs={"bucket_bytes": args.bucket_mb * 2**20},
    )


if __name__ == "__main__":
    main()
