"""Profiling/metrics subsystem: trace no-op + real trace, metrics flush.

TPU-native replacement for the reference's hand-rolled timing + external
dstat plots (SURVEY.md §5 "Tracing / profiling").
"""

import csv
import json
import os

import jax
import jax.numpy as jnp

from distributed_machine_learning_tpu.utils.profiling import (
    MetricsLogger,
    annotate,
    trace,
)


def test_trace_noop_without_dir():
    with trace(None):
        pass  # must not start the profiler


def test_trace_writes_profile(tmp_path):
    log_dir = tmp_path / "prof"
    with trace(log_dir):
        with annotate("test-span"):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    found = []
    for root, _, files in os.walk(log_dir):
        found.extend(f for f in files if f.endswith(".xplane.pb"))
    assert found, f"no xplane trace written under {log_dir}"


def test_metrics_logger_csv_and_jsonl(tmp_path):
    m = MetricsLogger()
    m.log(step=1, loss=2.5, iter_seconds=0.1)
    m.log(step=2, loss=2.4, iter_seconds=0.09, extra=7)

    csv_path = tmp_path / "m.csv"
    m.to_csv(csv_path)
    with open(csv_path) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 2
    assert rows[0]["step"] == "1" and rows[1]["extra"] == "7"
    assert rows[0]["extra"] == ""  # union-of-columns semantics

    jsonl_path = tmp_path / "m.jsonl"
    m.to_jsonl(jsonl_path)
    lines = [json.loads(l) for l in open(jsonl_path)]
    assert lines[1]["loss"] == 2.4 and "extra" not in lines[0]


def test_metrics_logger_empty_still_creates_file(tmp_path):
    # A reported path must always exist, even with zero rows.
    m = MetricsLogger()
    p = tmp_path / "empty.csv"
    m.save(p)
    assert p.exists() and p.read_text() == ""
    j = tmp_path / "empty.jsonl"
    m.save(j)
    assert j.exists() and j.read_text() == ""


def test_metrics_save_dispatches_by_extension(tmp_path):
    m = MetricsLogger()
    m.log(step=1, loss=1.0)
    m.save(tmp_path / "a.csv")
    assert (tmp_path / "a.csv").read_text().startswith("step,")
    m.save(tmp_path / "a.jsonl")
    assert json.loads((tmp_path / "a.jsonl").read_text())["step"] == 1
