# dmlcheck-virtual-path: distributed_machine_learning_tpu/telemetry/fixture.py
"""DML010 firing case: a JSONL stream truncated on open — erases the
pre-crash attempts a post-mortem needs."""
import json


def start_metrics(path):
    return open(path + "/metrics.jsonl", "w")


def reset_ledger(ledger_path, entries):
    with open(ledger_path, "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
