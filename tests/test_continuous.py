"""Continuous-batching engine (inference/continuous.py): token-for-token
parity with ``inference/generate.py``, EOS retirement + same-step
backfill, admission control against the paged pool, the swap fence,
and the regime lever (ISSUE 19).  All CPU; the tiny model keeps every
jitted program sub-second."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.inference.continuous import (
    ContinuousEngine,
    EngineConfig,
)
from distributed_machine_learning_tpu.inference.generate import (
    generate,
    make_serving_step,
)
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.runtime.scheduler import (
    RegimeConfig,
    RegimeScheduler,
)
from distributed_machine_learning_tpu.telemetry.registry import (
    MetricsRegistry,
)

EOS = 13  # the tiny model's greedy attractor (it emits runs of 13s)


@pytest.fixture(scope="module")
def lm():
    model = TransformerLM(
        vocab_size=32, d_model=16, n_layers=2, n_heads=4, n_kv_heads=2
    )
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _ref(model, params, prompt, n, **kw):
    return np.asarray(
        generate(model, params, np.asarray([prompt], np.int32), n, **kw)
    )[0].tolist()


def test_engine_greedy_parity_ragged_batch(lm):
    """Every ragged request decoded by one shared-pool engine matches
    the dedicated-cache generate() token for token."""
    model, params = lm
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=3, block_size=4, num_blocks=32, max_len=32,
        levers=("latency",),
    ))
    prompts = [[1, 2, 3, 4], [5, 6, 7], [9, 10, 11, 12, 13],
               [2, 4, 6, 8], [3, 3, 3]]
    for i, p in enumerate(prompts):
        eng.submit(f"r{i}", list(p), max_new=6)
    done = {d["rid"]: d for d in eng.drain()}
    assert len(done) == len(prompts)
    for i, p in enumerate(prompts):
        assert done[f"r{i}"]["tokens"] == _ref(model, params, p, 6)
        assert done[f"r{i}"]["finish"] == "length"


def test_engine_mid_flight_admission_parity(lm):
    """Requests submitted while others are mid-decode join without
    disturbing anyone's stream — the whole point of iteration-level
    scheduling."""
    model, params = lm
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=32, max_len=32,
        levers=("latency",),
    ))
    eng.submit("a", [1, 2, 3, 4], max_new=8)
    for _ in range(3):
        eng.step()
    assert eng.in_flight() == 1
    eng.submit("b", [5, 6, 7], max_new=8)     # joins mid-flight
    done = {d["rid"]: d for d in eng.drain()}
    assert done["a"]["tokens"] == _ref(model, params, [1, 2, 3, 4], 8)
    assert done["b"]["tokens"] == _ref(model, params, [5, 6, 7], 8)


def test_engine_eos_retires_and_backfills_same_step(lm):
    """EOS retirement frees the lane and the pool blocks, and a queued
    request backfills inside the same step() call."""
    model, params = lm
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=1, block_size=4, num_blocks=8, max_len=32,
        eos_id=EOS, levers=("latency",),
    ))
    # [9,10,11,12] greedily continues 13 13 ... -> instant EOS.
    eng.submit("a", [9, 10, 11, 12], max_new=10)
    eng.submit("b", [1, 2, 3], max_new=3)
    # Step until a retires; b must be admitted in that same call.
    for _ in range(50):
        out = eng.step()
        if out:
            break
    assert out and out[0]["rid"] == "a"
    assert out[0]["finish"] == "eos"
    assert out[0]["tokens"][-1] == EOS
    assert eng.in_flight() == 1            # b backfilled immediately
    assert eng.queued() == 0
    ref = _ref(model, params, [9, 10, 11, 12], 10, eos_id=EOS)
    cut = ref.index(EOS, 4) + 1
    assert out[0]["tokens"] == ref[:cut]
    done = eng.drain()
    assert done[0]["rid"] == "b"
    assert done[0]["tokens"] == _ref(model, params, [1, 2, 3], 3)


def test_serving_step_eos_parity_token_for_token(lm):
    """The ISSUE 19 semantics-drift fix: make_serving_step(eos_id=...)
    matches generate() token for token — identical prefix through the
    first EOS, eos-padding after — while the eos-free path is
    unchanged."""
    model, params = lm
    prompts = [[1, 2, 3, 4], [9, 10, 11, 12], [5, 6, 7, 8]]
    step = make_serving_step(model, params, 10, eos_id=EOS)
    outs = step([list(p) for p in prompts])
    for p, out in zip(prompts, outs):
        ref = _ref(model, params, p, 10)          # no-eos reference
        gen_ref = ref[len(p):]
        gen_out = out[len(p):]
        if EOS in gen_ref:
            cut = gen_ref.index(EOS) + 1
            assert gen_out[:cut] == gen_ref[:cut]
            assert all(t == EOS for t in gen_out[cut:])
        else:
            assert gen_out == gen_ref
    # eos_id=None keeps the original scan program's output exactly.
    plain = make_serving_step(model, params, 10)
    outs0 = plain([list(p) for p in prompts])
    for p, out in zip(prompts, outs0):
        assert out == _ref(model, params, p, 10)


def test_engine_admission_control_queues_then_serves(lm):
    """A pool too small for all requests at once admits what fits,
    holds the rest queued, and serves everything as retirements free
    blocks — nothing dropped, everything exact."""
    model, params = lm
    # 6 blocks x 4 slots = 24 slots; each request needs 4+4=8 slots
    # (2 blocks), so at most 3 of the 5 fit concurrently.
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=4, block_size=4, num_blocks=6, max_len=8,
        levers=("latency",),
    ))
    prompts = {f"r{i}": [1 + i, 2 + i, 3, 4] for i in range(5)}
    for rid, p in prompts.items():
        eng.submit(rid, list(p), max_new=4)
    eng.step()
    assert eng.in_flight() == 3 and eng.queued() == 2
    done = {d["rid"]: d for d in eng.drain()}
    assert len(done) == 5
    for rid, p in prompts.items():
        assert done[rid]["tokens"] == _ref(model, params, p, 4)


def test_engine_shared_pool_beats_padded_footprint(lm):
    """Engine-level statement of the paged-memory win: lanes x max_len
    padding would need 4 x 32 = 128 slots; this pool has 48 — yet the
    same 4-wide ragged batch runs, because residency is per-token."""
    model, params = lm
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=4, block_size=4, num_blocks=12, max_len=32,
        levers=("latency",),
    ))
    pool_slots = 12 * 4
    padded_slots = 4 * 32
    assert pool_slots < padded_slots
    prompts = [[1, 2, 3], [4, 5, 6], [7, 8, 9], [2, 4, 6]]
    for i, p in enumerate(prompts):
        eng.submit(f"r{i}", list(p), max_new=5)
    eng.step()
    assert eng.in_flight() == 4            # all admitted concurrently
    done = {d["rid"]: d for d in eng.drain()}
    for i, p in enumerate(prompts):
        assert done[f"r{i}"]["tokens"] == _ref(model, params, p, 5)


def test_engine_swap_fence_refuses_in_flight(lm):
    """swap_params is the weight hot-swap fence: it refuses while any
    sequence is in flight, and after a drain the new weights serve
    with the new version stamped on completions."""
    model, params = lm
    params2 = model.init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=16, max_len=32,
        levers=("latency",),
    ), version=1)
    eng.submit("a", [1, 2, 3, 4], max_new=6)
    eng.step()
    assert eng.in_flight() == 1
    with pytest.raises(RuntimeError, match="in flight"):
        eng.swap_params(params2, version=2)
    eng.pause_admission()
    done = eng.drain()
    assert done and done[0]["version"] == 1
    assert done[0]["tokens"] == _ref(model, params, [1, 2, 3, 4], 6)
    eng.swap_params(params2, version=2)
    eng.resume_admission()
    eng.submit("b", [1, 2, 3, 4], max_new=6)
    done2 = eng.drain()
    assert done2[0]["version"] == 2
    assert done2[0]["tokens"] == _ref(model, params2, [1, 2, 3, 4], 6)
    # The two versions genuinely decode differently (the mixing test
    # in tests/test_deploy.py leans on this).
    assert done2[0]["tokens"] != done[0]["tokens"]


def test_engine_regime_lever_int8_parity(lm):
    """The throughput lever serves int8 weight-only decode; outputs
    match generate(quantize="int8") and the lever is recorded."""
    model, params = lm
    sched = RegimeScheduler(RegimeConfig(
        thin_width=0, wide_width=1, dwell_steps=1,
    ))
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=16, max_len=16,
        levers=("latency", "throughput"),
    ), scheduler=sched)
    eng.submit("q", [1, 2, 3, 4], max_new=4)
    done = eng.drain()
    assert done[0]["lever"] == "throughput"
    assert sched.flips >= 1
    assert done[0]["tokens"] == _ref(
        model, params, [1, 2, 3, 4], 4, quantize="int8"
    )


def test_engine_router_hint_overrides_local_scheduler(lm):
    model, params = lm
    sched = RegimeScheduler(RegimeConfig(
        thin_width=0, wide_width=1, dwell_steps=1,
    ))
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=16, max_len=16,
        levers=("latency", "throughput"),
    ), scheduler=sched)
    eng.note_lever("latency")
    eng.submit("q", [1, 2, 3, 4], max_new=3)
    done = eng.drain()
    assert done[0]["lever"] == "latency"
    with pytest.raises(ValueError):
        eng.note_lever("warp")


def test_engine_telemetry_and_invariants(lm):
    """Histograms/gauges land in the registry and the allocator's
    invariants hold after a full serve cycle."""
    model, params = lm
    reg = MetricsRegistry()
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=16, max_len=16,
        levers=("latency",),
    ), registry=reg)
    for i in range(3):
        eng.submit(f"r{i}", [1 + i, 2, 3], max_new=4)
    eng.drain()
    eng.allocator.check_invariants()
    assert eng.allocator.free_blocks() == 16
    snap = reg.snapshot()
    hists = {m["name"]: m for m in snap["histograms"]}
    for name in ("engine_prefill_s", "engine_decode_s", "engine_e2e_s"):
        assert hists[name]["count"] == 3, name
    counters = {m["name"]: m["value"] for m in snap["counters"]}
    assert counters["engine_requests_total"] == 3
    assert counters["engine_tokens_total"] == 12


def test_engine_submit_validation(lm):
    model, params = lm
    eng = ContinuousEngine(model, params, EngineConfig(
        max_lanes=1, block_size=4, num_blocks=8, max_len=16,
        levers=("latency",),
    ))
    with pytest.raises(ValueError, match="empty"):
        eng.submit("a", [])
    with pytest.raises(ValueError, match="max_len"):
        eng.submit("a", list(range(1, 14)), max_new=8)
    with pytest.raises(ValueError, match="max_new"):
        eng.submit("a", [1, 2], max_new=0)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        ContinuousEngine(
            model.clone(kv_cache_dtype=jnp.int8), params,
            EngineConfig(levers=("latency",)),
        )
