"""Gang coordination: heartbeats, peer-failure detection, coordinated
abort, and the restore-point election.

PR 1's supervisor heals a *single* process; a real data-parallel gang
(``runtime/distributed.py``, the reference's 4-node gloo cluster) fails
differently: one rank dies or stalls mid-collective and every other
rank blocks forever inside gloo/ICI with no Python frame to raise from.
Nothing inside the process can un-hang it — the only cure is for the
*survivors* to notice, abort hard, and for a gang supervisor
(``runtime/supervisor.py::gang_supervise``) to relaunch everyone
together from a checkpoint every rank agrees on.

The medium is a shared directory (``gang_dir``) because it is the one
channel both local multi-process gangs and TPU pods reliably share (a
pod's workers mount common storage; collectives are exactly the thing
we cannot trust during a failure).  Three file families live there:

- ``beat_rank<r>.json`` — rank r's heartbeat.  A daemon thread rewrites
  it every ``heartbeat_interval_s`` with the age of the rank's last
  *training progress* (``beat()`` calls from the step loop).  File
  mtime going stale means the process died; a fresh file whose
  ``beat_age`` exceeds the timeout means the process is alive but stuck
  (hung collective, wedged loader).  ``suspend()`` marks expected-long
  non-step phases (checkpoint save, eval, compile, rendezvous) so they
  are not judged as stalls — liveness detection keeps running.
- ``restore_rank<r>.json`` — rank r's restore-point record: every
  checkpoint step it has locally verified (saved successfully or
  restored from).  The election (``elect_restore_step``) intersects all
  ranks' records and picks the highest step every rank agrees on —
  the only step where a coordinated relaunch is guaranteed to find all
  shards of one consistent checkpoint.
- ``abort.json`` — the coordinated-abort latch.  The first rank to
  declare a peer dead writes it (atomically, first writer wins) and
  exits with :data:`GANG_ABORT_EXIT`; every other rank's monitor sees
  the file and exits too, so the whole gang tears down within one
  heartbeat interval instead of hanging on the dead peer.

Everything here is host-side stdlib (files + one daemon thread per
rank): the compiled step and the collectives are never touched, and a
rank blocked inside a collective can still be aborted because
``os._exit`` works from the monitor thread.

Telemetry (PR 2): ``gang_heartbeat_age_s{rank=...}`` gauges track every
peer's progress age; ``gang_peer_failures`` counts declarations; all
abort events flush before exit so the post-mortem trace survives.

Observability plane (ISSUE 6): heartbeats are ENRICHED — each beat
carries a compact metric snapshot (current step, rolling step time
over the last ``metrics_window`` completed steps, last per-phase
breakdown) published by :meth:`GangCoordinator.observe_step`, so
liveness and progress travel on one channel and the gang supervisor's
straggler detector (``telemetry/aggregator.py``) reads the whole
gang's health from the beat directory alone.  Advisory verdicts and
restart/shrink events land in ``gang_health.jsonl``
(:func:`append_health_event`), the whole-run ledger
``tools/gang_status.py`` renders.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

# Exit code of a coordinated gang abort — distinct from an injected rank
# death (runtime/faults.py::KILL_RANK_EXIT) so logs show who was the
# victim and who pulled the cord.
GANG_ABORT_EXIT = 43

ABORT_FILE = "abort.json"
_BEAT_PREFIX = "beat_rank"
_RESTORE_PREFIX = "restore_rank"

# Per-rank consumed-example ledgers written by runtime/gang_worker.py
# (the elastic exactly-once audit trail).  Cleared with the fault
# ledger at fresh-run init — but NOT across restarts or shrinks, where
# they are the whole-run history a post-mortem reads.
CONSUMED_PREFIX = "consumed_rank"

# The gang health ledger: one JSON line per advisory event the gang
# supervisor records (straggler verdicts, restarts, shrinks, grows,
# promotions/demotions) — the durable half of the observability plane,
# read back by ``telemetry/aggregator.py::read_health_events`` and
# ``tools/gang_status.py``.  Whole-run history like the consumption
# ledgers: survives restarts and shrinks, cleared only at fresh-run
# init.
GANG_HEALTH_FILE = "gang_health.jsonl"

# The join/announcement channel (ISSUE 10, elastic GROW): one
# ``join_rank<r>.json`` per member announcing itself to the supervisor
# — a recovered host asking to be readmitted, or a warm spare
# publishing that it is alive and which checkpoint step it has
# prefetched.  Written atomically by the announcing process, consumed
# (deleted) by the supervisor when it ADMITS the member at a
# coordinated restart/grow boundary; pending announcements survive
# restarts and shrinks (they are exactly what the next boundary reads)
# and are cleared only at fresh-run init, like the ledgers above.
JOIN_PREFIX = "join_rank"


# ---------------------------------------------------------------------------
# Deterministic-scheduler seam (dmlcheck layer 3)
# ---------------------------------------------------------------------------
# ``analysis/interleave.py`` installs a cooperative scheduler here to
# explore thread interleavings of the gang control plane under its own
# control.  The hooks live in THIS module because it is the bottom of
# the runtime import chain (``runtime/transport.py`` already imports
# it, so the transport aliases these rather than the reverse).  With no
# scheduler installed — every production and ordinary-test run — a
# schedule point is one global read and a None test.

_SCHED = None


def install_scheduler(sched) -> None:
    """Route every schedule point to ``sched`` (layer-3 exploration
    only; one scheduler per process at a time)."""
    global _SCHED
    _SCHED = sched


def uninstall_scheduler() -> None:
    global _SCHED
    _SCHED = None


def _sched_point(label: str) -> None:
    """A schedule point: under an installed scheduler the calling
    thread (if registered with it) yields control here and resumes only
    when scheduled.  ``label`` is structured ``channel:...[:r|:w]`` so
    the explorer can judge independence of adjacent steps."""
    sched = _SCHED
    if sched is not None:
        sched.point(label)


def _sched_block(label: str, predicate) -> bool:
    """A blocking schedule point: the thread is descheduled until
    ``predicate()`` turns true (the seam for real waits like
    ``_InFlight.wait`` — a cooperatively-scheduled thread must never
    sit in a native wait the scheduler cannot see).  Returns True when
    a scheduler handled the wait (the predicate now holds), False when
    the caller must fall back to its real blocking wait."""
    sched = _SCHED
    if sched is not None:
        return sched.block(label, predicate)
    return False


def _beat_path(gang_dir: str, rank: int) -> str:
    return os.path.join(gang_dir, f"{_BEAT_PREFIX}{rank}.json")


def _restore_path(gang_dir: str, rank: int) -> str:
    return os.path.join(gang_dir, f"{_RESTORE_PREFIX}{rank}.json")


def _write_atomic(path: str, payload: dict) -> None:
    # Tmp name unique per process AND thread: the monitor thread and the
    # main thread (finish()) may both be writing this rank's beat file.
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def append_health_event(gang_dir: str | os.PathLike, kind: str,
                        **fields) -> None:
    """Record one advisory event in the gang health ledger — flushed
    AND fsynced before returning (dmlcheck DML002): the next supervisor
    action may be tearing the gang down via ``os._exit``, and a verdict
    that only reached the page cache at that point is lost with it."""
    payload = {"kind": kind, "time": time.time(), **fields}
    gang_dir = os.fspath(gang_dir)
    os.makedirs(gang_dir, exist_ok=True)
    with open(os.path.join(gang_dir, GANG_HEALTH_FILE), "a") as f:
        f.write(json.dumps(payload) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _join_path(gang_dir: str, rank: int) -> str:
    return os.path.join(gang_dir, f"{JOIN_PREFIX}{rank}.json")


def announce_join(gang_dir: str | os.PathLike, rank: int, *,
                  spare: bool = False, prefetched_step: int | None = None,
                  **fields) -> None:
    """Publish (or refresh) a join announcement for ORIGINAL-rank
    ``rank`` — the member's half of the grow protocol.  A recovered
    host announces ``spare=False`` (readmit me); a warm spare
    announces ``spare=True`` with the checkpoint step it has
    prefetched (``prefetched_step``), refreshed every heartbeat so the
    supervisor can tell a live spare from a dead announcement.
    Atomic overwrite: re-announcing is idempotent and the supervisor
    never reads a torn payload."""
    if rank < 0:
        raise ValueError(f"rank must be >= 0, got {rank}")
    gang_dir = os.fspath(gang_dir)
    os.makedirs(gang_dir, exist_ok=True)
    payload = {"rank": int(rank), "spare": bool(spare),
               "time": time.time(), **fields}
    if prefetched_step is not None:
        payload["prefetched_step"] = int(prefetched_step)
    _write_atomic(_join_path(gang_dir, rank), payload)


def read_joins(gang_dir: str | os.PathLike) -> dict[int, dict]:
    """rank -> announcement payload for every pending join under
    ``gang_dir`` (torn writes skipped — the next poll sees them
    whole)."""
    gang_dir = os.fspath(gang_dir)
    out: dict[int, dict] = {}
    try:
        names = os.listdir(gang_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith(JOIN_PREFIX) and name.endswith(".json")):
            continue
        rank_s = name[len(JOIN_PREFIX):-len(".json")]
        if not rank_s.isdigit():
            continue
        try:
            with open(os.path.join(gang_dir, name)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(payload, dict):
            out[int(rank_s)] = payload
    return out


def consume_join(gang_dir: str | os.PathLike, rank: int) -> None:
    """Remove rank ``rank``'s announcement — called by the supervisor
    at the boundary that ADMITS the member, so the same announcement
    can never drive two grows."""
    with contextlib.suppress(OSError):
        os.remove(_join_path(os.fspath(gang_dir), rank))


def read_abort(gang_dir: str | os.PathLike) -> dict | None:
    """The abort latch's payload, or None when no abort was declared.
    Tolerates a torn write (another rank mid-``os.replace``) by treating
    it as not-yet-declared — the next poll sees the complete file."""
    try:
        with open(os.path.join(os.fspath(gang_dir), ABORT_FILE)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def declare_abort(gang_dir: str | os.PathLike, reason: str,
                  by_rank: int, peer: int | None = None) -> bool:
    """Write the abort latch; returns True if THIS call won the race
    (False: someone already declared — their reason stands)."""
    path = os.path.join(os.fspath(gang_dir), ABORT_FILE)
    payload = {"reason": reason, "by_rank": by_rank, "time": time.time()}
    if peer is not None:
        payload["peer"] = peer
    try:
        with open(path, "x") as f:
            json.dump(payload, f)
        return True
    except FileExistsError:
        return False


def clear_gang_state(gang_dir: str | os.PathLike,
                     restore_records: bool = False,
                     fault_ledger: bool | None = None) -> None:
    """Remove the previous attempt's beats and abort latch (and, for a
    fresh run, the restore-point records and the fired-fault ledger).
    Restore records and the ledger survive between restart attempts by
    design: the records ARE the election input, and the ledger is what
    keeps an already-fired fault from re-firing in the relaunch.

    ``fault_ledger`` decouples the ledger from the records (default:
    follows ``restore_records``): a gang SHRINK renumbers ranks, so the
    old numbering's restore records must go — but the ledger must stay,
    or every already-fired fault would re-fire on whichever survivor
    inherited the fired rank's number.  Join announcements follow the
    same fresh-run-only rule: a pending join must survive the very
    boundary that will admit it (the supervisor consumes it there),
    while a stale one from an earlier run must not trigger a phantom
    grow."""
    from distributed_machine_learning_tpu.runtime.faults import (
        FAULT_LEDGER_FILE,
    )

    if fault_ledger is None:
        fault_ledger = restore_records
    gang_dir = os.fspath(gang_dir)
    if not os.path.isdir(gang_dir):
        os.makedirs(gang_dir, exist_ok=True)
        return
    for name in os.listdir(gang_dir):
        if (name == ABORT_FILE or name.startswith(_BEAT_PREFIX)
                or (restore_records and name.startswith(_RESTORE_PREFIX))
                or (fault_ledger
                    and (name == FAULT_LEDGER_FILE
                         or name == GANG_HEALTH_FILE
                         or name.startswith(CONSUMED_PREFIX)
                         or name.startswith(JOIN_PREFIX)))):
            with contextlib.suppress(OSError):
                os.remove(os.path.join(gang_dir, name))


def read_restore_record(gang_dir: str | os.PathLike, rank: int
                        ) -> set[int] | None:
    """The set of checkpoint steps rank ``rank`` has verified, or None
    when the rank never recorded one (fresh start / died pre-save)."""
    try:
        with open(_restore_path(os.fspath(gang_dir), rank)) as f:
            payload = json.load(f)
        return {int(s) for s in payload.get("steps", [])}
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def _as_dirs(ckpt_dirs) -> list[str]:
    if ckpt_dirs is None:
        return []
    if isinstance(ckpt_dirs, (str, os.PathLike)):
        return [os.fspath(ckpt_dirs)]
    return [os.fspath(d) for d in ckpt_dirs]


def elect_restore_step(gang_dir: str | os.PathLike, world: int,
                       ckpt_dirs=None, ranks=None,
                       transport=None) -> int | None:
    """The highest checkpoint step EVERY rank has verified (the
    intersection of all restore-point records), or None when no common
    step exists — the gang then starts from scratch / whatever the
    fallback chain finds.

    ``ckpt_dirs``: one shared checkpoint directory, or one per rank
    (per-host shard layouts).  When given, candidate steps are
    additionally filtered through the on-disk validity check
    (``validate_checkpoint``) in EVERY directory, so an
    agreed-but-since-corrupted checkpoint is never elected.

    ``ranks``: the ranks whose agreement matters (default: all of
    ``range(world)``).  The shrink-to-survivors path elects among the
    SURVIVORS only — a permanently lost rank can never verify anything
    again, and demanding its vote would strand the gang at step None
    forever.

    ``transport``: a ``runtime/transport.py::GangTransport`` to read
    the records through (the pluggable control plane); None keeps the
    historical direct-file read of ``gang_dir``.
    """
    gang_dir = os.fspath(gang_dir) if gang_dir is not None else None
    common: set[int] | None = None
    for rank in (range(world) if ranks is None else ranks):
        steps = (transport.read_restore_record(rank)
                 if transport is not None
                 else read_restore_record(gang_dir, rank))
        if steps is None:
            return None  # a rank with no record can't agree on anything
        common = steps if common is None else (common & steps)
    if not common:
        return None
    dirs = _as_dirs(ckpt_dirs)
    if not dirs:
        return max(common)
    from distributed_machine_learning_tpu.train.checkpoint import (
        validate_checkpoint,
    )

    # Highest-first with short-circuit: only the winner matters, and
    # validate_checkpoint is a full content hash — hashing every
    # commonly-recorded step in every rank dir would put
    # O(total checkpoint bytes x ranks) of read I/O on the restart
    # critical path for no better answer.
    for s in sorted(common, reverse=True):
        if all(not validate_checkpoint(os.path.join(d, f"step_{s}"))
               for d in dirs):
            return s
    return None


def enforce_restore_point(ckpt_dirs, step: int | None) -> list[str]:
    """Quarantine every complete checkpoint newer than the elected
    ``step`` (in each of ``ckpt_dirs``) so a relaunched gang's fallback
    chain resolves to the SAME restore point on every rank; returns the
    paths quarantined.  A newer checkpoint that not every rank verified
    may be torn on some host — restoring it would diverge the gang.
    ``step=None`` quarantines nothing (no agreement ⇒ the fallback
    chain decides)."""
    from distributed_machine_learning_tpu.train.checkpoint import (
        _is_complete,
        quarantine_checkpoint,
        quarantine_reason,
    )

    if step is None:
        return []
    quarantined = []
    for ckpt_dir in _as_dirs(ckpt_dirs):
        if not os.path.isdir(ckpt_dir):
            continue
        for name in os.listdir(ckpt_dir):
            if not (name.startswith("step_") and name[5:].isdigit()):
                continue
            s = int(name[5:])
            path = os.path.join(ckpt_dir, name)
            if s <= step or not _is_complete(path):
                continue
            if quarantine_reason(path) is not None:
                continue
            quarantine_checkpoint(
                path,
                f"gang restore-point election: step {s} is newer than "
                f"the agreed restore point {step}",
            )
            quarantined.append(path)
    return quarantined


class GangCoordinator:
    """One rank's view of the gang: writes its own heartbeat, watches
    every peer's, and aborts the process (loudly, via the shared latch)
    when a peer dies or stalls past ``peer_timeout_s``.

    Usage (one per worker process)::

        coord = GangCoordinator(gang_dir, rank=r, world=n,
                                peer_timeout_s=30).start()
        with coord.suspend():
            ...rendezvous / compile...
        for batch in batches:
            ...train step...
            coord.beat(step)
            ...checkpoint inside coord.suspend(); then
            coord.record_valid_step(step)...
        coord.stop()

    ``on_abort``: test hook replacing ``os._exit`` (receives the
    reason); production leaves it None — a hung collective can only be
    escaped by process death, which is exactly what the gang supervisor
    expects.  ``check_self=True`` also self-declares when this rank's
    own progress stalls past the timeout (the stalled rank usually
    notices first: its monitor thread keeps running while the main
    thread sleeps/hangs).

    ``transport`` (ISSUE 12): a ``runtime/transport.py::GangTransport``
    carrying every channel above; None builds the historical file
    backend over ``gang_dir`` (byte-identical layout).  With a lossy
    transport (TCP), a persistent ``TransportError`` streak longer
    than ``peer_timeout_s`` is treated as THIS rank being partitioned
    off the gang — peer death seen from the inside — and aborts the
    process just like a dead peer would.
    """

    def __init__(self, gang_dir: str | os.PathLike | None, rank: int,
                 world: int,
                 *, heartbeat_interval_s: float = 1.0,
                 peer_timeout_s: float = 30.0,
                 exit_code: int = GANG_ABORT_EXIT,
                 events=None, check_self: bool = True, on_abort=None,
                 metrics_window: int = 8, transport=None):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if not 0 <= rank < world:
            raise ValueError(f"rank must be in [0, {world}), got {rank}")
        if heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, got "
                f"{heartbeat_interval_s}"
            )
        if peer_timeout_s <= 2 * heartbeat_interval_s:
            raise ValueError(
                f"peer_timeout_s ({peer_timeout_s}) must exceed two "
                f"heartbeat intervals ({heartbeat_interval_s} each): a "
                "single delayed write would otherwise read as a death"
            )
        if gang_dir is None and transport is None:
            raise ValueError("a coordinator needs gang_dir or transport")
        self.gang_dir = os.fspath(gang_dir) if gang_dir is not None \
            else None
        if transport is None:
            from distributed_machine_learning_tpu.runtime.transport import (
                FileTransport,
            )

            transport = FileTransport(self.gang_dir, events=events)
        elif self.gang_dir is not None:
            os.makedirs(self.gang_dir, exist_ok=True)
        self.transport = transport
        self.rank = rank
        self.world = world
        self.heartbeat_interval_s = heartbeat_interval_s
        self.peer_timeout_s = peer_timeout_s
        self.exit_code = exit_code
        self.events = events
        self.check_self = check_self
        self.on_abort = on_abort
        self.aborted: str | None = None  # reason, once declared/observed
        self._seq = 0
        self._step = 0
        self._done = False
        self._suspended = 0
        self.suspensions = 0
        self._last_beat = time.monotonic()
        self._valid_steps: set[int] = set()
        if metrics_window < 1:
            raise ValueError(
                f"metrics_window must be >= 1, got {metrics_window}"
            )
        # The heartbeat metric snapshot (ISSUE 6): liveness and
        # progress travel on the same channel, so the supervisor's
        # straggler detector needs no second file family.  Appends are
        # GIL-atomic; the monitor thread reads a list() copy.
        self._step_times: collections.deque[float] = collections.deque(
            maxlen=metrics_window
        )
        self._phases: dict = {}
        # Digital-twin flag (ISSUE 20): when the harness reports
        # MODELED step times through ``observe_step`` (virtual
        # seconds, not this thread's wall time), beats mark their
        # metrics ``modeled`` so the supervisor's sampler judges the
        # model's clock only — wall-clock progress age is meaningless
        # when 512 thread-ranks share one core.  Liveness is
        # unaffected: heartbeats ride the real clock either way.
        self.modeled_time = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._write_lock = threading.Lock()
        # peer -> (beat signature, monotonic time this monitor first
        # saw that signature) — the skew-free staleness basis.  The
        # signature is transport-opaque (file: (mtime_ns, size); hub: a
        # version counter).
        self._peer_seen: dict[int, tuple[object, float]] = {}
        self._started_at = time.monotonic()
        # Monotonic instant the transport started failing (None =
        # healthy): the partition-is-peer-death escalation clock.
        self._tx_down_since: float | None = None

    # -- liveness/progress surface --------------------------------------
    def beat(self, step: int | None = None) -> None:
        """Record training progress — call once per completed step.
        In-memory only (no IO on the step path); the monitor thread
        publishes it at the heartbeat interval."""
        self._last_beat = time.monotonic()
        if step is not None:
            self._step = int(step)

    def observe_step(self, step: int, step_time_s: float,
                     phases: dict | None = None) -> None:
        """Record one completed step's wall time (and optional
        per-phase breakdown, ``{"barrier_wait_s": ..., ...}``) and
        beat.  The rolling mean over the last ``metrics_window`` steps
        rides every heartbeat as a compact metric snapshot — the
        signal the gang supervisor's straggler detector compares
        across ranks without touching any rank's metrics stream."""
        self._step_times.append(float(step_time_s))
        if phases:
            self._phases = {str(k): float(v) for k, v in phases.items()}
        self.beat(step)

    @contextlib.contextmanager
    def suspend(self):
        """Mark an expected-long non-step phase (checkpoint save, eval,
        compile, rendezvous): peers keep checking that this process is
        ALIVE (the heartbeat file keeps refreshing) but stop judging its
        progress age.  Re-entrant; beats on exit.  ``suspensions``
        counts entries monotonically, so interval-based step timers
        (``cli/common.py``'s stop-predicate deltas) can tell a pure
        step apart from one whose interval swallowed an eval or save."""
        self.suspensions += 1
        self._suspended += 1
        try:
            yield
        finally:
            try:
                self.beat()
            finally:
                self._suspended -= 1

    def peer_state(self, peer: int) -> dict | None:
        """The peer's latest heartbeat payload, or None (never wrote /
        torn write)."""
        from distributed_machine_learning_tpu.runtime.transport import (
            TransportError,
        )

        try:
            entry = self.transport.read_beat(peer)
        except TransportError:
            return None
        return entry[1] if entry is not None else None

    def wait_for_peers(self, step: int, poll_s: float | None = None,
                       stop=None) -> bool:
        """Block until every peer's published step reaches ``step`` (or
        the peer finished its run) — a lock-step barrier over the beat
        directory.

        This is the harness's stand-in for a synchronous collective
        where real cross-process collectives are unavailable (the CI
        host's CPU backend): it hangs exactly when a collective would —
        a dead or stalled peer never publishes the step — and is freed
        the same way: the monitor thread declares the peer and aborts
        this process.  Deliberately does NOT suspend the stall clock:
        time spent starved at the barrier is exactly what the detector
        must judge.  Returns False only in test mode (``on_abort`` set)
        once an abort was observed; production never returns False
        (the abort exits the process).

        ``poll_s`` defaults to the transport's barrier cadence; the
        read is BATCHED (one ``read_beats`` per poll for the whole
        gang, not one per peer — at world 128 over TCP the difference
        is the rank-0 host's life).  ``stop``: optional zero-arg
        predicate; True releases the barrier with False (the in-proc
        drain path — a thread cannot be SIGTERMed out of a wait)."""
        from distributed_machine_learning_tpu.runtime.transport import (
            TransportError,
        )

        if poll_s is None:
            poll_s = self.transport.barrier_poll_s()
        # Pod-scale seam: a transport may expose ``barrier_ready`` — a
        # single-pass, copy-free readiness probe.  The generic path
        # below snapshots the whole beat table per poll, which at 512
        # thread-ranks costs ~150µs × world pollers and saturates the
        # CI core; the in-proc fast path is what keeps the digital-twin
        # campaigns in tier-1 time.
        ready_fn = getattr(self.transport, "barrier_ready", None)
        while True:
            if self.aborted is not None:
                return False
            if stop is not None and stop():
                return False
            if ready_fn is not None:
                try:
                    ready = ready_fn(step, self.rank, self.world)
                except TransportError:
                    ready = False
            else:
                try:
                    beats = self.transport.read_beat_payloads()
                except TransportError:
                    beats = {}  # the monitor escalates a persistent outage
                ready = True
                for peer in range(self.world):
                    if peer == self.rank:
                        continue
                    payload = beats.get(peer)
                    if payload is None or (
                            not payload.get("done")
                            and int(payload.get("step", -1)) < step):
                        ready = False
                        break
            if ready:
                return True
            time.sleep(poll_s)

    def finish(self) -> None:
        """Publish clean completion and stop the monitor: a rank that
        finished its run must read as healthy forever (its heartbeat
        file will never refresh again), not as a death to declare."""
        self._done = True
        self._write_beat()
        self.stop()

    def record_valid_step(self, step: int) -> None:
        """Publish that this rank verified checkpoint ``step`` (its save
        returned, or it restored from it) — the rank's half of the
        restore-point election.  Written through the beat directory
        immediately: the record must survive this process dying at any
        later moment.

        MERGES with the record already on disk: a relaunched process
        starts with an empty in-memory set, and overwriting would drop
        the previously agreed steps from this rank's record — the
        election would then lose its only common point the moment any
        rank saved once after a restart."""
        self._valid_steps.add(int(step))
        _sched_point("coord:restore:rmw")
        prior = self.transport.read_restore_record(self.rank)
        if prior:
            self._valid_steps |= prior
        self.transport.write_restore_record(
            self.rank, sorted(self._valid_steps))

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "GangCoordinator":
        if self._thread is not None:
            raise RuntimeError("coordinator already started")
        if self.gang_dir is not None:
            os.makedirs(self.gang_dir, exist_ok=True)
        self._started_at = time.monotonic()
        self._last_beat = time.monotonic()
        self._write_beat()
        self._thread = threading.Thread(
            target=self._run, name=f"gang-coordinator-r{self.rank}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "GangCoordinator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals -------------------------------------------------------
    def _write_beat(self) -> None:
        _sched_point("coord:beat:w")
        with self._write_lock:
            self._write_beat_locked()

    def _write_beat_locked(self) -> None:
        now = time.monotonic()
        self._seq += 1
        payload = {
            "rank": self.rank,
            "seq": self._seq,
            "step": self._step,
            "beat_age": now - self._last_beat,
            "suspended": bool(self._suspended),
            "done": self._done,
            "time": time.time(),
        }
        times = list(self._step_times)
        if times:
            payload["metrics"] = {
                "step_time_s": sum(times) / len(times),
                "last_step_time_s": times[-1],
                "steps_timed": len(times),
                "phases": self._phases,
            }
            if self.modeled_time:
                payload["metrics"]["modeled"] = True
        from distributed_machine_learning_tpu.runtime.transport import (
            TransportError,
        )

        try:
            self.transport.publish_beat(self.rank, payload)
        except TransportError:
            # A failed publish is transport-outage evidence, not a
            # crash: the monitor loop escalates once the outage
            # outlives peer_timeout_s.
            self._note_transport(ok=False)
        # A SUCCESSFUL publish deliberately does NOT reset the outage
        # clock: on a half-open link (tiny beat writes succeed, the
        # ~world-sized batched reads keep timing out) a rank that can
        # publish but cannot observe the gang is still blind — it can
        # neither join an abort nor judge peers, and must escalate on
        # the READ path's schedule.  Only _run's successful read cycle
        # resets.

    def _note_transport(self, ok: bool) -> None:
        if ok:
            self._tx_down_since = None
        elif self._tx_down_since is None:
            self._tx_down_since = time.monotonic()

    def _telemetry(self):
        from distributed_machine_learning_tpu.telemetry import get_telemetry

        return get_telemetry()

    def _abort(self, reason: str, peer: int | None = None) -> None:
        """Declare (or join) the gang abort and kill this process."""
        from distributed_machine_learning_tpu.runtime.transport import (
            TransportError,
        )

        try:
            won = self.transport.declare_abort(reason, self.rank,
                                               peer=peer)
        except TransportError:
            # Partitioned off the gang: the latch is unreachable, but
            # this rank must still die loudly — the peers' detectors
            # will read its silence as the death it is.
            won = False
        self.aborted = reason
        if won and self.events is not None and peer is not None:
            self.events.peer_failures += 1
        tel = self._telemetry()
        if tel is not None:
            if won:
                tel.registry.counter("gang_peer_failures").inc()
            tel.tracer.instant("gang_abort", reason=reason)
            tel.flush()
        print(
            f"[gang] rank {self.rank} aborting: {reason} "
            f"(exit {self.exit_code})",
            flush=True,
        )
        if self.on_abort is not None:
            self.on_abort(reason)
            return
        os._exit(self.exit_code)

    def _check_peer(self, peer: int, entry, now: float, tel
                    ) -> str | None:
        """None if the peer looks healthy, else the failure reason.
        ``entry`` is the peer's ``(signature, payload)`` from this
        poll's batched ``read_beats`` (None: never published).

        Staleness is judged by LOCALLY-OBSERVED change (when did THIS
        monitor last see the peer's beat signature advance, on this
        host's monotonic clock), never by comparing wall clocks to
        filesystem mtimes: on the shared mounts pods actually use,
        cross-host clock/mtime skew of a minute is routine and would
        otherwise read as instant death (or mask a real one)."""
        if entry is None:
            # Never beat at all: allow a full timeout from gang start
            # (the peer may still be exec'ing / rendezvousing).
            if now - self._started_at > self.peer_timeout_s:
                return (f"rank {peer} never wrote a heartbeat within "
                        f"{self.peer_timeout_s}s of gang start")
            return None
        sig, payload = entry
        seen = self._peer_seen.get(peer)
        if seen is None or seen[0] != sig:
            self._peer_seen[peer] = (sig, now)
            file_age = 0.0
        else:
            file_age = now - seen[1]
        if payload is not None and payload.get("done"):
            return None  # finished cleanly: healthy forever (file frozen)
        if file_age > self.peer_timeout_s:
            return (f"rank {peer} heartbeat last changed "
                    f"{file_age:.1f}s ago (timeout {self.peer_timeout_s}s)"
                    ": process dead")
        if payload is None or payload.get("suspended"):
            return None
        progress_age = file_age + float(payload.get("beat_age", 0.0))
        if tel is not None:
            tel.registry.gauge(
                "gang_heartbeat_age_s", rank=str(peer)
            ).set(progress_age)
        # Stalls are judged at 1.5x the death timeout: when one rank
        # dies, every survivor blocked on it is ALSO progress-starved —
        # the extra half-window lets the true cause (the dead peer's
        # stale file) win the declaration race, so the abort reason
        # names the victim, not a symptom.
        if progress_age > 1.5 * self.peer_timeout_s:
            return (f"rank {peer} made no step progress for "
                    f"{progress_age:.1f}s (stall timeout "
                    f"{1.5 * self.peer_timeout_s:.1f}s): stalled (hung "
                    "collective or wedged input)")
        return None

    def _run(self) -> None:
        from distributed_machine_learning_tpu.runtime.transport import (
            TransportError,
        )

        # Poll cadence is a TRANSPORT property (ISSUE 12): file keeps
        # the historical min(heartbeat, timeout/4); in-proc polls
        # tightly (reads are dict lookups); TCP scales the interval
        # with the world so 128 monitors cannot self-DoS rank 0.
        poll_s = self.transport.monitor_poll_s(
            self.heartbeat_interval_s, self.peer_timeout_s, self.world)
        while not self._stop.wait(poll_s):
            self._write_beat()
            now = time.monotonic()
            try:
                abort = self.transport.read_abort()
                beats = self.transport.read_beats() if abort is None \
                    else {}
            except TransportError:
                # Connection loss IS peer-death evidence — for THIS
                # rank: a member that cannot reach the gang for a full
                # peer timeout is partitioned off it, and its peers are
                # already reading its silence as a death.
                self._note_transport(ok=False)
                if now - self._tx_down_since > self.peer_timeout_s:
                    self._abort(
                        f"rank {self.rank} lost the gang transport for "
                        f"{now - self._tx_down_since:.1f}s (timeout "
                        f"{self.peer_timeout_s}s): partitioned off the "
                        "gang", peer=self.rank,
                    )
                    return
                continue
            self._note_transport(ok=True)
            if abort is not None:
                self._abort(
                    f"joining gang abort declared by rank "
                    f"{abort.get('by_rank')}: {abort.get('reason')}"
                )
                return
            tel = self._telemetry()
            if (self.check_self and not self._suspended
                    and now - self._last_beat > 1.5 * self.peer_timeout_s):
                self._abort(
                    f"rank {self.rank} (self) made no step progress for "
                    f"{now - self._last_beat:.1f}s "
                    f"(stall timeout {1.5 * self.peer_timeout_s:.1f}s)",
                    peer=self.rank,
                )
                return
            for peer in range(self.world):
                if peer == self.rank:
                    continue
                reason = self._check_peer(peer, beats.get(peer), now,
                                          tel)
                if reason is not None:
                    self._abort(reason, peer=peer)
                    return
