# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/netmodel_pacer.py
"""DML016 firing cases: real clocks and sleeps leaking into a
virtual-clock (digital twin) module — each one re-couples the modeled
trajectory to host scheduling and breaks deterministic replay."""
import time
from time import sleep as snooze
from datetime import datetime


def settle_link(nm, src, dst, nbytes):
    time.sleep(0.05)                      # real sleep inside the twin
    return nm.link_time(src, dst, nbytes)


def stamp_modeled_step(nm, rank):
    t0 = time.perf_counter()              # real clock read
    dt = nm.step_time(rank)
    nm.clock.advance(dt)
    return t0, dt


def paced_rounds(nm, rounds):
    out = []
    for _ in range(rounds):
        snooze(0.01)                      # aliased `from time import sleep`
        out.append(nm.clock.now())
    return out


def wall_stamp_row(row):
    row["at"] = datetime.now().isoformat()   # wall clock in twin state
    return row
