"""2-D topology layer over the ppermute ring — compressed multi-hop
all-reduce with per-axis wire accounting (round 11).

The flat ring (``ops/ring.py``) treats every hop as equally expensive;
on a real pod the links are NOT uniform — intra-node (ICI/NVLink-class)
hops are cheap and inter-node (DCN-class) hops are the bottleneck.
DynamiQ (PAPERS.md, arxiv 2602.08923) frames the win as *compressed
multi-hop* all-reduce over the hierarchy; this module is that layer:

- :class:`Topology` — the descriptor: ``inner`` (fast-axis / intra-node
  world) × ``outer`` (slow-axis / inter-node world) with a per-axis
  :class:`~distributed_machine_learning_tpu.ops.ring.WireScheme`.  Ranks
  are inner-major: node ``o`` owns the contiguous block
  ``[o·inner, (o+1)·inner)``, so an inner hop stays inside a block and
  an outer hop jumps between blocks at stride ``inner``.
- :func:`hierarchical_all_reduce_flat` — the three-phase plan:
  (1) reduce-scatter on the fast inner axis (``inner−1`` hops), leaving
  each rank the NODE-sum of one 1/inner chunk; (2) a compressed ring
  all-reduce (reusing the round-7 codec + error-feedback machinery of
  ``ring_all_reduce_flat`` verbatim, via its ``perm``/``ring_rank``
  sub-ring form) on the slow outer axis over that 1/inner of the data —
  the inter-node traffic drops to ~1/inner of the flat ring's; (3)
  all-gather back down the inner axis.  Lossy codecs keep every rank's
  output BIT-IDENTICAL (encoded payloads are relayed verbatim, the
  flat ring's replication invariant), and the per-axis residuals still
  sum to the all-reduce's total compression error (see the residual
  contract below).
- :func:`halving_doubling_all_reduce_flat` — recursive halving +
  doubling for latency-bound small buckets: the same 2·(N−1)/N bytes
  as the ring but only ``2·log2(N)`` serial hops (the ring's
  ``2·(N−1)``), the classic latency-optimal exchange.
- ``Topology.select(bucket_bytes)`` — the per-bucket auto-selector the
  bucketed ``ring_all_reduce(topology=...)`` dispatches through.

**Residual contract (per-axis error feedback).**  The flat ring's EF
invariant is: summed over ranks, the residuals equal N × (exact mean −
output) — so reducing ``grad + residual`` next step recovers everything
the wire dropped.  The hierarchical plan preserves it per axis:

- an inner reduce-scatter hop's sender keeps ``v − decode(encode(v))``
  (the mass that encode drops from its node-sum, hence from the total
  sum — sum units, counted once);
- the outer sub-ring runs with SUM semantics and its own EF bookkeeping
  (``ring_all_reduce_flat(return_residual=True)``), so the residuals it
  hands back already sum to the outer phase's total drop in sum units;
- the inner all-gather encodes the finished (meaned) chunk ONCE per
  node; the chunk's owner in each node keeps ``inner × (own −
  decode(encode(own)))`` — there are ``outer`` such owners holding the
  identical gap (the encode is deterministic over bit-identical
  inputs), so the gaps total ``N × gap``, exactly the broadcast loss in
  the sum-unit convention.

Summing every rank's residual therefore still equals N × (exact mean −
output) — asserted to 1e-4 in ``tests/test_topology.py`` for codecs on
either axis or both.
"""

from __future__ import annotations

import dataclasses
import re

import jax
import jax.numpy as jnp
from jax import lax

from distributed_machine_learning_tpu.ops.ring import (
    CODEC_IMPLS,
    WIRE_SCHEMES,
    WireScheme,
    _bucket_bounds,
    get_wire_scheme,
    ring_all_reduce_flat,
)

#: When a lossy codec was requested, halving-doubling (which is exact
#: and would silently discard the codec) only takes buckets at or
#: under this size — the regime where per-chunk codec metadata and
#: encode compute rival the payload itself.  This is a FIDELITY bound,
#: not a performance threshold: the cost model below decides perf, but
#: silently rerouting a requested codec onto an exact plan is only
#: defensible where the codec could not have paid for itself anyway.
HD_LOSSY_MAX_BYTES = 4 * 1024


@dataclasses.dataclass(frozen=True)
class LinkModel:
    """Per-axis link cost model (round 20): the digital twin's notion
    of what one ``ppermute`` costs on a pod.

    Wormhole/cut-through routing semantics: a permute at ring distance
    ``d`` on an axis pays the axis's per-message **overhead once** (the
    header cuts through intermediate switches without store-and-forward
    buffering) but its **payload occupies d links** of that axis's ring
    — the congestion/bandwidth term scales with distance while the
    latency term does not.  That asymmetry is what gives every
    topology×scheme cell a genuine flat/hier/hd crossover: hd spends
    fewer serial overheads than hier but its long-distance exchanges
    multiply bytes across links, so hd wins small buckets and hier wins
    large ones (2x4 exact: the crossover sits at
    ``8·outer_overhead_s·outer_bytes_per_s`` = 1 MiB-ish under the
    defaults; 4x2 exact: ``4·inner_overhead_s·inner_bytes_per_s``).

    Defaults are ICI-class intra-node links (~1 µs, 100 GB/s) and
    DCN-class inter-node links (~5 µs, 25 GB/s) — the fast/slow axis
    split the :class:`Topology` descriptor declares.  Calibration:
    ``tests/test_netmodel.py`` pins the model's per-axis bytes to the
    static ``topology_wire_bytes`` accounting (itself pinned to the
    compiled HLO by DML103) and its plan ordering to the measured
    ``BENCH_r11_hier.json`` cells.
    """

    inner_overhead_s: float = 1.0e-6
    inner_bytes_per_s: float = 100.0e9
    outer_overhead_s: float = 5.0e-6
    outer_bytes_per_s: float = 25.0e9

    def permute_time(self, axis: str, distance: int, nbytes: int) -> float:
        """Modeled seconds for one permute: overhead once, bytes across
        ``distance`` links of the axis ring."""
        if axis == "inner":
            return (self.inner_overhead_s
                    + distance * nbytes / self.inner_bytes_per_s)
        return (self.outer_overhead_s
                + distance * nbytes / self.outer_bytes_per_s)


DEFAULT_LINK_MODEL = LinkModel()

_TOPOLOGY_RE = re.compile(r"^\s*(\d+)\s*[x×X]\s*(\d+)\s*$")


def parse_topology(spec: str) -> tuple[int, int]:
    """``"2x4"`` (also ``2×4``) → ``(inner, outer)``; raises ValueError
    on anything else — the parse-time half of ``--ring-topology``
    validation (the world-equality half needs the mesh and lives in
    ``RingAllReduce.topology_for``)."""
    m = _TOPOLOGY_RE.match(spec or "")
    if not m:
        raise ValueError(
            f"topology spec {spec!r} is not of the form INNERxOUTER "
            "(e.g. '2x4': inner=intra-node world, outer=inter-node world)"
        )
    inner, outer = int(m.group(1)), int(m.group(2))
    if inner < 1 or outer < 1:
        raise ValueError(
            f"topology axes must be >= 1, got {inner}x{outer}"
        )
    return inner, outer


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


@dataclasses.dataclass(frozen=True)
class Topology:
    """inner×outer factorization of the mesh's data axis, with a wire
    scheme per axis.

    ``inner``: the fast-axis world (chips sharing a node's cheap
    links); ``outer``: the slow-axis world (nodes).  ``inner_scheme`` /
    ``outer_scheme`` name the per-axis codecs (``ops.ring.WIRE_SCHEMES``)
    — the CLI maps ``--ring-compress`` onto the OUTER axis (compress
    where the wire is expensive) and leaves the inner axis exact, but
    the descriptor supports compressing either or both.
    ``hd_max_bytes`` (round 20): an OPTIONAL admissibility cap on the
    halving-doubling plan — ``None`` (default) lets the cost model
    decide, ``0`` disables hd entirely, and a positive value admits hd
    only at or under that many bytes; the lossy fidelity bound
    :data:`HD_LOSSY_MAX_BYTES` is applied on top in every case.
    ``codec_impl`` (round 13): the int8 codec implementation both axes
    resolve — ``"pallas"`` runs the fused in-register kernels
    (``ops/pallas/ring_codec.py``), bitwise-identical to ``"xla"``.
    """

    inner: int
    outer: int
    inner_scheme: str = "none"
    outer_scheme: str = "none"
    topk_frac: float = 0.125
    hd_max_bytes: int | None = None
    codec_impl: str = "xla"

    def __post_init__(self):
        if self.inner < 1 or self.outer < 1:
            raise ValueError(
                f"topology axes must be >= 1, got "
                f"{self.inner}x{self.outer}"
            )
        for name in (self.inner_scheme, self.outer_scheme):
            if name not in WIRE_SCHEMES:
                raise ValueError(
                    f"unknown wire scheme {name!r}; choose from "
                    f"{WIRE_SCHEMES}"
                )
        if self.codec_impl not in CODEC_IMPLS:
            raise ValueError(
                f"unknown codec impl {self.codec_impl!r}; choose from "
                f"{CODEC_IMPLS}"
            )

    @property
    def world(self) -> int:
        return self.inner * self.outer

    # -- per-axis codecs ------------------------------------------------

    def axis_scheme(self, axis: str) -> WireScheme:
        name = self.inner_scheme if axis == "inner" else self.outer_scheme
        return get_wire_scheme(name, topk_frac=self.topk_frac,
                               codec_impl=self.codec_impl)

    def _scheme_or_none(self, axis: str) -> WireScheme | None:
        s = self.axis_scheme(axis)
        return None if s.name == "none" else s

    def _flat_axis(self) -> str:
        """Which axis a FLAT whole-world ring's traffic rides: with one
        node (outer==1) every hop is intra-node; otherwise the ring
        crosses node boundaries and its bytes are charged to the
        bottleneck inter-node links (see ``classify_permute_pairs``)."""
        return "inner" if self.outer == 1 else "outer"

    # -- selector (round 20: prediction-driven, no byte threshold) ------

    def _hd_admissible(self, bucket_bytes: int) -> bool:
        """Whether halving-doubling may even be CONSIDERED for this
        bucket — correctness/fidelity gates, not performance (the cost
        model owns performance): pairwise exchange needs a power-of-two
        world; when a lossy codec was requested, hd (which is exact and
        would silently discard it) is only admissible at or under
        :data:`HD_LOSSY_MAX_BYTES`; an explicit ``hd_max_bytes`` caps
        it further (``0`` disables hd outright)."""
        if not (_is_pow2(self.world) and self.world >= 4):
            return False
        cap = self.hd_max_bytes
        if self.inner_scheme != "none" or self.outer_scheme != "none":
            cap = (HD_LOSSY_MAX_BYTES if cap is None
                   else min(cap, HD_LOSSY_MAX_BYTES))
        return cap is None or bucket_bytes <= cap

    def plan_hops(
        self, bucket_bytes: int, plan: str, itemsize: int = 4,
    ) -> list[tuple[str, int, int]]:
        """The serial hop schedule of one bucket under ``plan``: a list
        of ``(axis, distance, payload_bytes)``, one entry per
        ``ppermute`` on the program's critical path.

        The per-axis payload accounting is EXACTLY
        :func:`topology_wire_bytes` re-expressed hop-by-hop (asserted
        in ``tests/test_netmodel.py``), so the cost model prices the
        same bytes the HLO audit counts; ``distance`` is the axis-ring
        distance the payload travels (1 for ring hops, ``2**s`` scaled
        into node units for the hd exchanges — the congestion input of
        :meth:`LinkModel.permute_time`).
        """
        n = self.world
        blen = -(-bucket_bytes // itemsize)
        hops: list[tuple[str, int, int]] = []
        if n <= 1 or blen <= 0:
            return hops
        if plan == "flat":
            chunk = -(-blen // n)
            axis = self._flat_axis()
            pb = self.axis_scheme(axis).payload_bytes(chunk, itemsize)
            hops.extend([(axis, 1, pb)] * (2 * (n - 1)))
        elif plan == "hd":
            chunk = -(-blen // n)
            for s in range(n.bit_length() - 1):
                d = 1 << s
                # An exchange at rank distance d stays inside a block
                # when d < inner (power-of-two factors nest), else it
                # jumps d/inner nodes — the same block arithmetic as
                # classify_permute_pairs, with the distance kept.
                axis, dist = (("inner", d) if d < self.inner
                              else ("outer", d // self.inner))
                pb = (n >> (s + 1)) * chunk * itemsize
                hops.extend([(axis, dist, pb)] * 2)
        elif plan == "hier":
            chunk_i = -(-blen // self.inner)
            chunk_o = -(-chunk_i // self.outer)
            pb_i = self.axis_scheme("inner").payload_bytes(
                chunk_i, itemsize)
            pb_o = self.axis_scheme("outer").payload_bytes(
                chunk_o, itemsize)
            hops.extend([("inner", 1, pb_i)] * (2 * (self.inner - 1)))
            hops.extend([("outer", 1, pb_o)] * (2 * (self.outer - 1)))
        else:
            raise ValueError(f"unknown plan {plan!r}")
        return hops

    def predict_bucket_time(
        self,
        bucket_bytes: int,
        plan: str | None = None,
        link: LinkModel | None = None,
        itemsize: int = 4,
    ) -> float:
        """Modeled seconds for one bucket's all-reduce under ``plan``
        (default: whatever :meth:`select` picks under the same link
        model) — the sum of the hop schedule through the link model."""
        link = link or DEFAULT_LINK_MODEL
        if plan is None:
            plan = self.select(bucket_bytes, link=link)
        return sum(
            link.permute_time(axis, dist, pb)
            for axis, dist, pb in self.plan_hops(bucket_bytes, plan,
                                                 itemsize)
        )

    def select(self, bucket_bytes: int,
               link: LinkModel | None = None) -> str:
        """Pick the plan for one bucket: ``"flat"`` / ``"hier"`` /
        ``"hd"`` — by PREDICTED hop time under the link model (round
        20), not a hard-coded byte threshold.

        - a degenerate axis (inner==1 or outer==1) means there is no
          hierarchy to exploit: the flat ring, with the live axis's
          scheme, for EVERY bucket size — bit-for-bit the round-7
          program, never a crash and never a silent reroute (the
          ``--ring-topology 1xN`` contract);
        - otherwise every admissible plan is priced through
          :meth:`plan_hops` × :class:`LinkModel` and the cheapest wins.
          Under the default pod parameters that reproduces the old
          policy's *shape* from first principles: hd (fewest serial
          overheads) takes small buckets, hier (1/inner the inter-node
          bytes) takes large ones, and the crossover now moves with
          the topology and link speeds instead of sitting at a frozen
          64 KiB.  hd admissibility (:meth:`_hd_admissible`) stays a
          correctness/fidelity gate: power-of-two worlds only, lossy
          codecs never silently discarded above
          :data:`HD_LOSSY_MAX_BYTES`, ``hd_max_bytes=0`` still
          disables the plan.  Ties go to ``hier`` (keeps the codec).
        """
        if self.world == 1 or self.inner == 1 or self.outer == 1:
            # Degenerate axis FIRST: the documented contract is that a
            # 1-sized axis IS the flat ring, bit-for-bit the round-7
            # program — routing its small buckets to hd would change
            # the association order (and could discard a codec) behind
            # the user's declared no-hierarchy topology.
            return "flat"
        link = link or DEFAULT_LINK_MODEL
        candidates = ["hier"]
        if self._hd_admissible(bucket_bytes):
            candidates.append("hd")
        candidates.append("flat")
        best, best_t = None, None
        for plan in candidates:
            t = self.predict_bucket_time(bucket_bytes, plan, link=link)
            if best_t is None or t < best_t:
                best, best_t = plan, t
        return best

    # -- static permutation tables (one entry per physical rank; the
    #    disjoint sub-rings all move in a single ppermute) --------------

    def inner_perm(self) -> list[tuple[int, int]]:
        """Right-shift ring inside every inner block."""
        return [
            (o * self.inner + i, o * self.inner + (i + 1) % self.inner)
            for o in range(self.outer)
            for i in range(self.inner)
        ]

    def outer_perm(self) -> list[tuple[int, int]]:
        """Right-shift ring across blocks at stride ``inner``, one ring
        per inner position."""
        return [
            (o * self.inner + i,
             ((o + 1) % self.outer) * self.inner + i)
            for o in range(self.outer)
            for i in range(self.inner)
        ]

    def hd_perm(self, step: int) -> list[tuple[int, int]]:
        """Pairwise exchange at rank distance ``2**step``."""
        return [(r, r ^ (1 << step)) for r in range(self.world)]


def hierarchical_all_reduce_flat(
    x: jax.Array,
    axis_name: str,
    topo: Topology,
    mean: bool = True,
    return_residual: bool = False,
):
    """Hierarchical all-reduce of a flat vector inside ``shard_map``.

    Reduce-scatter on the inner axis → compressed ring on the outer
    axis over 1/inner of the data → all-gather down the inner axis.
    Requires ``inner > 1`` and ``outer > 1`` (degenerate axes are
    dispatched to the flat ring by ``topology_all_reduce_flat``).

    Every rank ends with IDENTICAL bits (lossy encodes are relayed
    verbatim and decoded everywhere, including by their producer), and
    with ``return_residual`` the per-axis EF residuals sum — over all
    N ranks — to N × (exact mean − output): the module docstring's
    residual contract.
    """
    inner, outer = topo.inner, topo.outer
    n = topo.world
    assert inner > 1 and outer > 1, "degenerate topology must go flat"
    inner_scheme = topo._scheme_or_none("inner")
    outer_scheme = topo._scheme_or_none("outer")
    perm_inner = topo.inner_perm()

    rank = lax.axis_index(axis_name)
    inner_idx = rank % inner
    outer_idx = rank // inner

    orig_len = x.shape[0]
    chunk = -(-orig_len // inner)
    chunks = jnp.pad(x, (0, inner * chunk - orig_len)).reshape(inner, chunk)

    def hop(payload):
        return tuple(
            lax.ppermute(p, axis_name, perm_inner) for p in payload
        )

    # Phase 1 — inner reduce-scatter (same roll-by-rank trick as the
    # flat ring, over the inner sub-ring): after inner−1 hops this rank
    # holds the NODE-sum of global inner-chunk (inner_idx+1) mod inner,
    # at local row 1.
    chunks = jnp.roll(chunks, -inner_idx, axis=0)
    account = return_residual and (
        inner_scheme is not None or outer_scheme is not None
    )
    res_rows = jnp.zeros_like(chunks) if account else None
    for s in range(inner - 1):
        send_row = (-s) % inner
        recv_row = (-s - 1) % inner
        v = chunks[send_row]
        if inner_scheme is None:
            recvd = lax.ppermute(v, axis_name, perm_inner)
            chunks = chunks.at[recv_row].add(recvd)
        else:
            # Routed through the scheme's fusion seams (round 13) like
            # the flat ring, so the fused int8 codec collapses each
            # piece to one in-register kernel on this axis too.
            if account:
                # Send error: mass this encode drops from the node-sum,
                # hence from the total sum — sum units, sender-observed,
                # once per hop (the flat ring's phase-1 bookkeeping).
                enc, err = inner_scheme.encode_with_residual(v)
                res_rows = res_rows.at[send_row].add(err)
            else:
                enc = inner_scheme.encode(v)
            chunks = chunks.at[recv_row].set(
                inner_scheme.decode_add(hop(enc), chunks[recv_row], chunk)
            )
    own = chunks[1 % inner]

    # Phase 2 — compressed ring all-reduce on the outer axis, SUM
    # semantics (one global mean division below keeps the accounting in
    # sum units throughout).  The round-7 codec + EF machinery runs
    # unchanged on the sub-ring via perm/ring_rank.
    outer_out = ring_all_reduce_flat(
        own,
        axis_name,
        outer,
        mean=False,
        scheme=outer_scheme,
        return_residual=account,
        perm=topo.outer_perm(),
        ring_rank=outer_idx,
    )
    if account:
        outer_out, outer_res = outer_out
    own_final = outer_out / n if mean else outer_out

    # Phase 3 — all-gather back down the inner axis: encode the
    # finished chunk ONCE, relay the payload bit-exactly, decode it on
    # every rank (owner included) — the replication invariant.
    out_rows = jnp.zeros_like(chunks)
    own_dec = own_final
    if inner_scheme is None:
        out_rows = out_rows.at[1 % inner].set(own_final)
        cur = own_final
        for s in range(inner - 1):
            cur = lax.ppermute(cur, axis_name, perm_inner)
            out_rows = out_rows.at[(-s) % inner].set(cur)
    else:
        payload = inner_scheme.encode(own_final)
        own_dec = inner_scheme.decode(payload, chunk).astype(x.dtype)
        out_rows = out_rows.at[1 % inner].set(own_dec)
        for s in range(inner - 1):
            payload = hop(payload)
            out_rows = out_rows.at[(-s) % inner].set(
                inner_scheme.decode(payload, chunk).astype(x.dtype)
            )
    result = jnp.roll(out_rows, inner_idx, axis=0).reshape(-1)[:orig_len]
    if not return_residual:
        return result
    if not account:
        return result, jnp.zeros_like(x)
    # Owner corrections on the owned row: the outer sub-ring's residual
    # (already sum units), plus the inner broadcast gap.  Each node's
    # owner holds the identical gap (deterministic encode of identical
    # bits), so the `outer` copies need a per-owner factor of
    # N/outer = inner under mean semantics (total = N × gap) and
    # 1/outer under sum semantics (total = gap).
    res_rows = res_rows.at[1 % inner].add(outer_res)
    gfactor = float(inner) if mean else 1.0 / outer
    res_rows = res_rows.at[1 % inner].add(gfactor * (own_final - own_dec))
    res = jnp.roll(res_rows, inner_idx, axis=0).reshape(-1)[:orig_len]
    return result, res


def halving_doubling_all_reduce_flat(
    x: jax.Array,
    axis_name: str,
    axis_size: int,
    mean: bool = True,
):
    """Recursive halving-doubling all-reduce (exact, power-of-two
    worlds): ``log2 N`` pairwise-exchange reduce-scatter steps at rank
    distances 1, 2, 4, …, then the mirror ``log2 N`` all-gather steps —
    the same 2·(N−1)/N per-device bytes as the ring in 2·log2 N serial
    hops instead of 2·(N−1), the latency-optimal exchange for small
    buckets.

    Every chunk's total is computed at its owning rank through one
    fixed reduction tree and broadcast verbatim, so all ranks end with
    IDENTICAL bits (and, the sum being a single association order, the
    result is deterministic across plans only up to float rounding —
    the selector never mixes plans within one bucket).
    """
    n = axis_size
    if n == 1:
        return x
    if not _is_pow2(n):
        raise ValueError(
            f"halving-doubling needs a power-of-two world, got {n}"
        )
    k = n.bit_length() - 1
    orig_len = x.shape[0]
    chunk = -(-orig_len // n)
    a = jnp.pad(x, (0, n * chunk - orig_len)).reshape(n, chunk)
    rank = lax.axis_index(axis_name)

    # Recursive halving (reduce-scatter).  Invariant entering step s:
    # `a` holds the partial sums of the chunks whose low s index bits
    # equal this rank's, row-indexed by the remaining high bits — so
    # row parity IS chunk bit s, and the rank-dependent "send the half
    # whose bit s differs from mine" is a traced select of two static
    # strided slices (the payload halves each step: the halving).
    for s in range(k):
        bit = ((rank >> s) & 1) == 1
        evens, odds = a[0::2], a[1::2]
        send = jnp.where(bit, evens, odds)
        keep = jnp.where(bit, odds, evens)
        recvd = lax.ppermute(
            send, axis_name, [(r, r ^ (1 << s)) for r in range(n)]
        )
        a = keep + recvd
    own = a[0]  # the chunk whose index == this rank, fully summed
    if mean:
        own = own / n

    # Recursive doubling (all-gather): unfix the bits in reverse order;
    # after the step at distance 2**s the array holds the chunks whose
    # low s bits match, row-indexed by chunk >> s — interleaving the
    # kept and received halves lands the final array in GLOBAL chunk
    # order with no repacking pass.
    b = own[None]
    for s in reversed(range(k)):
        recvd = lax.ppermute(
            b, axis_name, [(r, r ^ (1 << s)) for r in range(n)]
        )
        bit = ((rank >> s) & 1) == 1
        first = jnp.where(bit, recvd, b)   # chunks with bit s == 0
        second = jnp.where(bit, b, recvd)  # chunks with bit s == 1
        b = jnp.stack([first, second], axis=1).reshape(-1, chunk)
    return b.reshape(-1)[:orig_len]


def topology_all_reduce_flat(
    x: jax.Array,
    axis_name: str,
    topo: Topology,
    mean: bool = True,
    return_residual: bool = False,
    plan: str | None = None,
):
    """One bucket's all-reduce under a topology: dispatch through
    ``topo.select`` (or an explicit ``plan``) to flat / hier / hd.

    The flat fallback carries the live axis's wire scheme (a 1-sized
    axis degenerates to exactly the round-7 compressed ring); the hd
    path is exact, so its residual is identically zero.
    """
    plan = plan or topo.select(x.shape[0] * x.dtype.itemsize)
    if plan == "hier":
        return hierarchical_all_reduce_flat(
            x, axis_name, topo, mean=mean,
            return_residual=return_residual,
        )
    if plan == "hd":
        out = halving_doubling_all_reduce_flat(
            x, axis_name, topo.world, mean=mean
        )
        if return_residual:
            return out, jnp.zeros_like(x)
        return out
    return ring_all_reduce_flat(
        x,
        axis_name,
        topo.world,
        mean=mean,
        scheme=topo._scheme_or_none(topo._flat_axis()),
        return_residual=return_residual,
    )


# ---------------------------------------------------------------------------
# Static per-axis wire accounting.
# ---------------------------------------------------------------------------


def classify_permute_pairs(pairs, inner: int) -> str:
    """Attribute one permute's routing to a topology axis (round 11).

    Ranks are inner-major (see :class:`Topology`): node ``o`` is the
    contiguous block ``[o·inner, (o+1)·inner)``.  A permute whose every
    pair stays inside a block is intra-node (``"inner"``); one with ANY
    cross-block pair is charged to the inter-node links (``"outer"``) —
    bottleneck-rank accounting: the block-edge ranks of a flat ring
    push every hop's payload inter-node, so a mixed permute's bytes ARE
    outer-axis exposure.  The HLO walker
    (``bench.overlap_audit.wire_bytes_from_hlo``) classifies compiled
    ``source_target_pairs`` through this same function, so compiled and
    static attribution can never drift."""
    if any(s // inner != t // inner for s, t in pairs):
        return "outer"
    return "inner"


def topology_wire_bytes(
    n_elems: int,
    topo: Topology,
    bucket_bytes: int,
    itemsize: int = 4,
) -> dict[str, int]:
    """Per-device wire bytes of one bucketed topology all-reduce, split
    ``{"inner": ..., "outer": ...}`` by the link class each hop rides.

    Every hop is attributed through the SAME permutation-pair
    classifier the HLO audit applies to the compiled program's
    ``source_target_pairs`` (:func:`classify_permute_pairs`, which
    ``bench.overlap_audit.wire_bytes_from_hlo`` imports) — the static
    accounting and the executable attribution cannot chunk or classify
    differently.  Note
    the flat plan's bytes land on the OUTER axis whenever the ring
    crosses nodes: the bottleneck-link exposure is the honest number
    (the block-edge ranks push every hop inter-node), and it is exactly
    what the hierarchical plan divides by ``inner``.
    """
    out = {"inner": 0, "outer": 0}
    if n_elems <= 0 or topo.world <= 1:
        return out
    n = topo.world
    for start, stop in _bucket_bounds(n_elems, bucket_bytes, itemsize):
        blen = stop - start
        plan = topo.select(blen * itemsize)
        if plan == "flat":
            chunk = -(-blen // n)
            axis = classify_permute_pairs(
                [(r, (r + 1) % n) for r in range(n)], topo.inner
            )
            scheme = topo.axis_scheme(topo._flat_axis())
            out[axis] += 2 * (n - 1) * scheme.payload_bytes(chunk, itemsize)
        elif plan == "hd":
            chunk = -(-blen // n)
            k = n.bit_length() - 1
            for s in range(k):
                axis = classify_permute_pairs(topo.hd_perm(s), topo.inner)
                # The halving step at distance 2**s and its mirror
                # doubling step each move (n >> (s+1)) chunks.
                out[axis] += 2 * (n >> (s + 1)) * chunk * itemsize
        else:  # hier
            chunk_i = -(-blen // topo.inner)
            chunk_o = -(-chunk_i // topo.outer)
            si = topo.axis_scheme("inner")
            so = topo.axis_scheme("outer")
            # inner reduce-scatter + inner all-gather: (inner−1) hops
            # each, payload one inner chunk through the inner codec.
            axis = classify_permute_pairs(topo.inner_perm(), topo.inner)
            out[axis] += (
                2 * (topo.inner - 1) * si.payload_bytes(chunk_i, itemsize)
            )
            # outer compressed ring: 2·(outer−1) hops over 1/inner of
            # the data — the 1/inner_world inter-node reduction.
            axis = classify_permute_pairs(topo.outer_perm(), topo.inner)
            out[axis] += (
                2 * (topo.outer - 1) * so.payload_bytes(chunk_o, itemsize)
            )
    return out


def predict_all_reduce_time(
    n_elems: int,
    topo: Topology,
    bucket_bytes: int,
    link: LinkModel | None = None,
    itemsize: int = 4,
) -> float:
    """Modeled seconds for one FULL bucketed all-reduce (round 20):
    every bucket priced under the plan the selector picks for it,
    summed — serial buckets, the conservative no-overlap estimate.
    This is the ``--modeled-network`` column of the bench suite and the
    collective term of ``runtime.netmodel.NetModel.step_time``."""
    link = link or DEFAULT_LINK_MODEL
    if n_elems <= 0 or topo.world <= 1:
        return 0.0
    total = 0.0
    for start, stop in _bucket_bounds(n_elems, bucket_bytes, itemsize):
        total += topo.predict_bucket_time(
            (stop - start) * itemsize, link=link, itemsize=itemsize)
    return total
