"""Real-data parity harness — all four reference parts, one command.

The reference's published end-state (``group25.pdf``) is a handful of
numbers: part1's 10% test accuracy / 2.3031 average test loss after 40
iterations, and per-part execution times (93.44 s / 47.23 s / 36.44 s /
32.68 s for parts 1 / 2a / 2b / 3).  This harness runs the EXACT
reference protocol for every part — by invoking the same four CLI
entrypoints a user would, with their reference-default batch sizes,
seed 69143, 40-iteration cap, and full-test-set eval — and prints a
side-by-side table against the published numbers
(``/root/reference/part1/main.py:62-77,120-123``; BASELINE.md).

Usage::

    python -m distributed_machine_learning_tpu.cli.parity \
        --data-root /path/with/cifar-10-batches-py

Without a real ``cifar-10-batches-py/`` under ``--data-root`` the parts
train on the deterministic synthetic stand-in (``data/cifar10.py``) and
every row is marked ``synthetic`` — the harness is then a smoke test of
itself (this environment has no egress, so the real-data column fills
in whenever a host with the dataset exists).  Accuracy/loss parity is
published for part1 only; parts 2a/2b/3 compare step times.

The reference timed a 4-node CPU cluster; this harness runs whatever
devices the host offers and reports the world size next to each ratio
— time ratios across different hardware are a speedup statement, not a
parity check (accuracy/loss are the parity check).
"""

from __future__ import annotations

import argparse
import io
import json
import os
import re
import sys
from contextlib import redirect_stdout

# Published numbers: group25.pdf via BASELINE.md (the report is the only
# source; parts 2a/2b/3 publish times but no end-state accuracy).
REFERENCE = {
    "part1": {
        "total_s": 93.44, "avg_iter_s": 2.39,
        "accuracy_pct": 10.0, "avg_test_loss": 2.3031,
        "config": "batch 256, 1 CPU node", "source": "group25.pdf p.2",
    },
    "part2a": {
        "total_s": 47.23, "avg_iter_s": 1.21,
        "config": "batch 64/node, 4 CPU nodes", "source": "group25.pdf p.3",
    },
    "part2b": {
        "total_s": 36.44, "avg_iter_s": 0.934,
        "config": "batch 64/node, 4 CPU nodes", "source": "group25.pdf p.5",
    },
    "part3": {
        "total_s": 32.68, "avg_iter_s": 0.838,
        "config": "batch 64/node, 4 CPU nodes", "source": "group25.pdf p.6",
    },
}

_PARTS = list(REFERENCE)


def _part_main(part: str):
    import importlib

    mod = importlib.import_module(
        f"distributed_machine_learning_tpu.cli.{part}"
    )
    return mod.main


def _parse_output(out: str) -> dict:
    """Pull the reference-protocol numbers out of a part's print surface."""
    res: dict = {}
    m = re.search(r"Total execution time is : ([\d.eE+-]+) seconds", out)
    if m:
        res["total_s"] = float(m.group(1))
    m = re.search(r"Average execution time is\s+: ([\d.eE+-]+) seconds", out)
    if m:
        res["avg_iter_s"] = float(m.group(1))
    m = re.search(
        r"Test set: Average loss: ([\d.]+), Accuracy: \d+/\d+ \((\d+)%\)",
        out,
    )
    if m:
        res["avg_test_loss"] = float(m.group(1))
        res["accuracy_pct"] = float(m.group(2))
    return res


def run_parity(args) -> list[dict]:
    """Run the selected parts; return one result row per part."""
    from distributed_machine_learning_tpu.data.cifar10 import _maybe_extract

    real_data = (
        os.path.isdir(args.data_root)
        and _maybe_extract(args.data_root) is not None
    )
    import jax

    # Validate the whole list before any (potentially long) training run
    # — a typo in the last part must not discard the first's 40 iters.
    parts = [p.strip() for p in args.parts.split(",")]
    unknown = [p for p in parts if p not in REFERENCE]
    if unknown:
        raise ValueError(f"unknown part(s) {unknown}; choose from {_PARTS}")

    rows = []
    for part in parts:
        argv = ["--data-root", args.data_root,
                "--max-iters", str(args.max_iters)]
        if args.batch_size is not None:
            argv += ["--batch-size", str(args.batch_size)]
        if args.eval_batches is not None:
            argv += ["--eval-batches", str(args.eval_batches)]
        if args.eval_batch_size is not None:
            argv += ["--eval-batch-size", str(args.eval_batch_size)]
        if args.model is not None:
            argv += ["--model", args.model]
        buf = io.StringIO()
        # The part prints its protocol surface; capture it but keep the
        # user informed on stderr.
        print(f"[parity] running {part} {' '.join(argv)}", file=sys.stderr)
        with redirect_stdout(buf):
            _part_main(part)(argv)
        out = buf.getvalue()
        got = _parse_output(out)
        if not got:
            raise RuntimeError(
                f"{part} produced no parseable protocol output:\n{out}"
            )
        rows.append({
            "part": part,
            "data": "cifar-10-batches-py" if real_data else "synthetic",
            "world": jax.device_count(),
            "max_iters": args.max_iters,
            "reference": REFERENCE[part],
            "measured": got,
        })
    return rows


def print_table(rows: list[dict]) -> None:
    hdr = (f"{'part':8} {'metric':15} {'reference':>12} {'measured':>12} "
           f"{'ref/ours':>9}  note")
    print(hdr)
    print("-" * len(hdr))
    for row in rows:
        ref, got = row["reference"], row["measured"]
        note = f"{row['data']}, world={row['world']} (ref: {ref['config']})"
        # The reference total is 39 timed iterations; a shortened smoke
        # run's total is not comparable, so its label says what was run
        # and its ratio is suppressed (sec/iter stays fair at any cap).
        full_protocol = row["max_iters"] == 40
        timed = max(row["max_iters"] - 1, 1)
        for key, label in (
            ("total_s", f"total_s({timed}it)"),
            ("avg_iter_s", "sec/iter"),
            ("accuracy_pct", "accuracy_%"),
            ("avg_test_loss", "avg_test_loss"),
        ):
            if key not in ref:
                continue
            r = ref[key]
            g = got.get(key)
            if g is None:
                cell, ratio = "—", "—"
            else:
                cell = f"{g:.4f}" if key != "accuracy_pct" else f"{g:.0f}"
                comparable = key == "avg_iter_s" or (
                    key == "total_s" and full_protocol
                )
                ratio = (f"{r / g:.1f}x"
                         if key.endswith("_s") and g > 0 and comparable
                         else "—")
            print(f"{row['part']:8} {label:15} {r:>12} {cell:>12} "
                  f"{ratio:>9}  {note}")
            note = ""
    if any(r["data"] == "synthetic" for r in rows):
        print(
            "\nNOTE: no cifar-10-batches-py found under --data-root — the "
            "parts trained on the deterministic synthetic stand-in, so "
            "accuracy/loss rows are NOT a real-data parity claim.  Place "
            "the dataset (or its .tar.gz) under --data-root and re-run."
        )


def run_equivalence(args, devices=None) -> dict:
    """Machine-check the report's mathematical-equivalence argument
    (group25.pdf p.5-6) as a loss-trajectory table over the full
    40-iteration protocol on deterministic synthetic data:

    - **part2a ≡ part2b**: gather→sum→scatter and all-reduce(SUM) are
      the same update through different collectives — trajectories must
      match to float-associativity noise.
    - **SUM parts ≡ part1 at world× LR**: with per-node batch b and
      mean-reduction loss, the summed gradient over w workers equals
      w × the global-batch mean gradient — so 2a/2b on global batch w·b
      must track part1 on the same batches with ``lr × w`` (the §2.4
      effective-LR fact the reference's report glossed over).
    - **part3 (mean) ≡ part1**: the bucketed ppermute ring with pmean
      semantics is DDP's averaged update — must track part1 at the
      same LR.

    Controlled variables: BN-free model (BN running stats are the one
    part3 divergence the reference documented away — group25.pdf
    p.3-4), augmentation off, weight decay off (the SUM ≡ hot-LR
    identity holds for the GRADIENT term only: decay is ``lr·wd·p`` on
    the SUM side but ``lr·w·wd·p`` at the hot LR — a real semantic
    footnote to §2.4, excluded so the collectives are what is
    checked), identical synthetic batches, identical seed-69143 init.
    The strategy is the ONLY thing that varies — the trajectory table
    is the reference report's argument, machine-checked instead of
    eyeballed.

    ``devices``: optional explicit device list (the dryrun passes its
    virtual CPU devices).  A world of 1 would make every check
    vacuously pass (five identical runs), so it is refused.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.cli.common import (
        SEED,
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )

    n = len(devices) if devices is not None else jax.device_count()
    world = min(4, n)  # the reference cluster was 4 nodes
    if world < 2:
        raise ValueError(
            "the equivalence check needs >= 2 devices (a world of 1 "
            "makes every check vacuously pass); run with "
            "XLA_FLAGS=--xla_force_host_platform_device_count=4 "
            "JAX_PLATFORMS=cpu, or on a multi-chip host"
        )
    iters = args.max_iters
    per_node = args.batch_size or 64
    global_batch = per_node * world
    model = get_model(args.model or "vgg11", use_bn=False)
    base_lr = 0.1  # part1/main.py:120

    rng = np.random.default_rng(SEED)
    batches = [
        (
            rng.integers(0, 256, (global_batch, 32, 32, 3), dtype=np.uint8),
            rng.integers(0, 10, global_batch).astype(np.int32),
        )
        for _ in range(iters)
    ]

    def trajectory(strategy_name, lr):
        state = init_model_and_state(
            model, config=SGDConfig(learning_rate=lr, weight_decay=0.0)
        )
        if strategy_name is None:
            step = make_train_step(model, mesh=None, augment=False)
            dev0 = devices[0] if devices is not None else None
            place = lambda x, y: (
                jax.device_put(jnp.asarray(x), dev0),
                jax.device_put(jnp.asarray(y), dev0),
            )
        else:
            mesh = make_mesh(
                world,
                devices=devices[:world] if devices is not None else None,
            )
            step = make_train_step(
                model, get_strategy(strategy_name), mesh=mesh, augment=False
            )
            place = lambda x, y: shard_batch(mesh, x, y)
        losses = []
        for x, y in batches:
            state, loss = step(state, *place(x, y))
            losses.append(float(loss))
        return np.asarray(losses)

    print(f"[equivalence] world={world}, per-node batch {per_node} "
          f"(global {global_batch}), {iters} iters, model "
          f"{args.model or 'vgg11'} (BN-free), augment off",
          file=sys.stderr)
    part1 = trajectory(None, base_lr)
    part1_hot = trajectory(None, base_lr * world)  # the SUM-equivalent LR
    p2a = trajectory("gather_scatter", base_lr)
    p2b = trajectory("all_reduce", base_lr)
    p3 = trajectory("ring", base_lr)

    checks = {
        # gather/scatter vs all-reduce: identical SUM through different
        # collectives — float-associativity noise only.
        "part2a==part2b": (p2a, p2b, 1e-5),
        # SUM semantics = world× effective LR on the global batch
        # (exact with weight decay off — see docstring; tolerance is
        # 40 iters of f32 reduction-order drift).
        f"part2b==part1@lr*{world}": (p2b, part1_hot, 2e-3),
        # ring pmean = part3/DDP's averaged update = part1's rule.
        "part3==part1": (p3, part1, 1e-4),
    }

    hdr = (f"{'iter':>4} {'part1':>9} {'p1@hotlr':>9} {'part2a':>9} "
           f"{'part2b':>9} {'part3':>9}")
    print(hdr)
    print("-" * len(hdr))
    for i in range(0, iters, max(1, iters // 8)):
        print(f"{i:>4} {part1[i]:9.5f} {part1_hot[i]:9.5f} {p2a[i]:9.5f} "
              f"{p2b[i]:9.5f} {p3[i]:9.5f}")
    results = {}
    ok = True
    for name, (a, b, tol) in checks.items():
        dev = float(np.max(np.abs(a - b)))
        passed = dev <= tol
        ok &= passed
        results[name] = {"max_abs_dev": dev, "tol": tol, "pass": passed}
        print(f"{'PASS' if passed else 'FAIL'}  {name:28} "
              f"max|Δloss| = {dev:.2e} (tol {tol:g})")
    return {
        "world": world, "global_batch": global_batch, "iters": iters,
        "checks": results, "ok": ok,
    }


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-root", default="./data",
                   help="directory containing cifar-10-batches-py/ (or "
                        "its tar.gz); synthetic stand-in otherwise")
    p.add_argument("--parts", default=",".join(_PARTS),
                   help="comma-separated subset of " + ",".join(_PARTS))
    p.add_argument("--max-iters", default=40, type=int,
                   help="reference protocol: 40 (iteration 0 untimed)")
    p.add_argument("--batch-size", default=None, type=int,
                   help="override each part's reference batch size "
                        "(smoke-testing the harness itself)")
    p.add_argument("--eval-batches", default=None, type=int,
                   help="cap eval batches (reference: full test set)")
    p.add_argument("--eval-batch-size", default=None, type=int)
    p.add_argument("--model", default=None,
                   help="override the model (reference: vgg11)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="also write the rows as JSON to this path")
    p.add_argument("--equivalence", action="store_true",
                   help="machine-check the report's equivalence argument "
                        "(group25.pdf p.5-6) as a loss-trajectory table: "
                        "part2a==part2b, SUM parts==part1 at world x LR, "
                        "part3 mean==part1 — over the 40-iter synthetic "
                        "protocol; exits non-zero on any FAIL")
    return p


def main(argv=None) -> None:
    args = make_parser().parse_args(argv)
    if args.equivalence:
        result = run_equivalence(args)
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(result, f, indent=2)
            print(f"\nwrote {args.json_out}")
        if not result["ok"]:
            sys.exit(1)
        return
    rows = run_parity(args)
    print_table(rows)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=2)
        print(f"\nwrote {args.json_out}")


if __name__ == "__main__":
    main()
