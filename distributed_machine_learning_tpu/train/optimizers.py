"""Optimizer registry: one table mapping name → (config, init, update).

Single source of truth consumed by the train-step builder
(``train/step.py``), the CLI (``cli/common.py`` — flag choices and config
construction), state creation (``train/state.py`` — momentum-buffer
layout per optimizer), and checkpoint restore (``train/checkpoint.py`` —
config class by saved name), so adding an optimizer is one entry here
instead of five coordinated edits.

Every update fn shares the signature
``(params, moments, grads, config, lr=None, step=None) ->
(new_params, new_moments)`` where ``moments`` is whatever the matching
init fn built (a zeros tree for SGD/LARS, an fp32 ``{"mu","nu"}`` pair of
trees for AdamW) and ``step`` is the pre-update step counter (used by
AdamW's bias correction, ignored by the others).
"""

from __future__ import annotations

from distributed_machine_learning_tpu.train.adamw import (
    AdamWConfig,
    adamw_init,
    adamw_update,
)
from distributed_machine_learning_tpu.train.lars import LARSConfig, lars_update
from distributed_machine_learning_tpu.train.sgd import (
    SGDConfig,
    sgd_init,
    sgd_update,
)

OPTIMIZERS = {
    "sgd": (SGDConfig, sgd_init, sgd_update),
    "lars": (LARSConfig, sgd_init, lars_update),
    "adamw": (AdamWConfig, adamw_init, adamw_update),
}


def optimizer_names() -> list[str]:
    return sorted(OPTIMIZERS)


def get_optimizer(name: str):
    """(config_class, init_fn, update_fn) for ``name``; raises on unknown
    names."""
    try:
        return OPTIMIZERS[name]
    except KeyError:
        raise ValueError(
            f"unknown optimizer {name!r}; choose from {optimizer_names()}"
        ) from None


def _entry_for_config(config):
    # Exact-type dispatch only: an unregistered SGDConfig *subclass* must
    # raise, not silently train with plain-SGD semantics (a LARS-like
    # config created without a registry entry would otherwise lose its
    # intended update rule without any error).
    for cfg_cls, init_fn, update_fn in OPTIMIZERS.values():
        if type(config) is cfg_cls:
            return cfg_cls, init_fn, update_fn
    raise ValueError(
        f"no registered optimizer for config type {type(config).__name__}; "
        f"add it to OPTIMIZERS (registered: {optimizer_names()})"
    )


def init_for_config(config):
    """Momentum/moments init fn matching a config instance — how
    ``TrainState.create`` builds the right buffer layout.  Every
    registry init fn takes the uniform ``(params, config)`` signature,
    and the config is bound in so dtype-bearing configs
    (SGDConfig.momentum_dtype) shape their buffers."""
    init = _entry_for_config(config)[1]
    return lambda params: init(params, config)


def update_fn_for_config(config):
    """Update fn matching a config instance.  The config is static
    (``pytree_node=False``) so this dispatch happens at trace time —
    step impls that can't take an ``optimizer`` build argument (LM,
    pipeline, expert-parallel) use it to honor the state's config."""
    return _entry_for_config(config)[2]


def moment_layout(params_specs, params_example, momentum_example):
    """Project a per-parameter spec/sharding tree onto the momentum slot.

    The momentum slot is either params-shaped (SGD/LARS) or a dict of
    params-shaped moment trees (AdamW's ``{"mu","nu"}``); each moment
    tree inherits its parameter's entry.  Shared by every sharded-state
    builder (``parallel/gspmd.py``, ``parallel/pipeline.py``,
    ``parallel/parallel3d.py``) so a new moment layout is one edit here.
    """
    import jax

    if momentum_example is None:
        return params_specs
    p_struct = jax.tree_util.tree_structure(params_example)
    if jax.tree_util.tree_structure(momentum_example) == p_struct:
        return params_specs
    if isinstance(momentum_example, dict) and all(
        jax.tree_util.tree_structure(v) == p_struct
        for v in momentum_example.values()
    ):
        return {k: params_specs for k in momentum_example}
    raise ValueError(
        "momentum layout matches neither the param tree nor a dict of "
        "param-shaped moment trees; cannot derive its specs"
    )


def config_class_by_name(class_name: str):
    """Config class by its __name__ (checkpoint restore)."""
    for cfg_cls, _init, _update in OPTIMIZERS.values():
        if cfg_cls.__name__ == class_name:
            return cfg_cls
    raise ValueError(
        f"unknown optimizer config class in checkpoint: {class_name!r}"
    )
