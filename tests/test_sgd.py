"""SGD update semantics vs torch.optim.SGD (the reference's optimizer,
``part1/main.py:120-121``: lr=0.1, momentum=0.9, weight_decay=1e-4)."""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_machine_learning_tpu.train.sgd import SGDConfig, sgd_init, sgd_update

torch = pytest.importorskip("torch")


def _torch_reference(params_np, grads_list, cfg):
    tparams = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    opt = torch.optim.SGD(
        tparams,
        lr=cfg.learning_rate,
        momentum=cfg.momentum,
        weight_decay=cfg.weight_decay,
    )
    for grads_np in grads_list:
        opt.zero_grad()
        for p, g in zip(tparams, grads_np):
            p.grad = torch.tensor(g)
        opt.step()
    return [p.detach().numpy() for p in tparams]


def test_sgd_matches_torch_over_steps(rng):
    cfg = SGDConfig()
    shapes = [(3, 4), (7,), (2, 3, 3)]
    params_np = [rng.standard_normal(s).astype(np.float32) for s in shapes]
    grads_list = [
        [rng.standard_normal(s).astype(np.float32) for s in shapes] for _ in range(5)
    ]

    params = [jnp.asarray(p) for p in params_np]
    momentum = sgd_init(params)
    for grads_np in grads_list:
        params, momentum = sgd_update(
            params, momentum, [jnp.asarray(g) for g in grads_np], cfg
        )

    expected = _torch_reference(params_np, grads_list, cfg)
    for ours, theirs in zip(params, expected):
        np.testing.assert_allclose(np.asarray(ours), theirs, rtol=1e-5, atol=1e-6)


def test_first_step_equals_lazy_torch_buffer(rng):
    # torch lazily sets buf = g on step 1; zeros-init must reproduce that.
    cfg = SGDConfig(weight_decay=0.0)
    p = jnp.asarray(rng.standard_normal(5).astype(np.float32))
    g = jnp.asarray(rng.standard_normal(5).astype(np.float32))
    new_p, new_m = sgd_update([p], sgd_init([p]), [g], cfg)
    np.testing.assert_allclose(np.asarray(new_m[0]), np.asarray(g), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(new_p[0]), np.asarray(p - cfg.learning_rate * g), rtol=1e-6
    )


def test_bf16_momentum_buffer():
    """momentum_dtype narrows the CARRIED buffer while the update math
    stays f32 — the trajectory must track full-precision SGD closely
    (bitwise for the first step, where buf == g)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.train.sgd import (
        SGDConfig,
        sgd_init,
        sgd_update,
    )

    params = {"w": jnp.linspace(-1.0, 1.0, 64, dtype=jnp.float32)}
    grads = {"w": jnp.cos(params["w"]) * 0.1}
    cfg16 = SGDConfig(momentum_dtype="bfloat16")
    cfg32 = SGDConfig()
    m16 = sgd_init(params, cfg16)
    m32 = sgd_init(params, cfg32)
    assert m16["w"].dtype == jnp.bfloat16
    assert m32["w"].dtype == jnp.float32
    p16, m16 = sgd_update(params, m16, grads, cfg16)
    p32, m32 = sgd_update(params, m32, grads, cfg32)
    # First step: buffers start at zero so both compute buf = g in f32;
    # params update before the buffer narrows -> identical params.
    np.testing.assert_array_equal(np.asarray(p16["w"]), np.asarray(p32["w"]))
    assert m16["w"].dtype == jnp.bfloat16
    # Subsequent steps accumulate in f32 from the narrowed carry: close,
    # not bitwise.
    for _ in range(5):
        p16, m16 = sgd_update(p16, m16, grads, cfg16)
        p32, m32 = sgd_update(p32, m32, grads, cfg32)
    np.testing.assert_allclose(np.asarray(p16["w"]), np.asarray(p32["w"]),
                               rtol=0, atol=5e-3)
