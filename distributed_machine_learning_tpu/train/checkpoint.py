"""Checkpoint / resume via orbax.

The reference has no checkpointing at all — no ``state_dict``/save/load
anywhere in its 908 LoC (SURVEY.md §5: runs are 40 iterations, results
transcribed by hand).  This subsystem goes beyond parity: save the full
:class:`TrainState` (params, momentum buffers, BN running stats, step
counter, augmentation PRNG key) plus the SGD hyperparameters, and resume
bit-exactly.

TPU-native notes: orbax's OCDBT-backed PyTree checkpointing writes each
host's addressable shards, so the same API covers single-chip and
multi-host pod saves; ``restore`` takes an ``abstract_state`` template so
arrays come back with the correct shardings placed onto the mesh (or as
host arrays when no template is given).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from distributed_machine_learning_tpu.train.state import TrainState

_CONFIG_FILE = "sgd_config.json"
_STATE_DIR = "state"


def _state_pytree(state: TrainState) -> dict:
    """The array-valued part of TrainState (SGDConfig is static metadata)."""
    return {
        "params": state.params,
        "momentum": state.momentum,
        "batch_stats": state.batch_stats,
        "step": state.step,
        "rng": state.rng,
    }


def save_checkpoint(directory: str | os.PathLike, state: TrainState,
                    layout: str | None = None) -> str:
    """Write `state` under `directory/step_<n>/`; returns the path written.

    Only process 0's metadata file is written once; array shards are saved
    by every host (orbax handles the multi-host coordination).

    ``layout``: optional tag naming the PARAMETER layout (e.g. the
    pipeline schedules' block-stacking orders, which share one tree
    structure but permute the layers) — recorded so a resume under a
    different layout can be rejected instead of silently loading
    permuted weights (``checkpoint_layout``).
    """
    directory = os.path.abspath(os.fspath(directory))
    step = int(jax.device_get(state.step))
    path = os.path.join(directory, f"step_{step}")
    with ocp.PyTreeCheckpointer() as ckptr:
        # force=True: re-saving the same step (e.g. rerunning a crashed job
        # into the same --ckpt-dir) overwrites instead of raising.
        ckptr.save(os.path.join(path, _STATE_DIR), _state_pytree(state),
                   force=True)
    if jax.process_index() == 0:
        with open(os.path.join(path, _CONFIG_FILE), "w") as f:
            # Record the config class so restore rebuilds the right
            # optimizer config (LARSConfig carries extra fields that
            # SGDConfig(**...) would reject).
            payload = {"__class__": type(state.config).__name__,
                       **dataclasses.asdict(state.config)}
            if layout is not None:
                payload["__layout__"] = layout
            json.dump(payload, f)
    return path


class AsyncCheckpointWriter:
    """Non-blocking checkpoint saves — training continues while orbax
    serializes in a background thread.

    At LM scale a synchronous save stalls every step for seconds; the
    async writer hides that behind compute (the standard production
    setup).  Layout and completeness semantics are identical to
    :func:`save_checkpoint`: orbax writes the state dir to a temp name
    and renames atomically on finish, and the config file alone does not
    satisfy ``_is_complete`` — so an in-flight or crashed async save is
    invisible to ``latest_checkpoint`` until it actually lands.

    Call :meth:`wait` before process exit (or rely on ``close``); a new
    ``save`` transparently waits for the previous one (orbax serializes
    saves on one thread).
    """

    def __init__(self):
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, directory: str | os.PathLike, state: TrainState) -> str:
        directory = os.path.abspath(os.fspath(directory))
        step = int(jax.device_get(state.step))
        path = os.path.join(directory, f"step_{step}")
        self._ckptr.save(
            os.path.join(path, _STATE_DIR), _state_pytree(state), force=True
        )
        if jax.process_index() == 0:
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, _CONFIG_FILE), "w") as f:
                json.dump(
                    {"__class__": type(state.config).__name__,
                     **dataclasses.asdict(state.config)},
                    f,
                )
        return path

    def wait(self) -> None:
        """Block until the in-flight save (if any) is fully on disk."""
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._ckptr.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _is_complete(path: str) -> bool:
    """A checkpoint is complete iff both halves landed: the orbax state dir
    (orbax writes to a tmp dir and renames atomically, so a crashed save
    never leaves a final-named `state/`) and the config file written after
    it.  An interrupted save therefore fails this check."""
    return os.path.isdir(os.path.join(path, _STATE_DIR)) and os.path.isfile(
        os.path.join(path, _CONFIG_FILE)
    )


def latest_checkpoint(directory: str | os.PathLike) -> str | None:
    """Highest-step *complete* `step_<n>` subdirectory of `directory`, or
    None.  Incomplete checkpoints (crash mid-save) are skipped so resume
    falls back to the newest complete one."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    for step in sorted(steps, reverse=True):
        path = os.path.join(directory, f"step_{step}")
        if _is_complete(path):
            return path
    return None


def checkpoint_config(path: str | os.PathLike):
    """The optimizer config instance a checkpoint was saved with — lets a
    resume build its abstract template with the *saved* momentum layout
    (AdamW's moment dict vs SGD's buffer tree) before restoring."""
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        payload = json.load(f)
    from distributed_machine_learning_tpu.train.optimizers import (
        config_class_by_name,
    )

    # "SGDConfig" default: checkpoints written before the class tag existed.
    payload.pop("__layout__", None)  # layout tag is checkpoint_layout's
    return config_class_by_name(payload.pop("__class__", "SGDConfig"))(
        **payload
    )


def checkpoint_layout(path: str | os.PathLike) -> str | None:
    """The parameter-layout tag a checkpoint was saved with (see
    ``save_checkpoint``); None for plain layouts or pre-tag checkpoints."""
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        return json.load(f).get("__layout__")


def checkpoint_array_shapes(path: str | os.PathLike) -> dict:
    """Shapes of the arrays a checkpoint holds — a pure metadata read
    (no array IO).  For callers that must pick a restore template by the
    SAVED layout (e.g. ``--unsync-bn``'s stacked ``[world, C]`` BN stats
    vs a pre-quirk checkpoint's plain ``[C]``) instead of fishing
    structure mismatches out of a blanket except."""
    path = os.path.abspath(os.fspath(path))
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = ckptr.metadata(os.path.join(path, _STATE_DIR))
    tree = meta.item_metadata
    tree = tree.tree if hasattr(tree, "tree") else tree
    return jax.tree_util.tree_map(lambda m: tuple(m.shape), tree)


def restore_checkpoint(
    path: str | os.PathLike, abstract_state: TrainState | None = None
) -> TrainState:
    """Load the TrainState saved at `path` (a `step_<n>` directory).

    `abstract_state` (e.g. the freshly initialized state, possibly with
    sharded arrays) restores each leaf with matching dtype/sharding; without
    it, arrays land unsharded on the default device.
    """
    path = os.path.abspath(os.fspath(path))
    restore_args: Any = None
    if abstract_state is not None:
        template = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, _state_pytree(abstract_state)
        )
        restore_args = ocp.args.PyTreeRestore(
            item=template,
            restore_args=ocp.checkpoint_utils.construct_restore_args(template),
        )
    with ocp.PyTreeCheckpointer() as ckptr:
        if restore_args is not None:
            tree = ckptr.restore(os.path.join(path, _STATE_DIR), args=restore_args)
        else:
            tree = ckptr.restore(os.path.join(path, _STATE_DIR))
    config = checkpoint_config(path)
    return TrainState(
        params=tree["params"],
        momentum=tree["momentum"],
        batch_stats=tree.get("batch_stats") or {},
        step=tree["step"],
        rng=tree["rng"],
        config=config,
    )
