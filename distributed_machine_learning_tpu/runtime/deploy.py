"""Train-to-serve continuous deployment (ISSUE 18).

The training side writes verified checkpoints; the serving side
(ISSUE 16/17) runs a replicated fleet with epoch-fenced results, SLO
burn rates, and request-scoped traces.  This module closes the loop:
a :class:`DeployController` watches the training run's checkpoint
stream and rolls every new step onto the live fleet with zero dropped
requests, a canaried quality gate, and an automatic, *counted*
rollback path.

The pipeline, end to end:

1. **Watch** — :func:`~..train.checkpoint.latest_checkpoint` walks the
   step directory newest-first through the PR 13 verified chain:
   quarantined dirs are skipped without touching their data, torn or
   digest-mismatched checkpoints are quarantined and counted, and
   only a checkpoint that fully verifies is ever considered for
   deployment.  No unverified bytes reach a replica.

2. **Reshard + requantize** — :func:`load_serving_weights` restores
   the train-layout state (dp / zero1 / fsdp at any world size)
   through ``reshard_restore`` onto the serving layout (world 1),
   rebuilds the params tree, and re-quantizes to int8 through the
   serving quantizer (``ops/quant.py::quantize_lm_params``).  The
   per-leaf LOGICAL digests are then re-verified **post-requantize**:
   the exact f32 vector the quantizer consumed is re-raveled and its
   sha256 compared against the manifest's logical leaf digest — the
   end-to-end chain covers every hop from the trainer's save to the
   quantizer's input, not just the restore.

3. **Fenced hot-swap** — per replica, a two-phase handoff over the
   transport's versioned-weights channel: :meth:`~.transport
   .GangTransport.set_weights` *stages* the new version (the replica
   keeps serving — and completing — old-version work; nothing drops),
   the worker drains its in-flight micro-batch, loads, and
   :meth:`~.transport.GangTransport.commit_weights` flips the
   committed version atomically with the result fence at the hub.  A
   late post from an old-version compute can never complete a
   new-version rid — the protocol dmlcheck layer 3 explores as
   ``weight_swap`` (and whose seeded TOCTOU bug ``--mutate
   swap-unfenced`` rediscovers).  Both ops ride the PR 12 op-id dedup:
   exactly-once staging under forced tcp retries.

4. **Canary** — the router steers a deterministic traffic slice
   (every Nth dispatch) at the swapped replicas; the controller
   compares per-version latency and a quality probe between canary
   and stable over a bounded window, with a deploy-scoped
   :class:`~..telemetry.slo.SLOEngine` watching burn rates on the
   canary's outcomes alone.

5. **Promote / roll back** — a clean window swaps the rest of the
   fleet and counts ``canary_promotions``; a regression (quality,
   latency ratio, SLO burn, or a canary that dies mid-swap) re-swaps
   every touched replica back to the prior verified version and
   counts ``canary_rollbacks`` — never silent.  Every edge lands in
   the health ledger (``weight_swap`` / ``deploy_canary`` /
   ``deploy_promote`` / ``deploy_rollback``) and mirrors into the
   telemetry registry through :class:`~.faults.FaultEvents`, so
   ``tools/serve_status.py`` renders the deployment state machine
   after the fact.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from collections import deque

import numpy as np

from distributed_machine_learning_tpu.runtime.faults import FaultEvents
from distributed_machine_learning_tpu.train.checkpoint import (
    CheckpointVerifyError,
    checkpoint_manifest,
    latest_checkpoint,
    quarantine_checkpoint,
    reshard_restore,
)


def _sha256_arr(arr) -> str:
    return hashlib.sha256(
        np.ascontiguousarray(np.asarray(arr)).tobytes()).hexdigest()


def tree_digest(tree) -> str:
    """Deterministic sha256 over a pytree's leaves (traversal order is
    the pytree order — stable for a fixed structure).  The QUANTIZED
    tree's digest is the deployed version's identity: two deploys of
    bit-identical serving weights get the same digest, and the digest
    in the swap history lets a postmortem tie a served answer back to
    the exact weights that produced it."""
    import jax

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def load_serving_weights(path, template_params=None, *, events=None):
    """Checkpoint → serving weights, through the full verified chain.

    Restores the train-layout state at ``path`` onto the serving
    layout (world 1) via ``reshard_restore`` (manifest file digests +
    logical leaf digests verified there, quarantine on mismatch),
    rebuilds the params tree — zero1/fsdp flat vectors are sliced to
    their logical prefix and unraveled through ``template_params``'
    structure — and re-quantizes to int8 through the serving
    quantizer.  Then the **post-requantize** check: the f32 vector the
    quantizer actually consumed is re-raveled and its sha256 compared
    against the manifest's logical leaf digest (``param_flat`` /
    ``param_shards``; dp checkpoints compare against the restore-time
    ravel) — a corruption anywhere between the trainer's save and the
    quantizer's input fails loudly and quarantines the checkpoint.

    Returns ``{"params", "quantized", "meta", "spec"}`` where ``meta``
    is the transport-ready ``set_weights`` payload: ``{"step", "path",
    "digest", "layout"}`` with ``digest`` the quantized tree's
    identity (:func:`tree_digest`).
    """
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from distributed_machine_learning_tpu.ops.quant import (
        quantize_lm_params,
    )

    path = os.path.abspath(os.fspath(path))
    manifest = checkpoint_manifest(path) or {}
    state, spec = reshard_restore(path, world=1, events=events)
    if spec.layout == "dp":
        params = state.params
        expected = _sha256_arr(ravel_pytree(params)[0])
    else:
        if template_params is None:
            raise ValueError(
                f"restoring a {spec.layout} checkpoint for serving "
                "needs template_params (the flat layouts don't record "
                "the unravel)")
        flat_key = ("param_shards" if spec.layout == "fsdp"
                    else "param_flat")
        unravel = ravel_pytree(template_params)[1]
        vec = np.asarray(getattr(state, flat_key))
        logical = np.ascontiguousarray(vec[: spec.n_elems])
        params = unravel(jnp.asarray(logical))
        expected = (manifest.get("leaves", {})
                    .get(flat_key, {}).get("sha256"))
        if expected is None:  # manifest-less legacy save
            expected = _sha256_arr(logical)
    quantized = quantize_lm_params(params)
    # Post-requantize verification: digest the exact f32 logical
    # content the quantizer consumed, AFTER quantization ran, against
    # the manifest's logical leaf digest.
    got = _sha256_arr(ravel_pytree(params)[0])
    if got != expected:
        quarantine_checkpoint(
            path, f"post-requantize digest mismatch ({got[:12]}…)")
        if events is not None:
            events.ckpt_verify_failures += 1
        raise CheckpointVerifyError(
            f"checkpoint {path}: serving params failed post-requantize "
            f"verification (got {got[:12]}…, want {expected[:12]}…)")
    step = int(np.asarray(state.step))
    meta = {"step": step, "path": path,
            "digest": tree_digest(quantized), "layout": spec.layout}
    return {"params": params, "quantized": quantized,
            "meta": meta, "spec": spec}


@dataclasses.dataclass
class DeployConfig:
    """Controller policy.  Defaults suit the in-proc campaigns;
    ``cli/deploy.py`` maps its flags onto these."""

    checkpoint_dir: str = ""
    canary_replicas: int = 1     # how many replicas take the canary
    canary_every_n: int = 3      # traffic slice: every Nth dispatch
    canary_window: int = 12      # canary completions needed to judge
    max_latency_ratio: float = 3.0  # canary p50 vs stable p50 gate
    max_bad_ratio: float = 0.0   # quality-probe failure ratio tolerated
    commit_timeout_s: float = 5.0   # per-replica wait for worker commit
    judge_timeout_s: float = 30.0   # canary window fill deadline
    poll_s: float = 0.01         # watcher cadence
    slo: tuple = ()              # canary-scoped objectives ("p99<=250ms",)
    burn_threshold: float = 2.0


class DeployController:
    """The train-to-serve deployment state machine:
    ``idle → swapping → canary → promoted | rolled_back``.

    Wire-up: the controller takes the fleet's transport and its
    :class:`~.serving.ServingRouter`, registers itself as the router's
    ``on_complete`` hook (per-outcome latency + posted weights
    version), and drives swaps over the transport's versioned-weights
    channel.  ``quality_fn(outcome) -> bool`` is the deploy-time
    quality probe — e.g. ``cli/deploy.py`` checks the synthetic
    step's checksum token; a model probe would score a step-loss
    eval.  ``template_params`` is the unravel donor for zero1/fsdp
    checkpoints (see :func:`load_serving_weights`).  ``now_fn``
    injects a deterministic clock for the SLO windows (tests).
    """

    def __init__(self, tx, router, cfg: DeployConfig, *,
                 events: FaultEvents | None = None, telemetry=None,
                 template_params=None, quality_fn=None, now_fn=None):
        self.tx = tx
        self.router = router
        self.cfg = cfg
        self.events = events if events is not None else router.events
        self._tel = telemetry
        self._template = template_params
        self._quality = quality_fn
        self._now = now_fn if now_fn is not None else time.monotonic
        self._lock = threading.Lock()
        self._stats: dict[int, dict] = {}
        self._slo = None          # deploy-scoped engine, one per canary
        self._candidate: int | None = None  # version the canary judges
        self.state = "idle"
        self.deployed_version = 0
        self.deployed_meta: dict = {}
        self.history: list[dict] = []   # every committed swap, in order
        self.deploys: list[dict] = []   # one row per deploy() outcome
        self._last_step: int | None = None
        self._seq = 0
        self._pending: dict | None = None
        router.on_complete = self._on_complete

    # -- the router's per-outcome feed -----------------------------------
    def _on_complete(self, outcome: dict) -> None:
        v = outcome.get("version")
        if v is None:
            return
        v = int(v)
        lat = outcome.get("latency_s")
        ok = True
        if self._quality is not None:
            ok = bool(self._quality(outcome))
        with self._lock:
            st = self._stats.get(v)
            if st is None:
                st = self._stats[v] = {
                    "count": 0, "bad": 0, "lat": deque(maxlen=256)}
            st["count"] += 1
            if not ok:
                st["bad"] += 1
            if lat is not None:
                st["lat"].append(float(lat))
            if self._slo is not None and v == self._candidate:
                self._slo.observe(latency_s=lat, error=not ok,
                                  now=self._now())

    def _stats_since(self, version: int, base: dict) -> dict:
        """Counts since the canary opened (``base`` snapshots the
        per-version tallies at deploy start); p50 over the bounded
        recent-latency window — for the brand-new canary version that
        IS the canary window."""
        st = self._stats.get(version) or {"count": 0, "bad": 0,
                                          "lat": deque()}
        b = base.get(version) or {"count": 0, "bad": 0}
        lats = sorted(st["lat"])
        return {
            "count": st["count"] - b["count"],
            "bad": st["bad"] - b["bad"],
            "p50": lats[len(lats) // 2] if lats else None,
        }

    # -- one replica's two-phase swap ------------------------------------
    def _swap(self, rank: int, version: int, meta: dict,
              *, why: str) -> bool:
        """Stage ``version`` on ``rank`` and wait for the worker's
        commit.  True iff the committed version reached ``version``
        within the timeout — a replica that dies mid-swap times out
        here and the caller takes the rollback path."""
        cur = (self.tx.read_serving(rank).get("weights") or {})
        if int(cur.get("version", 0) or 0) == int(version):
            self.router.note_weights(rank, version)
            return True
        self.tx.set_weights(rank, version, meta)
        deadline = time.monotonic() + self.cfg.commit_timeout_s
        while time.monotonic() < deadline:
            rec = (self.tx.read_serving(rank).get("weights") or {})
            if int(rec.get("version", 0) or 0) == int(version):
                self.router.note_weights(rank, version)
                self.events.weight_swaps += 1
                self.history.append({
                    "rank": rank, "version": int(version),
                    "step": meta.get("step"), "why": why,
                    "digest": meta.get("digest")})
                self.tx.append_health_event(
                    "weight_swap", rank=rank, version=int(version),
                    step=meta.get("step"), why=why)
                if self._tel is not None:
                    self._tel.tracer.instant(
                        "weight_swap", rank=rank, version=int(version))
                return True
            time.sleep(self.cfg.poll_s)
        return False

    def _live_ranks(self) -> list[int]:
        return sorted(self.router.audit()["weight_versions"])

    # -- the deploy state machine ----------------------------------------
    def deploy(self, path, *, wait: bool = True) -> dict:
        """Roll the checkpoint at ``path`` onto the fleet.  Returns the
        deploy row: ``{"outcome": "promoted" | "rolled_back", ...}``.
        ``wait=False`` stops after the canary swap (callers drive
        :meth:`judge` themselves — the chaos campaigns do, so they can
        kill replicas mid-window)."""
        loaded = load_serving_weights(
            path, self._template, events=self.events)
        meta = loaded["meta"]
        self._seq += 1
        version = self._seq
        prev_version, prev_meta = self.deployed_version, self.deployed_meta
        ranks = self._live_ranks()
        canary = ranks[: max(1, self.cfg.canary_replicas)]
        rest = [r for r in ranks if r not in canary]
        with self._lock:
            self._candidate = version
            self._slo = self._make_slo()
            base = {v: {"count": st["count"], "bad": st["bad"]}
                    for v, st in self._stats.items()}
        self.state = "swapping"
        swapped: list[int] = []
        for rank in canary:
            if self._swap(rank, version, meta, why="canary"):
                swapped.append(rank)
            else:
                return self._rollback(
                    swapped, version, prev_version, prev_meta,
                    reason=f"replica {rank} failed to commit v{version}")
        self.router.set_canary(canary, self.cfg.canary_every_n)
        self.state = "canary"
        self.tx.append_health_event(
            "deploy_canary", version=version, step=meta.get("step"),
            ranks=list(canary), every_n=self.cfg.canary_every_n)
        ctx = {"version": version, "meta": meta, "canary": canary,
               "rest": rest, "swapped": swapped,
               "prev_version": prev_version, "prev_meta": prev_meta,
               "base": base}
        if not wait:
            self._pending = ctx
            return {"outcome": "canary", "version": version}
        return self.judge(ctx)

    def judge(self, ctx: dict | None = None) -> dict:
        """Fill the canary window, compare versions, then promote or
        roll back.  Separated from :meth:`deploy` so campaigns can
        inject chaos between the canary swap and the judgement."""
        if ctx is None:
            ctx = self._pending
        version, meta = ctx["version"], ctx["meta"]
        prev_version, prev_meta = ctx["prev_version"], ctx["prev_meta"]
        base = ctx["base"]
        deadline = time.monotonic() + self.cfg.judge_timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                cn = self._stats_since(version, base)["count"]
            if cn >= self.cfg.canary_window:
                break
            time.sleep(self.cfg.poll_s)
        with self._lock:
            cstat = self._stats_since(version, base)
            sstat = self._stats_since(prev_version, base)
            alerts = list(self._slo.alerts) if self._slo else []
        reason = None
        if cstat["count"] == 0:
            reason = "canary starved: no completions in the window"
        elif cstat["bad"] > self.cfg.max_bad_ratio * cstat["count"]:
            reason = (f"quality regression: {cstat['bad']}/"
                      f"{cstat['count']} canary answers failed the probe")
        elif alerts:
            reason = (f"SLO burn on canary: {alerts[0]['slo']} "
                      f"(short burn {alerts[0]['short_burn']:.1f}x)")
        elif (cstat["p50"] is not None and sstat["p50"] is not None
              and sstat["p50"] > 0
              and cstat["p50"] > self.cfg.max_latency_ratio
              * sstat["p50"]):
            reason = (f"latency regression: canary p50 "
                      f"{cstat['p50']:.4f}s vs stable "
                      f"{sstat['p50']:.4f}s "
                      f"(> {self.cfg.max_latency_ratio:.1f}x)")
        if reason is not None:
            return self._rollback(ctx["swapped"], version,
                                  prev_version, prev_meta, reason=reason)
        # Clean window: promote the rest of the fleet.
        for rank in ctx["rest"]:
            if self._swap(rank, version, meta, why="promote"):
                ctx["swapped"].append(rank)
            else:
                return self._rollback(
                    ctx["swapped"], version, prev_version, prev_meta,
                    reason=f"replica {rank} failed to commit v{version} "
                           "during promote")
        self.router.clear_canary()
        self.state = "promoted"
        self.deployed_version = version
        self.deployed_meta = meta
        self.events.canary_promotions += 1
        self.tx.append_health_event(
            "deploy_promote", version=version, step=meta.get("step"),
            canary=cstat, stable=sstat)
        row = {"outcome": "promoted", "version": version,
               "step": meta.get("step"), "canary": cstat,
               "stable": sstat}
        self.deploys.append(row)
        self._teardown_canary()
        return row

    def _rollback(self, swapped: list[int], version: int,
                  prev_version: int, prev_meta: dict, *,
                  reason: str) -> dict:
        """Re-swap every touched replica back to the prior verified
        version.  Counted and ledgered — never silent.  A replica that
        also fails the rollback commit (it died) is left to the
        router's beat-staleness eviction, which requeues its work."""
        self.router.clear_canary()
        failed: list[int] = []
        for rank in swapped:
            if not self._swap(rank, prev_version, prev_meta,
                              why="rollback"):
                failed.append(rank)
        self.state = "rolled_back"
        self.events.canary_rollbacks += 1
        self.tx.append_health_event(
            "deploy_rollback", version=version,
            to_version=prev_version, reason=reason,
            unrecovered=failed)
        row = {"outcome": "rolled_back", "version": version,
               "to_version": prev_version, "reason": reason,
               "unrecovered": failed}
        self.deploys.append(row)
        self._teardown_canary()
        return row

    def _teardown_canary(self) -> None:
        with self._lock:
            self._candidate = None
            self._slo = None
        self._pending = None

    def _make_slo(self):
        if not self.cfg.slo:
            return None
        from distributed_machine_learning_tpu.telemetry.slo import (
            SLOEngine,
        )

        return SLOEngine(self.cfg.slo,
                         burn_threshold=self.cfg.burn_threshold,
                         now_fn=self._now)

    # -- the watcher -----------------------------------------------------
    def poll_once(self) -> dict | None:
        """One watcher iteration: deploy the newest verified checkpoint
        if it is newer than the last one deployed (or attempted — a
        checkpoint that rolled back is not retried forever)."""
        if not self.cfg.checkpoint_dir:
            return None
        path = latest_checkpoint(self.cfg.checkpoint_dir, self.events)
        if path is None:
            return None
        step = int(os.path.basename(path)[5:])
        if self._last_step is not None and step <= self._last_step:
            return None
        self._last_step = step
        try:
            return self.deploy(path)
        except CheckpointVerifyError as exc:
            # load_serving_weights quarantined it; the NEXT poll walks
            # the fallback chain past it.  Surface the failure.
            self.tx.append_health_event(
                "deploy_verify_failed", step=step, error=str(exc))
            self._last_step = step - 1 if step > 0 else None
            return {"outcome": "verify_failed", "step": step,
                    "error": str(exc)}

    def run(self, stop_event: threading.Event,
            interval_s: float = 0.1) -> None:
        """The watcher loop — the controller's own thread target."""
        while not stop_event.is_set():
            self.poll_once()
            stop_event.wait(interval_s)

    def summary(self) -> dict:
        """The deployment view ``tools/serve_status.py`` renders."""
        with self._lock:
            per_version = {
                v: {"count": st["count"], "bad": st["bad"]}
                for v, st in sorted(self._stats.items())}
        return {
            "state": self.state,
            "deployed_version": self.deployed_version,
            "deployed_step": self.deployed_meta.get("step"),
            "swaps": len(self.history),
            "history": list(self.history),
            "deploys": list(self.deploys),
            "per_version": per_version,
        }
