"""ZeRO-3 / FSDP-style *sharded* data parallelism.

The reference's capability surface stops at replicated data parallelism
(SURVEY.md §2.3 — "ZeRO/FSDP sharding: absent"), whose memory cost is a
full copy of params + momentum on every worker (~38 MB × 2 for VGG-11,
``group25.pdf`` p.2).  This module goes beyond parity with the sharded
scheme DDP cannot express: every device owns a 1/N slice of the flattened
parameter and momentum vectors, and the train step

  1. **all-gathers** the parameter shards into the full vector
     (``lax.all_gather(tiled=True)`` — one bandwidth-optimal ICI
     collective, not a per-tensor broadcast),
  2. runs forward/backward on the full params,
  3. **reduce-scatters** the gradient so each device receives only the
     reduced slice it owns (``lax.psum_scatter(tiled=True)`` — half the
     ring all-reduce, the same trick phase 1 of ``ops/ring.py`` plays),
  4. applies the SGD/momentum update **on the local shard only**.

Per-device optimizer memory drops from 2·P to 2·P/N (the ZeRO-3
partitioning), and per-step traffic is the same 2·(N−1)/N·P bytes as the
ring all-reduce — FSDP costs no extra bandwidth, it just moves the
all-gather before the forward instead of after the backward.

Flat-vector sharding (rather than per-tensor) keeps every collective a
single static-shape op on one contiguous buffer — the layout XLA/ICI
likes — and sidesteps uneven-tensor bookkeeping: one pad to a multiple of
N covers the whole model.

What the flat layout GIVES UP: the single up-front all-gather is a
serial ICI prelude the forward must wait out, and the full parameter
vector stays resident in HBM for the whole step — there is no
gather/compute overlap and no per-layer liveness.  Two ways back:
``overlap=True`` (round 9, ``parallel/overlap.py``) keeps the flat
layout but moves the gather off the critical path entirely — the
updated shards are gathered by a separately-dispatched bucketed ring
that runs behind the next step's data wait, at the cost of ZeRO-1-like
parameter residency between steps; the per-layer GSPMD scheme
(``parallel/fsdp_perlayer.py``) trades the flat layout's simplicity
for use-site gathers and per-layer liveness (layer i+1's gather
overlapped with layer i's compute by XLA's latency-hiding scheduler).
Prefer per-layer for deep models at scale and this one as the simplest
correct baseline and for the CNN path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.data.augment import augment_batch, normalize
from distributed_machine_learning_tpu.runtime.mesh import (
    BATCH_AXIS,
    padded_len,
    shard_map_no_check as _shard_map,
)
from distributed_machine_learning_tpu.train.common import make_loss_fn, step_rng
from distributed_machine_learning_tpu.train.lars import LARSConfig
from distributed_machine_learning_tpu.train.optimizers import update_fn_for_config
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState


@struct.dataclass
class FSDPState:
    """Sharded training state: flat 1/N param + momentum slices per device.

    ``param_shards``/``momentum_shards`` are global arrays of shape
    ``(padded_len,)`` sharded along the mesh batch axis, so each device
    materializes only ``padded_len / N`` elements (ZeRO-3 partitioning).
    BatchNorm running stats stay replicated — they are O(channels), not
    O(params), and the cross-replica invariant keeps them bit-identical.
    """

    param_shards: jax.Array
    # Flat like param_shards for SGD; a {"mu","nu"} dict of flat vectors
    # for AdamW (both elementwise — exact on arbitrary slices).
    momentum_shards: jax.Array | dict
    batch_stats: dict
    step: jax.Array
    rng: jax.Array
    config: SGDConfig = struct.field(pytree_node=False)


def _padded_len(n_elems: int, n_dev: int) -> int:
    # Canonical definition lives in runtime/mesh.py so the checkpoint
    # resharder recomputes the same partition boundaries.
    return padded_len(n_elems, n_dev)


def flat_mean_grad_shard(
    model, params, batch_stats, x, labels, axis_name: str, n: int,
    padded_len: int,
):
    """Shared back half of the flat-shard schemes' forward/backward:
    loss + grads on full params, flatten/pad, reduce-scatter the MEAN
    gradient so each device holds only the slice it owns, axis-sync BN
    stats and the loss.  Returns ``(loss, new_stats, grad_shard)``.
    One copy so ZeRO-1 and ZeRO-3 cannot drift apart.
    """
    loss_fn = make_loss_fn(model, batch_stats, x, labels, train=True)
    (loss, (_, new_stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params
    )
    flat_grads, _ = ravel_pytree(grads)
    flat_grads = jnp.pad(flat_grads, (0, padded_len - flat_grads.shape[0]))
    grad_shard = lax.psum_scatter(flat_grads, axis_name, tiled=True) / n
    if new_stats:
        new_stats = jax.tree_util.tree_map(
            lambda s: lax.pmean(s, axis_name), new_stats
        )
    return lax.pmean(loss, axis_name), new_stats, grad_shard


def flatten_padded(state: TrainState, n_dev: int):
    """Flatten params + momentum to N-divisible padded vectors — the
    shared front half of every flat-shard scheme (ZeRO-1 and ZeRO-3).

    Returns ``(param_flat, momentum_flat, unravel, n_elems)``.
    """
    flat, unravel = ravel_pytree(state.params)
    n_elems = int(flat.shape[0])
    padded = _padded_len(n_elems, n_dev)
    flat = jnp.pad(flat, (0, padded - n_elems))

    def flat_pad(tree):
        f, _ = ravel_pytree(tree)
        return jnp.pad(f, (0, padded - f.shape[0]))

    p_struct = jax.tree_util.tree_structure(state.params)
    if jax.tree_util.tree_structure(state.momentum) == p_struct:
        mom_flat = flat_pad(state.momentum)  # SGD: one buffer vector
    else:
        # AdamW: each param-shaped moment tree flattens in the same leaf
        # order as the params, so flat index i of mu/nu is the moment of
        # flat param i — slicing stays aligned.
        mom_flat = {k: flat_pad(v) for k, v in state.momentum.items()}
    return flat, mom_flat, unravel, n_elems


def shard_fsdp_state(
    state: TrainState, mesh: Mesh, axis_name: str = BATCH_AXIS
):
    """Flatten a replicated TrainState into FSDP shards on the mesh.

    Returns ``(fsdp_state, unravel, n_elems)``: ``unravel`` maps the
    unpadded flat vector back to the params pytree and ``n_elems`` is the
    unpadded parameter count — both needed by
    :func:`make_fsdp_train_step` and by checkpoint export.
    """
    if isinstance(state.config, LARSConfig):
        # The flat-shard layout slices the parameter vector arbitrarily:
        # elementwise updates (SGD, AdamW) are exact on any slice, but
        # LARS's per-leaf norms would become per-slice norms.
        raise ValueError(
            "ZeRO-3/FSDP cannot shard LARS (per-layer norms are not "
            "sliceable); use sgd or adamw"
        )
    flat, mom_flat, unravel, n_elems = flatten_padded(
        state, mesh.shape[axis_name]
    )
    sharding = NamedSharding(mesh, P(axis_name))
    replicated = NamedSharding(mesh, P())
    fsdp_state = FSDPState(
        param_shards=jax.device_put(flat, sharding),
        momentum_shards=jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), mom_flat
        ),
        batch_stats=jax.device_put(state.batch_stats, replicated),
        step=jax.device_put(state.step, replicated),
        rng=jax.device_put(state.rng, replicated),
        config=state.config,
    )
    return fsdp_state, unravel, n_elems


def gather_fsdp_params(fsdp_state: FSDPState, unravel, n_elems: int):
    """Reassemble the full params pytree from shards (for eval/checkpoint)."""
    flat = jnp.asarray(fsdp_state.param_shards)[:n_elems]
    return unravel(flat)


def make_fsdp_train_step(
    model,
    mesh: Mesh,
    unravel,
    n_elems: int,
    axis_name: str = BATCH_AXIS,
    augment: bool = True,
    jit: bool = True,
    overlap: bool = False,
):
    """Build the jitted ZeRO-3 train step.

    ``unravel``/``n_elems`` come from :func:`shard_fsdp_state`.  Gradient
    reduction is MEAN (DDP/part3 semantics — the natural pairing for a
    scheme whose comparison point is DDP-style replicated DP).

    Returns ``step(fsdp_state, images_u8, labels) -> (fsdp_state, loss)``
    with the batch sharded along the data axis.  ``jit=False`` returns
    the traceable step for callers that compile it inside a larger
    program (the bench harness's scan epoch — same convention as
    ``make_train_step``); the donate-argnums buffer reuse only applies
    to the jitted form.

    ``overlap=True`` (requires ``jit``): the prefetch protocol of the
    overlap-aware sharded update (arxiv 2004.13336; see
    ``parallel/overlap.py``).  The up-front all-gather leaves the step
    program: the wrapper gathers the UPDATED shards into a full vector
    as a separate, immediately-dispatched bucketed-ring program right
    after each update, so the gather runs behind the host's data wait
    and the next step's program consumes the pre-gathered vector
    directly.  Bit-identical trajectory to the sync build (the gather
    is pure data movement).  The cost is ZeRO-1-like parameter
    residency: the prefetched full vector stays live between steps —
    the flat scheme keeps it live across the whole step anyway, so the
    delta is the inter-step window only.  (``FSDPState`` is unchanged;
    after a restore or any state rebind the wrapper detects the
    prefetch miss and re-gathers.)
    """
    n = mesh.shape[axis_name]

    def sharded_for(cfg: SGDConfig, gather: bool = True):
        # cfg is static (FSDPState.config is not a pytree node), so the
        # enclosing jit keys its trace cache on it and this builder runs
        # once per config — no memoization needed here.
        def body(full_flat, param_shards, momentum_shards, batch_stats,
                 step_ctr, rng, images_u8, labels):
            params = unravel(full_flat[:n_elems])

            r = step_rng(rng, step_ctr, axis_name)
            x = augment_batch(r, images_u8) if augment else normalize(images_u8)

            # (2)+(3) forward/backward + reduce-scatter of the MEAN grad —
            # each device receives the slice it owns (half the ring, half
            # the bytes of a full all-reduce).
            loss, new_stats, grad_shard = flat_mean_grad_shard(
                model, params, batch_stats, x, labels, axis_name, n,
                full_flat.shape[0],
            )

            # (4) Optimizer update on the local shard only (the registry
            # update fns work on bare arrays / dicts of arrays): weight
            # decay reads the local *param* shard, so no second
            # all-gather is needed.
            new_params, new_mom = update_fn_for_config(cfg)(
                param_shards, momentum_shards, grad_shard, cfg,
                step=step_ctr,
            )
            return new_params, new_mom, new_stats, loss

        shard = P(axis_name)
        if gather:
            # Sync build: (1) the up-front all-gather INSIDE the program
            # — a serial ICI prelude the forward must wait out.
            def impl(param_shards, momentum_shards, batch_stats, step_ctr,
                     rng, images_u8, labels):
                full_flat = lax.all_gather(param_shards, axis_name,
                                           tiled=True)
                return body(full_flat, param_shards, momentum_shards,
                            batch_stats, step_ctr, rng, images_u8, labels)

            return _shard_map(
                impl,
                mesh=mesh,
                in_specs=(shard, shard, P(), P(), P(), shard, shard),
                out_specs=(shard, shard, P(), P()),
            )
        # Overlap build: the full vector arrives pre-gathered (the
        # consume phase of the previous step's prefetch dispatch).
        return _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), shard, shard, P(), P(), P(), shard, shard),
            out_specs=(shard, shard, P(), P()),
        )

    if not overlap:
        def step(state: FSDPState, images_u8, labels):
            new_params, new_mom, new_stats, loss = sharded_for(
                state.config
            )(
                state.param_shards,
                state.momentum_shards,
                state.batch_stats,
                state.step,
                state.rng,
                images_u8,
                labels,
            )
            new_state = state.replace(
                param_shards=new_params,
                momentum_shards=new_mom,
                batch_stats=new_stats,
                step=state.step + 1,
            )
            return new_state, loss

        return jax.jit(step, donate_argnums=(0,)) if jit else step

    if not jit:
        raise ValueError(
            "overlap=True manages its own two-program dispatch and "
            "cannot be embedded un-jitted; use overlap=False with "
            "jit=False for scanned-epoch callers"
        )
    return _make_fsdp_overlap_step(
        mesh, axis_name, n,
        update_sharded_for=lambda cfg: sharded_for(cfg, gather=False),
        make_state=lambda state, new_params, new_mom, new_stats: state.replace(
            param_shards=new_params,
            momentum_shards=new_mom,
            batch_stats=new_stats,
            step=state.step + 1,
        ),
        state_args=lambda state: (
            state.momentum_shards,
            state.batch_stats,
            state.step,
            state.rng,
        ),
        donate=(0, 2, 3),
    )


def _make_fsdp_overlap_step(mesh, axis_name, n, update_sharded_for,
                            make_state, state_args,
                            donate=(0, 2, 3)):
    """Prefetch-protocol wrapper shared by the CNN and LM ZeRO-3 steps:
    holds the in-flight full-parameter vector between steps, re-gathers
    on a prefetch miss (first call, restore, external rebind), and
    keeps the ``param_gather`` telemetry span.

    The update program takes ``(full_flat, param_shards, *state_args,
    x, y)`` and returns ``(new_shards, new_mom, *rest, loss)``; the
    wrapper dispatches the next gather right after it."""
    from distributed_machine_learning_tpu.parallel.overlap import (
        GatherSpanClock,
        make_ring_gather,
    )

    # donate=False: the gather input IS the state's param_shards — the
    # next update (and any checkpoint) still reads it.
    gather_inner = make_ring_gather(mesh, axis_name, n, donate=False)

    jitted: dict = {}

    def update_for(cfg):
        fn = jitted.get(cfg)
        if fn is None:
            # Donate the prefetched full vector (arg 0 — consumed by
            # the forward; freeing it mid-program caps peak HBM at the
            # sync build's level) plus the momentum/stats buffers,
            # which alias their updated twins.  NOT donated:
            # param_shards (arg 1 — the separately-dispatched gather
            # still reads it), step (re-read by the wrapper's
            # ``state.step + 1``) and rng (carried unchanged into the
            # next step).
            fn = jitted[cfg] = jax.jit(
                update_sharded_for(cfg), donate_argnums=donate
            )
        return fn

    clock = GatherSpanClock()
    holder: dict = {"shards": None, "full": None}

    def step(state: FSDPState, images_u8, labels):
        clock.close()
        if holder["shards"] is not state.param_shards:
            # Prefetch miss: first step, post-restore, or the caller
            # rebound the state — gather now (still an async dispatch;
            # the update program below queues behind it).
            holder["full"] = gather_inner(state.param_shards)
        full, holder["full"] = holder["full"], None  # donated below
        out = update_for(state.config)(
            full, state.param_shards, *state_args(state), images_u8,
            labels,
        )
        new_params, loss = out[0], out[-1]
        new_state = make_state(state, *out[:-1])
        holder["shards"] = new_params
        holder["full"] = gather_inner(new_params)
        clock.open(holder["full"])
        return new_state, loss

    step.overlap = True
    step.update_for = update_for
    step.gather_inner = gather_inner
    step.pop_gather_seconds = clock.pop
    return step


def make_fsdp_lm_train_step(
    model,
    mesh: Mesh,
    unravel,
    n_elems: int,
    axis_name: str = BATCH_AXIS,
    fused_ce_chunks: int | None = None,
    overlap: bool = False,
):
    """ZeRO-3 for the transformer LM: params + optimizer state sharded
    1/N over the data axis, batch sharded over the same axis.

    The flat-shard machinery is model-agnostic, so this is the same
    all-gather → fwd/bwd → psum_scatter → local-shard-update recipe as
    the CNN step, with the LM loss (``train/lm_step.py::lm_loss`` —
    optionally the fused head+loss) in the middle.  Pair with AdamW
    (``config=AdamWConfig()``): the two fp32 moment vectors are the
    memory ZeRO exists to shard.  Dense attention only (ring/ulysses
    need a 2-D mesh; composing FSDP×CP is future work).

    ``overlap=True``: the prefetch protocol (see
    :func:`make_fsdp_train_step` and ``parallel/overlap.py``) — the
    up-front gather leaves the program and runs behind the host's data
    wait as a bucketed-ring dispatch; bit-identical trajectory.

    Returns ``step(fsdp_state, tokens, targets) -> (fsdp_state, loss)``.
    """
    if model.attn_impl != "dense":
        raise ValueError(
            "FSDP LM step requires attn_impl='dense' (sequence-sharded "
            "attention needs a second mesh axis)"
        )
    n = mesh.shape[axis_name]

    def sharded_for(cfg, gather: bool = True):
        def body(full_flat, param_shards, momentum_shards, step_ctr, rng,
                 tokens, targets):
            del rng  # no augmentation on the LM path
            from distributed_machine_learning_tpu.train.lm_step import lm_loss

            params = unravel(full_flat[:n_elems])

            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(model, p, tokens, targets, fused_ce_chunks)
            )(params)
            flat_grads, _ = ravel_pytree(grads)
            flat_grads = jnp.pad(
                flat_grads, (0, full_flat.shape[0] - flat_grads.shape[0])
            )
            grad_shard = lax.psum_scatter(flat_grads, axis_name, tiled=True) / n

            new_params, new_mom = update_fn_for_config(cfg)(
                param_shards, momentum_shards, grad_shard, cfg,
                step=step_ctr,
            )
            return new_params, new_mom, lax.pmean(loss, axis_name)

        shard = P(axis_name)
        if gather:
            def impl(param_shards, momentum_shards, step_ctr, rng, tokens,
                     targets):
                full_flat = lax.all_gather(param_shards, axis_name,
                                           tiled=True)
                return body(full_flat, param_shards, momentum_shards,
                            step_ctr, rng, tokens, targets)

            return _shard_map(
                impl,
                mesh=mesh,
                in_specs=(shard, shard, P(), P(), shard, shard),
                out_specs=(shard, shard, P()),
            )
        return _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), shard, shard, P(), P(), shard, shard),
            out_specs=(shard, shard, P()),
        )

    if not overlap:
        def step(state: FSDPState, tokens, targets):
            new_params, new_mom, loss = sharded_for(state.config)(
                state.param_shards,
                state.momentum_shards,
                state.step,
                state.rng,
                tokens,
                targets,
            )
            new_state = state.replace(
                param_shards=new_params,
                momentum_shards=new_mom,
                step=state.step + 1,
            )
            return new_state, loss

        return jax.jit(step, donate_argnums=(0,))

    return _make_fsdp_overlap_step(
        mesh, axis_name, n,
        update_sharded_for=lambda cfg: sharded_for(cfg, gather=False),
        make_state=lambda state, new_params, new_mom: state.replace(
            param_shards=new_params,
            momentum_shards=new_mom,
            step=state.step + 1,
        ),
        state_args=lambda state: (state.momentum_shards, state.step,
                                  state.rng),
        donate=(0, 2),
    )


def fsdp_memory_footprint(n_params: int, n_dev: int, bytes_per_elem: int = 4):
    """Per-device optimizer-state bytes: replicated DP vs ZeRO-3 shards."""
    replicated = 2 * n_params * bytes_per_elem
    sharded = 2 * _padded_len(n_params, n_dev) // n_dev * bytes_per_elem
    return {"replicated": replicated, "fsdp": sharded}
