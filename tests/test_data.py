"""Data pipeline: sharding vs torch DistributedSampler (the reference's
sharder — part2/2a/main.py:158-159), loaders, augmentation, normalization."""

import numpy as np
import pytest

from distributed_machine_learning_tpu.data.cifar10 import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    Dataset,
    load_cifar10,
)
from distributed_machine_learning_tpu.data.distributed_loader import (
    DistributedBatchLoader,
)
from distributed_machine_learning_tpu.data.loader import BatchLoader
from distributed_machine_learning_tpu.data.sharding import shard_indices


def _tiny_dataset(n=512, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset(
        images=rng.integers(0, 256, (n, 32, 32, 3), dtype=np.uint8),
        labels=rng.integers(0, 10, n).astype(np.int32),
        synthetic=True,
    )


@pytest.mark.parametrize("n,world", [(100, 4), (101, 4), (50000, 4), (16, 8)])
def test_shard_indices_matches_torch_distributed_sampler(n, world):
    torch = pytest.importorskip("torch")
    from torch.utils.data import DistributedSampler

    class _FakeDataset:
        def __len__(self):
            return n

    for rank in range(world):
        sampler = DistributedSampler(
            _FakeDataset(), num_replicas=world, rank=rank, shuffle=False, seed=69143
        )
        expected = np.array(list(iter(sampler)))
        ours = shard_indices(n, rank=rank, num_replicas=world, shuffle=False)
        np.testing.assert_array_equal(ours, expected)


def test_distributed_loader_rank_major_layout():
    """Shard r of the global batch == rank r's DistributedSampler batch."""
    ds = _tiny_dataset(512)
    b, w = 8, 4
    loader = DistributedBatchLoader(ds, per_rank_batch=b, num_ranks=w)
    step0_imgs, step0_labels = next(iter(loader))
    assert step0_imgs.shape == (b * w, 32, 32, 3)
    for rank in range(w):
        rank_indices = shard_indices(len(ds), rank, w)[:b]
        shard = step0_labels[rank * b : (rank + 1) * b]
        np.testing.assert_array_equal(shard, ds.labels[rank_indices])
        np.testing.assert_array_equal(
            step0_imgs[rank * b : (rank + 1) * b], ds.images[rank_indices]
        )


def test_distributed_global_batch_equals_part1_block():
    """The union of the 4 workers' batches is part1's contiguous batch-256
    block — 'test on the same data for all tasks' (part1/main.py:99)."""
    ds = _tiny_dataset(512)
    loader = DistributedBatchLoader(ds, per_rank_batch=64, num_ranks=4)
    _, labels = next(iter(loader))
    np.testing.assert_array_equal(np.sort(labels), np.sort(ds.labels[:256]))


def test_batch_loader_covers_dataset_with_final_short_batch():
    ds = _tiny_dataset(100)
    loader = BatchLoader(ds, batch_size=32)
    batches = list(loader)
    assert len(batches) == 4
    assert sum(len(l) for _, l in batches) == 100
    np.testing.assert_array_equal(
        np.concatenate([l for _, l in batches]), ds.labels
    )


def test_synthetic_cifar10_is_deterministic(tmp_path):
    # The train split is generated ONCE (the 50k synthesis is ~9s on the
    # 1-core box); determinism of the shared generator is asserted on
    # the 5x-cheaper test split, which runs the identical code path.
    a = load_cifar10(root=str(tmp_path / "nope"), download=False)
    assert a.synthetic
    assert len(a) == 50_000
    t1 = load_cifar10(root=str(tmp_path / "nope"), train=False,
                      download=False)
    t2 = load_cifar10(root=str(tmp_path / "nope"), train=False,
                      download=False)
    assert t1.synthetic and t2.synthetic
    assert len(t1) == 10_000
    np.testing.assert_array_equal(t1.images, t2.images)
    np.testing.assert_array_equal(t1.labels, t2.labels)


def _write_cifar_dir(tmp_path, n=20, seed=3):
    """A handcrafted on-disk cifar-10-batches-py layout with a DISTINCT
    payload per batch file (so concatenation order is proven), returning
    the per-file CHW arrays/labels."""
    import pickle, os

    rng = np.random.default_rng(seed)
    batch_dir = tmp_path / "cifar-10-batches-py"
    os.makedirs(batch_dir, exist_ok=True)
    per_file = {}
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        imgs_chw = rng.integers(0, 256, (n, 3, 32, 32), dtype=np.uint8)
        labels = rng.integers(0, 10, n).tolist()
        with open(batch_dir / name, "wb") as f:
            pickle.dump({b"data": imgs_chw.reshape(n, -1), b"labels": labels}, f)
        per_file[name] = (imgs_chw, labels)
    return per_file


def test_cifar10_pickle_parser_roundtrip(tmp_path):
    """Write batches in the standard cifar-10-batches-py layout and parse
    them back: CHW→NHWC orientation, int32 labels, file concat order
    (data/cifar10.py:_load_batches ≡ torchvision's unpickle path,
    part1/main.py:96-97)."""
    n = 20
    per_file = _write_cifar_dir(tmp_path, n=n)
    ds = load_cifar10(root=str(tmp_path), train=True, download=False)
    assert not ds.synthetic
    assert ds.images.shape == (5 * n, 32, 32, 3)
    assert ds.labels.dtype == np.int32
    for i in range(5):
        imgs_chw, labels = per_file[f"data_batch_{i + 1}"]
        np.testing.assert_array_equal(
            ds.images[i * n : (i + 1) * n], imgs_chw.transpose(0, 2, 3, 1)
        )
        np.testing.assert_array_equal(ds.labels[i * n : (i + 1) * n], labels)
    # train=False reads only test_batch.
    test_ds = load_cifar10(root=str(tmp_path), train=False, download=False)
    imgs_chw, labels = per_file["test_batch"]
    assert test_ds.images.shape == (n, 32, 32, 3)
    np.testing.assert_array_equal(test_ds.images, imgs_chw.transpose(0, 2, 3, 1))
    np.testing.assert_array_equal(test_ds.labels, labels)


def test_cifar10_targz_extraction(tmp_path):
    """The tar.gz on disk (what a real download leaves) is extracted and
    parsed without re-downloading (data/cifar10.py:_maybe_extract)."""
    import tarfile

    src = tmp_path / "src"
    src.mkdir()
    per_file = _write_cifar_dir(src, n=4)
    root = tmp_path / "root"
    root.mkdir()
    with tarfile.open(root / "cifar-10-python.tar.gz", "w:gz") as tar:
        tar.add(src / "cifar-10-batches-py", arcname="cifar-10-batches-py")
    ds = load_cifar10(root=str(root), train=True, download=False)
    assert not ds.synthetic and len(ds) == 20
    imgs_chw, _ = per_file["data_batch_1"]
    np.testing.assert_array_equal(ds.images[:4], imgs_chw.transpose(0, 2, 3, 1))


def test_normalize_and_augment_shapes():
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.data.augment import augment_batch, normalize

    imgs = np.random.default_rng(0).integers(0, 256, (4, 32, 32, 3), dtype=np.uint8)
    x = normalize(jnp.asarray(imgs))
    assert x.shape == (4, 32, 32, 3) and x.dtype == jnp.float32
    expected = (imgs.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    np.testing.assert_allclose(np.asarray(x), expected, rtol=1e-5)

    y1 = augment_batch(jax.random.PRNGKey(0), jnp.asarray(imgs))
    y2 = augment_batch(jax.random.PRNGKey(0), jnp.asarray(imgs))
    y3 = augment_batch(jax.random.PRNGKey(1), jnp.asarray(imgs))
    assert y1.shape == (4, 32, 32, 3)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))  # deterministic
    assert not np.allclose(np.asarray(y1), np.asarray(y3))  # key-dependent


def test_augment_einsum_crop_matches_gather_formulation():
    """The MXU-friendly one-hot-einsum crop (data/augment.py) must be
    bit-identical to the naive per-image dynamic_slice + flip formulation
    it replaced (same keys -> same offsets, coins, and pixels)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.data.augment import (
        augment_batch,
        normalize,
    )

    def gather_augment(key, images_u8, padding=4):
        def crop_one(key, img):
            h, w, _ = img.shape
            padded = jnp.pad(
                img, ((padding, padding), (padding, padding), (0, 0))
            )
            kx, ky = jax.random.split(key)
            top = jax.random.randint(kx, (), 0, 2 * padding + 1)
            left = jax.random.randint(ky, (), 0, 2 * padding + 1)
            return jax.lax.dynamic_slice(
                padded, (top, left, 0), (h, w, img.shape[2])
            )

        n = images_u8.shape[0]
        crop_keys = jax.random.split(jax.random.fold_in(key, 0), n)
        flip_key = jax.random.fold_in(key, 1)
        cropped = jax.vmap(crop_one)(crop_keys, images_u8)
        flip = jax.random.bernoulli(flip_key, 0.5, (n,))
        flipped = jnp.where(
            flip[:, None, None, None], cropped[:, :, ::-1, :], cropped
        )
        return normalize(flipped)

    rng = np.random.default_rng(7)
    imgs = jnp.asarray(rng.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8))
    for seed in (0, 69143):
        key = jax.random.PRNGKey(seed)
        np.testing.assert_array_equal(
            np.asarray(gather_augment(key, imgs)),
            np.asarray(augment_batch(key, imgs)),
        )
