"""AdamW — decoupled-weight-decay Adam (Loshchilov & Hutter).

The reference's only optimizer is SGD+momentum (``part1/main.py:120-121``
— SURVEY.md §2.5); that is kept as the parity default (``train/sgd.py``).
AdamW is the extension the transformer-LM side of this framework needs:
large-batch LM training is Adam-shaped, and every modern LM recipe pairs
it with decoupled weight decay.

Update rule (torch ``optim.AdamW`` semantics; ``t = step + 1``):

    mu  = b1·mu + (1−b1)·g
    nu  = b2·nu + (1−b2)·g²
    m̂   = mu / (1 − b1ᵗ)          # bias correction
    n̂   = nu / (1 − b2ᵗ)
    p  −= lr · ( m̂ / (√n̂ + eps) + wd·p )

The wd term uses the *pre-update* parameter, which makes the combined
form above identical to torch's sequential "decay, then Adam step" (the
Adam term never reads p).  Moments are kept in fp32 regardless of the
parameter dtype — bf16 moment accumulation visibly degrades LM loss
curves, and the fp32 master-moment convention is what both torch and
optax implement.

Drop-in companion to ``train/sgd.py``: same
``(params, moments, grads, config, lr=None, step=None)`` signature; the
``moments`` slot of ``TrainState`` holds ``{"mu": tree, "nu": tree}``
(initialized by :func:`adamw_init` via the optimizer registry), and
``step`` must be the state's step counter — bias correction is
mandatory, not optional.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class AdamWConfig:
    # LM-flavored defaults (the CNN parity paths default to SGD).
    learning_rate: float = 3e-4
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.01
    #: Run the update as the fused one-pass Pallas kernel
    #: (``ops/pallas/fused_adamw.py``; CLI ``--fused-update``) instead
    #: of the XLA elementwise chain.  Same signature, same rule; held
    #: to a documented ulp bound against the reference (the kernel's
    #: FMA contraction may differ in the last bits — see the kernel
    #: module docstring).  A config field rather than a step-builder
    #: argument so every consumer of the optimizer registry — the
    #: replicated step, zero1/fsdp and their overlap builds, the LM
    #: steps — picks the kernel up with no builder changes.
    fused: bool = False


def adamw_init(params, config=None):
    """First/second-moment buffers — fp32 zeros, one pair per leaf.
    ``config`` accepted for the registry's uniform (params, config)
    init signature; AdamW's moments are always fp32."""
    del config
    zeros32 = lambda p: jnp.zeros(jnp.shape(p), jnp.float32)
    return {
        "mu": jax.tree_util.tree_map(zeros32, params),
        "nu": jax.tree_util.tree_map(zeros32, params),
    }


def adamw_update(params, moments, grads, config: AdamWConfig, lr=None, step=None):
    """One AdamW step; returns ``(new_params, new_moments)``.

    ``lr``: optional traced scalar overriding ``config.learning_rate``
    (schedule support, as in ``train/sgd.py``).  ``step``: the 0-indexed
    step counter *before* this update (``TrainState.step``); required.
    """
    if type(config) is not AdamWConfig:
        raise TypeError(
            f"adamw_update needs an AdamWConfig on the TrainState, got "
            f"{type(config).__name__}; build the state with "
            "config=AdamWConfig()"
        )
    if step is None:
        raise ValueError(
            "adamw_update requires step= (the TrainState step counter) "
            "for bias correction"
        )
    lr = config.learning_rate if lr is None else lr
    t = jnp.asarray(step, jnp.float32) + 1.0
    bc1 = 1.0 - jnp.power(config.beta1, t)
    bc2 = 1.0 - jnp.power(config.beta2, t)

    def _update(p, m, v, g):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = config.beta1 * m + (1.0 - config.beta1) * g32
        v = config.beta2 * v + (1.0 - config.beta2) * jnp.square(g32)
        adam_term = (m / bc1) / (jnp.sqrt(v / bc2) + config.eps)
        p32 = p32 - lr * (adam_term + config.weight_decay * p32)
        return p32.astype(p.dtype), m, v

    if config.fused:
        # One-pass Pallas kernel (ops/pallas/fused_adamw.py): moment
        # update, bias correction, decay, parameter update, and the
        # dtype cast in-register per tile — read 4, write 3, nothing
        # between.  Same rule; documented-ulp parity with _update.
        from distributed_machine_learning_tpu.ops.pallas.fused_adamw import (
            fused_adamw_leaf,
        )

        def _update(p, m, v, g):  # noqa: F811 — fused twin of the above
            return fused_adamw_leaf(
                p, m, v, g, lr, bc1, bc2,
                beta1=config.beta1, beta2=config.beta2, eps=config.eps,
                weight_decay=config.weight_decay,
            )

    flat = jax.tree_util.tree_map(
        _update, params, moments["mu"], moments["nu"], grads
    )
    is_triple = lambda x: isinstance(x, tuple)
    pick = lambda i: jax.tree_util.tree_map(
        lambda tup: tup[i], flat, is_leaf=is_triple
    )
    return pick(0), {"mu": pick(1), "nu": pick(2)}
