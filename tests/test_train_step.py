"""Per-strategy single-step numerical equivalence vs the single-device
baseline (SURVEY.md §4c) — the invariant the reference only eyeballed via
loss-curve comparison (group25.pdf p.4-6), here as unit tests.

Math (SURVEY.md §2.4): with global batch B split over N shards and
mean-reduction cross-entropy,
  - pmean of local grads == the single-device grad of the same global batch
    → `ring` (DDP/part3 semantics) reproduces part1's update exactly;
  - psum of local grads == N × the single-device grad
    → `all_reduce`/`gather_scatter` (2a/2b SUM semantics) step with an
    effective N× learning rate, exactly like the reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.train.sgd import SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState
from distributed_machine_learning_tpu.train.step import (
    broadcast_bn_stats,
    make_eval_step,
    make_train_step,
    shard_batch,
)

GLOBAL_BATCH = 16


@pytest.fixture(scope="module")
def model():
    return VGGTest()


@pytest.fixture(scope="module")
def init_state(model):
    variables = model.init(jax.random.PRNGKey(69143), jnp.zeros((1, 32, 32, 3)))

    def fresh():
        # Deep-copy: the train step donates its input state (in-place param
        # update on device), so each test needs its own buffers.
        params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), variables["params"]
        )
        return TrainState.create(
            params=params, rng=jax.random.PRNGKey(7), config=SGDConfig()
        )

    return fresh


@pytest.fixture(scope="module")
def batch(request):
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (GLOBAL_BATCH, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (GLOBAL_BATCH,)).astype(np.int32)
    return images, labels


def _single_device_step(model, state, images, labels):
    step = make_train_step(model, mesh=None, augment=False)
    return step(state, jnp.asarray(images), jnp.asarray(labels))


def _distributed_step(model, state, images, labels, mesh, strategy_name, **kw):
    strategy = get_strategy(strategy_name, **kw)
    step = make_train_step(model, strategy, mesh=mesh, augment=False)
    x, y = shard_batch(mesh, images, labels)
    return step(state, x, y)


def _tree_allclose(a, b, rtol=1e-4, atol=1e-5):
    for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), rtol=rtol, atol=atol)


def test_ring_step_equals_single_device(model, init_state, batch, mesh8):
    images, labels = batch
    ref_state, ref_loss = _single_device_step(model, init_state(), images, labels)
    dist_state, dist_loss = _distributed_step(
        model, init_state(), images, labels, mesh8, "ring", bucket_bytes=1 << 20
    )
    # part3/DDP mean semantics == part1's update on the same global batch.
    np.testing.assert_allclose(float(dist_loss), float(ref_loss), rtol=1e-5)
    _tree_allclose(dist_state.params, ref_state.params)


@pytest.mark.slow
def test_ring_step_equals_single_device_full_vgg11(batch, mesh8):
    """The same part3 keystone at the reference's FULL VGG-11 size —
    excluded from the default (1-core-host) run; the fast run proves the
    strategy math on the narrow VGGTest, whose invariants are
    model-independent, and the full model is exercised by bench.py and
    the dryrun regardless."""
    from distributed_machine_learning_tpu.models.vgg import VGG11

    full = VGG11()
    variables = full.init(jax.random.PRNGKey(69143), jnp.zeros((1, 32, 32, 3)))

    def fresh():
        params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), variables["params"]
        )
        return TrainState.create(params=params, rng=jax.random.PRNGKey(7))

    images, labels = batch
    ref_state, ref_loss = _single_device_step(full, fresh(), images, labels)
    dist_state, dist_loss = _distributed_step(
        full, fresh(), images, labels, mesh8, "ring", bucket_bytes=1 << 20
    )
    np.testing.assert_allclose(float(dist_loss), float(ref_loss), rtol=1e-5)
    _tree_allclose(dist_state.params, ref_state.params)


def test_all_reduce_sum_is_nx_learning_rate(model, init_state, batch, mesh8):
    """2b SUM semantics: the distributed update equals a single-device step
    whose gradient is scaled by N (SURVEY.md §2.4)."""
    images, labels = batch
    n = 8
    # Numpy snapshot of the shared init (step inputs get donated/deleted).
    base_params = jax.tree_util.tree_map(np.asarray, init_state().params)
    dist_state, _ = _distributed_step(
        model, init_state(), images, labels, mesh8, "all_reduce"
    )
    ref_state, _ = _single_device_step(model, init_state(), images, labels)
    # momentum starts at 0, so step-1 updates: dist Δ = lr*(N·g + wd·p),
    # ref Δ = lr*(g + wd·p) ⇒ dist Δ − ref Δ = lr·(N−1)·g.
    g_ref = jax.tree_util.tree_map(
        lambda p0, p1: (p0 - np.asarray(p1)) / 0.1, base_params, ref_state.params,
    )
    g_dist = jax.tree_util.tree_map(
        lambda p0, p1: (p0 - np.asarray(p1)) / 0.1, base_params, dist_state.params,
    )
    wd = 1e-4
    for p, gr, gd in zip(
        jax.tree_util.tree_leaves(base_params),
        jax.tree_util.tree_leaves(g_ref),
        jax.tree_util.tree_leaves(g_dist),
    ):
        pure_g = gr - wd * p  # single-device gradient
        expected = n * pure_g + wd * p
        np.testing.assert_allclose(gd, expected, rtol=5e-3, atol=1e-5)


def test_gather_scatter_equals_all_reduce(model, init_state, batch, mesh8):
    """2a and 2b produce identical updates (both SUM — SURVEY.md §2.4)."""
    images, labels = batch
    s_gs, _ = _distributed_step(
        model, init_state(), images, labels, mesh8, "gather_scatter"
    )
    s_ar, _ = _distributed_step(
        model, init_state(), images, labels, mesh8, "all_reduce"
    )
    _tree_allclose(s_gs.params, s_ar.params, rtol=1e-5, atol=1e-6)


def test_bn_model_distributed_step(mesh8):
    """part3 model (BN on) trains under the ring strategy; synced stats
    stay identical across replicas by construction."""
    model = VGGTest(use_bn=True)
    variables = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 32, 32, 3)),
                           train=False)
    state = TrainState.create(
        params=variables["params"], batch_stats=variables["batch_stats"],
        rng=jax.random.PRNGKey(3),
    )
    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, (GLOBAL_BATCH, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (GLOBAL_BATCH,)).astype(np.int32)
    step = make_train_step(model, get_strategy("ring"), mesh=mesh8, augment=False)
    x, y = shard_batch(mesh8, images, labels)
    # COPY the stats snapshot (flake root cause, dmlcheck DML003 class):
    # np.asarray on a CPU jax array is a ZERO-COPY view of the XLA
    # buffer, and the step below donates its input state — XLA may then
    # reuse those very buffers for the updated stats (or anything else),
    # so an aliased `old` flakily compares new-against-new and the
    # "stats moved" assertion fails depending on allocator state (it
    # only reproduced in-suite, under memory pressure).  np.array(...,
    # copy=True) pins the pre-step values in host-owned memory.
    old = [np.array(s, copy=True)
           for s in jax.tree_util.tree_leaves(state.batch_stats)]
    new_state, loss = step(state, x, y)
    assert np.isfinite(float(loss))
    # Running stats moved.
    new = jax.tree_util.tree_leaves(new_state.batch_stats)
    assert any(not np.allclose(o, np.asarray(n)) for o, n in zip(old, new))
    # Eval path runs with the updated stats.
    eval_step = make_eval_step(model)
    loss, correct = eval_step(new_state.params, new_state.batch_stats,
                              jnp.asarray(images), jnp.asarray(labels))
    assert np.isfinite(float(loss)) and 0 <= int(correct) <= GLOBAL_BATCH


def test_local_loss_mode(model, init_state, batch, mesh8):
    """local_loss=True (reference print surface: every rank prints its own
    shard loss — part2/2a/main.py:58-61): the step returns the [world]
    per-device loss vector whose mean equals the pmean-mode scalar."""
    images, labels = batch
    step = make_train_step(
        model, get_strategy("all_reduce"), mesh=mesh8, augment=False,
        local_loss=True,
    )
    x, y = shard_batch(mesh8, images, labels)
    _, losses = step(init_state(), x, y)
    assert losses.shape == (8,)
    _, mean_loss = make_train_step(
        model, get_strategy("all_reduce"), mesh=mesh8, augment=False
    )(init_state(), *shard_batch(mesh8, images, labels))
    np.testing.assert_allclose(
        float(np.mean(np.asarray(losses))), float(mean_loss), rtol=1e-5
    )
    with pytest.raises(ValueError, match="local_loss requires a mesh"):
        make_train_step(model, mesh=None, local_loss=True)


def test_unsynced_bn_quirk_mode(mesh8):
    """sync_bn=False (reference part3 parity: per-node running stats,
    part3/model.py:24 + group25.pdf p.3-4): per-device stats rows drift
    apart because each device normalizes its own shard, while params —
    synced by the ring — stay a single replicated tree that matches the
    sync_bn=True params to BN-stats-induced tolerance."""
    model = VGGTest(use_bn=True)
    variables = model.init(jax.random.PRNGKey(1), jnp.zeros((1, 32, 32, 3)),
                           train=False)

    def fresh():
        params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), variables["params"]
        )
        stats = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), variables["batch_stats"]
        )
        return TrainState.create(
            params=params, batch_stats=stats, rng=jax.random.PRNGKey(3)
        )

    rng = np.random.default_rng(2)
    images = rng.integers(0, 256, (GLOBAL_BATCH, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (GLOBAL_BATCH,)).astype(np.int32)
    x, y = shard_batch(mesh8, images, labels)

    state = broadcast_bn_stats(fresh(), 8)
    # Stacked layout: one stats row per device.
    for leaf in jax.tree_util.tree_leaves(state.batch_stats):
        assert leaf.shape[0] == 8
    step = make_train_step(
        model, get_strategy("ring"), mesh=mesh8, augment=False, sync_bn=False
    )
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))

    # Per-device rows diverged (each shard has different batch moments)…
    mean_leaves = [
        np.asarray(s)
        for s in jax.tree_util.tree_leaves(state.batch_stats)
    ]
    assert any(
        not np.allclose(leaf[0], leaf[1]) for leaf in mean_leaves
    ), "per-device BN stats should drift apart"

    # …while params stay replicated and near the synced-mode params (the
    # reference's documented <1% drift is stats-only on step 1: grads are
    # computed from batch moments, not running stats, so updates match).
    synced_state, _ = (
        make_train_step(model, get_strategy("ring"), mesh=mesh8,
                        augment=False, sync_bn=True)(fresh(), *shard_batch(
                            mesh8, images, labels))
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(synced_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )

    # Quirk-mode eval: each device scores its shard with its own row.
    eval_step = make_eval_step(model, mesh=mesh8, sync_bn=False)
    loss, correct = eval_step(
        state.params, state.batch_stats, *shard_batch(mesh8, images, labels)
    )
    assert np.isfinite(float(loss)) and 0 <= int(correct) <= GLOBAL_BATCH
