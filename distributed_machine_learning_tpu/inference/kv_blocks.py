"""Block/paged KV-cache allocator for the continuous-batching engine.

The batch-static serving path (``inference/generate.py``) sizes its KV
cache ``batch x max_len`` — every request pays the worst case even
when most sequences are short.  This module carves one shared cache
budget into fixed-size **token blocks** (the vLLM PagedAttention idea)
with a per-sequence **block table** mapping logical block index ->
physical block id:

* **reserve-on-admit**: admission reserves the sequence's worst case
  (``ceil((prompt_len + max_new) / block_size)`` blocks) so an
  admitted sequence can never fail mid-decode — no preemption path —
  and rejects (:class:`CacheExhausted`) when the pledge would exceed
  the physically free pool.  The caller queues and retries; that IS
  the admission control.
* **alloc-on-append**: physical blocks bind lazily — prefill blocks at
  admission, one more each time decode crosses a block boundary — so
  the *allocated* footprint tracks actual tokens, not the reservation.
* **free-on-finish**: retiring a sequence returns its blocks (and its
  unused pledge — an EOS early-exit frees what it never touched) to
  the pool the same step, which is what lets the engine backfill the
  slot immediately.

Because reservations are worst-case but *lengths are ragged*, a mix
whose total reserved tokens exceeds ``batch x max_len`` padding fits
in the same budget whenever per-request ``prompt+max_new`` vary —
asserted in ``tests/test_kv_blocks.py``.

Thread-safety: the router thread admits while the engine thread
appends/frees, so every public op is one critical section under a
single lock, with a dmlcheck layer-3 schedule point before the acquire
(the ``analysis/interleave.py`` ``continuous_batching`` scenario
explores admit/retire/swap interleavings here; its seeded
``admit-unlocked`` mutation re-creates the capacity check-then-act
race this layout forbids).  Lock order: the allocator lock is a leaf —
no transport/hub call is ever made while holding it.
"""

from __future__ import annotations

import threading

from distributed_machine_learning_tpu.runtime.coordinator import (
    _sched_point,
)


class CacheExhausted(RuntimeError):
    """Admission would overcommit the block pool — queue and retry."""


def blocks_needed(tokens: int, block_size: int) -> int:
    """Blocks covering ``tokens`` cache slots (ceil division)."""
    return -(-tokens // block_size)


class BlockAllocator:
    """Fixed-pool block allocator with per-sequence block tables.

    ``num_blocks`` physical blocks of ``block_size`` token slots each.
    Sequences are any hashable id (the engine uses request rids).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        # LIFO free stack: blocks freed by a retired sequence are the
        # first reused — the warmest pages.
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))
        self._tables: dict = {}    # seq -> [physical block id, ...]
        self._lengths: dict = {}   # seq -> tokens written (cache slots)
        self._reserved: dict = {}  # seq -> total blocks pledged
        # Blocks pledged by reservations but not yet bound to a
        # physical block (sum over seqs of reserved - len(table)).
        self._pledged = 0

    # -- queries (lock-free reads are fine for monitoring, but the
    # values used for decisions must come from inside admit/append) ----

    def free_blocks(self) -> int:
        """Physically unbound blocks (includes pledged-not-yet-bound)."""
        with self._lock:
            return len(self._free)

    def available_blocks(self) -> int:
        """Blocks admission may still pledge: free minus outstanding
        pledges.  This is the admission-control headroom."""
        with self._lock:
            return len(self._free) - self._pledged

    def sequences(self) -> list:
        with self._lock:
            return list(self._tables)

    def table(self, seq) -> list[int]:
        with self._lock:
            return list(self._tables[seq])

    def length(self, seq) -> int:
        with self._lock:
            return self._lengths[seq]

    # -- lifecycle ------------------------------------------------------

    def admit(self, seq, prompt_len: int, max_new: int) -> list[int]:
        """Admit one sequence: pledge its worst case, bind its prefill
        blocks, return the (prefill) block table.  Raises
        :class:`CacheExhausted` when the pledge exceeds free blocks and
        ``ValueError`` on a duplicate/invalid sequence.  The capacity
        check and the binding are ONE critical section — splitting them
        is exactly the ``admit-unlocked`` layer-3 mutation."""
        if prompt_len < 1:
            raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
        if max_new < 0:
            raise ValueError(f"max_new must be >= 0, got {max_new}")
        _sched_point("kvb:admit")
        with self._lock:
            if seq in self._tables:
                raise ValueError(f"sequence {seq!r} already admitted")
            need = blocks_needed(prompt_len + max_new, self.block_size)
            if need > len(self._free) - self._pledged:
                raise CacheExhausted(
                    f"need {need} blocks, "
                    f"{len(self._free) - self._pledged} available "
                    f"({len(self._free)} free, {self._pledged} pledged)"
                )
            now = blocks_needed(prompt_len, self.block_size)
            table = [self._free.pop() for _ in range(now)]
            self._tables[seq] = table
            self._lengths[seq] = prompt_len
            self._reserved[seq] = need
            self._pledged += need - now
            return list(table)

    def append(self, seq) -> int:
        """Claim the next cache slot for ``seq`` (the decode step is
        about to write position ``length``): binds a fresh block from
        the sequence's pledge at block boundaries.  Returns the slot's
        absolute position.  Never raises for an admitted sequence
        within its reservation — that is the reserve-on-admit
        guarantee."""
        _sched_point("kvb:append")
        with self._lock:
            pos = self._lengths[seq]
            table = self._tables[seq]
            bidx = pos // self.block_size
            if bidx >= self._reserved[seq]:
                raise ValueError(
                    f"sequence {seq!r} exceeded its reservation "
                    f"({self._reserved[seq]} blocks)"
                )
            if bidx == len(table):
                table.append(self._free.pop())
                self._pledged -= 1
            self._lengths[seq] = pos + 1
            return pos

    def free(self, seq) -> list[int]:
        """Retire ``seq``: return its bound blocks (and its unused
        pledge) to the pool.  Returns the freed physical ids."""
        _sched_point("kvb:free")
        with self._lock:
            table = self._tables.pop(seq)
            self._lengths.pop(seq)
            reserved = self._reserved.pop(seq)
            self._pledged -= reserved - len(table)
            self._free.extend(reversed(table))
            return list(table)

    # -- auditing -------------------------------------------------------

    def stats(self) -> dict:
        """Pool occupancy snapshot for telemetry gauges."""
        with self._lock:
            bound = self.num_blocks - len(self._free)
            tokens = sum(self._lengths.values())
            # Fragmentation: slots bound but unwritten (tail-of-block
            # waste) — bounded by block_size - 1 per live sequence.
            waste = bound * self.block_size - tokens
            return {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "free": len(self._free),
                "pledged": self._pledged,
                "available": len(self._free) - self._pledged,
                "bound": bound,
                "sequences": len(self._tables),
                "tokens": tokens,
                "waste_slots": waste,
                "utilization": bound / self.num_blocks,
            }

    def check_invariants(self) -> None:
        """Assert the accounting identities; raises AssertionError on
        any violation.  Cheap enough that tests run it after every op;
        the layer-3 scenario runs it after every explored schedule."""
        with self._lock:
            bound = [b for t in self._tables.values() for b in t]
            assert len(bound) == len(set(bound)), (
                "physical block double-booked across tables"
            )
            assert not set(bound) & set(self._free), (
                "block simultaneously bound and free"
            )
            assert len(bound) + len(self._free) == self.num_blocks, (
                f"block leak: {len(bound)} bound + {len(self._free)} "
                f"free != {self.num_blocks}"
            )
            # The ISSUE invariant: sum of table entries == allocated.
            assert len(bound) == self.num_blocks - len(self._free)
            assert self._pledged == sum(
                self._reserved[s] - len(self._tables[s])
                for s in self._tables
            ), "pledge accounting drifted"
            assert 0 <= self._pledged <= len(self._free), (
                f"pledged {self._pledged} outside [0, {len(self._free)}]"
                " — admission overcommitted the pool"
            )
            for s, t in self._tables.items():
                need = blocks_needed(self._lengths[s], self.block_size)
                assert len(t) == max(need, 1), (
                    f"sequence {s!r}: {len(t)} blocks bound, "
                    f"{need} covered by length {self._lengths[s]}"
                )
                assert len(t) <= self._reserved[s]
