"""Minimal batched loader.

Replaces the reference's ``DataLoader(batch_size, shuffle=False,
pin_memory=True)`` (``part2/2a/main.py:162-167``).  Because augmentation
and normalization moved on-device (``augment.py``), the host side reduces
to contiguous uint8 slicing — there is nothing left for worker processes
to do, so no multiprocessing machinery is needed (pin_memory has no TPU
equivalent; transfers stage through the runtime).  A background-thread
prefetcher overlaps the (tiny) host slicing + H2D with device compute.
A C++ fast path for parsing/slicing lives in ``native/`` (see
``native_loader.py``).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import numpy as np

from distributed_machine_learning_tpu.data.cifar10 import Dataset


class BatchLoader:
    """Iterates (images_u8, labels) batches over given indices.

    drop_last=False like the reference's DataLoader: the final short batch
    is yielded as-is (the reference's 40-iteration cap makes this moot for
    training, but eval consumes the full test set — part1/main.py:67).
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        indices: np.ndarray | None = None,
        prefetch: int = 2,
        retry=None,
    ):
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.indices = (
            np.arange(len(dataset)) if indices is None else np.asarray(indices)
        )
        self.prefetch = prefetch
        # Optional data/retry.py::RetryPolicy: slicing is deterministic and
        # seekable, so a transient dataset fault (remote storage, mmap IO)
        # retries/skips instead of killing the epoch.
        self.retry = retry

    def __len__(self) -> int:
        return (len(self.indices) + self.batch_size - 1) // self.batch_size

    def _batches(self, start: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Batches from absolute batch index ``start`` — the seekable
        source the retry wrapper rebuilds after a failure."""
        imgs, labels = self.dataset.images, self.dataset.labels
        for lo in range(start * self.batch_size, len(self.indices),
                        self.batch_size):
            idx = self.indices[lo : lo + self.batch_size]
            yield imgs[idx], labels[idx]

    def _source(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        if self.retry is None:
            return self._batches()
        from distributed_machine_learning_tpu.data.retry import retry_batches

        return retry_batches(self._batches, self.retry)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        if self.prefetch <= 0:
            yield from self._source()
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        sentinel = object()
        failure: list[BaseException] = []

        def _put(item) -> bool:
            # Bounded put that aborts if the consumer goes away (the
            # training loop breaks at its 40-iteration cap mid-epoch —
            # part1/main.py:32-33 — so early abandonment is the norm).
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for batch in self._source():
                    if not _put(batch):
                        return
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                # A producer death must reach the consumer: swallowing it
                # here would leave the training loop blocked on q.get()
                # forever — the exact silent-hang failure mode the
                # resilience layer exists to eliminate.
                failure.append(exc)
            _put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        # Queue-depth gauge: sampled at every consumer get, so a
        # telemetry timeline shows whether the prefetcher keeps ahead of
        # the step (depth ~prefetch) or the loop is data-starved
        # (depth ~0 — the data_wait spans will be wide at the same
        # steps).  One module-level lookup per epoch, nothing per batch
        # when telemetry is off.
        from distributed_machine_learning_tpu.telemetry import get_telemetry

        tel = get_telemetry()
        depth = (
            tel.registry.gauge("data_queue_depth") if tel is not None
            else None
        )
        try:
            while True:
                item = q.get()
                if depth is not None:
                    depth.set(q.qsize())
                if item is sentinel:
                    if failure:
                        raise failure[0]
                    break
                yield item
        finally:
            stop.set()
            t.join()
