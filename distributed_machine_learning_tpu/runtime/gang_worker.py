"""One rank of a coordinated local gang — the end-to-end chaos harness.

Run as a subprocess by ``gang_supervise`` (``cli/gang.py`` launches it;
``tests/test_gang.py`` / ``tests/test_elastic.py`` assert on it): each
of N OS processes trains lock-step SGD steps with real verified
checkpoints (``train/checkpoint.py``) in a PER-RANK checkpoint
directory (``<ckpt-root>/rank<orig>`` — the per-host-shards layout of a
pod run, which is what makes the restore-point election load-bearing:
validity is each rank's own view), and wires the gang coordinator
(``runtime/coordinator.py``) around the loop: heartbeats per step,
suspensions around compile/saves, a restore-point record after every
verified save.

Lock-step is enforced by ``GangCoordinator.wait_for_peers`` — a barrier
over the beat directory — rather than a cross-process XLA collective:
the CI host's CPU backend cannot run multi-process XLA computations
(the same env drift that fails ``tests/test_multihost.py`` here), and
the barrier reproduces the exact failure semantics this subsystem
exists for: when a peer dies or stalls, the survivors BLOCK, and only
the peer-failure detector's coordinated abort frees them.  On real TPU
pods the blocking collective is the psum itself and the identical
coordinator sits around it (``cli/common.py``'s ``--gang-dir`` path).

Elastic semantics (ISSUE 5 + ISSUE 10): the worker is
WORLD-SIZE-AWARE.  Each step's GLOBAL batch is ``--global-batch``
examples under the launch world — or, with a grow-aware
``--scaling-rule`` (``train/scaling.py``), the rule's batch at the
CURRENT world — keyed on the cumulative EXAMPLE cursor (checkpointed
alongside the step counter), and a rank consumes only its exact shard
of it — ``data/sharding.py::exact_shard_indices(B, rank, world)`` —
logging the consumed example ids to ``consumed_rank<orig>.jsonl`` in
the gang dir.  When the supervisor reshapes the gang from N to M
workers (shrink OR grow), relaunched workers re-evaluate their shards
at world M; under ``pinned`` (the default) the per-host batch rescales
while the global batch and LR are preserved, under ``linear``/``lars``
the global batch tracks the world and the LR tracks the batch so the
loss trajectory stays continuous across the transition (the
load-bearing half of the 4→3→5 chaos proof; ``unscaled`` is the
deliberately-wrong control).  Example-id accounting stays exactly-once
either way: ids are ``example_cursor + shard`` and the cursor rides
the checkpoint, so any world-size history partitions the stream into
contiguous, non-overlapping global batches.  The gradient each rank
applies is the mean over the global batch in canonical order — the
value the psum over ANY world-size partition of it produces — so
params stay bit-identical across ranks, across restarts, and across
world changes.  Each step also logs the toy quadratic loss
``||w - w*||^2`` (w* = 0), the observable the continuity assertion
reads.  Checkpoints are saved with a dp ``ShardSpec`` recording the
world size and restored through ``reshard_restore``, which tolerates
(and counts) a world-size change.

Warm spares (ISSUE 10): launched with ``--spare``, the worker never
joins the barrier or consumes data.  It announces itself on the
coordinator's join channel (``join_rank<orig>.json``, refreshed every
heartbeat so the supervisor can tell a live spare from a stale file),
and PREFETCHES the newest verified checkpoint from the live ranks'
directories into its own ``rank<orig>`` directory — so promotion at a
restart/grow boundary costs O(restore), not O(provision): the
promoted worker resumes from its own directory like any survivor.

Observability (ISSUE 6): per-rank telemetry is ON by default — each
rank streams attempt-tagged step rows, phase spans
(``barrier_wait``/``compute``) and trace instants into the shared
``<gang-dir>/telemetry`` under collision-safe rank-suffixed filenames
(``metrics.rank<orig>.jsonl``, ...), and publishes a rolling
step-time snapshot on every heartbeat via
``GangCoordinator.observe_step`` — the inputs to
``telemetry/aggregator.py``'s cross-rank rollups, the supervisor's
straggler detector, and the ``gang_status``/``trace_merge`` tools.
Disable with ``--no-telemetry``.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import signal
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _global_batch_at(example_cursor: int, batch: int, dim: int) -> "object":
    """The global batch starting at absolute example id
    ``example_cursor`` — row ``j`` is example ``example_cursor + j``,
    generated from the example id ALONE, so every rank, every restart
    attempt, and every world size (and therefore every batch size a
    scaling rule may pick) agrees on each example's content.  Keying on
    the example id rather than the step index is what keeps the stream
    well-defined when a grow/shrink changes the batch size mid-run: the
    step boundary moves, the examples don't."""
    import numpy as np

    rows = np.empty((batch, dim), np.float32)
    for j in range(batch):
        rng = np.random.default_rng(10_000 + example_cursor + j)
        rows[j] = rng.standard_normal(dim)
    return rows


def _parse_tx_chaos(spec: str | None, orig_rank: int, attempt: int):
    """A ``TransportChaos`` plan when this (orig rank, attempt) is the
    target, else None.  Grammar: ``partition@RANK:AFTER_OPS`` — sever
    the channel after N transport ops, attempt 0 only (the relaunch
    heals the link, so the proof can also show the gang FINISHES)."""
    if not spec:
        return None
    kind, _, rest = spec.partition("@")
    if kind.strip() != "partition":
        raise ValueError(
            f"unknown --tx-chaos kind {kind!r} (known: partition)")
    rank_s, _, after_s = rest.partition(":")
    if not (rank_s.strip().isdigit() and after_s.strip().isdigit()):
        raise ValueError(
            f"bad --tx-chaos spec {spec!r}: expected partition@rank:ops")
    if int(rank_s) != orig_rank or attempt != 0:
        return None
    from distributed_machine_learning_tpu.runtime.faults import (
        TransportChaos,
    )

    return TransportChaos(partition_after=int(after_s))


def _make_transport(args, orig_rank: int, attempt: int = 0, events=None):
    """The control-plane backend from the CLI flags (ISSUE 12): file
    keeps the byte-compatible shared-directory layout; tcp talks to
    the gang server with the retry/timeout/idempotency layer."""
    from distributed_machine_learning_tpu.runtime.transport import (
        make_transport,
    )

    if args.gang_transport == "tcp":
        return make_transport(
            "tcp", address=args.gang_addr, events=events,
            chaos=_parse_tx_chaos(args.tx_chaos, orig_rank, attempt),
        )
    return make_transport("file", gang_dir=args.gang_dir, events=events)


def _spare_main(args, orig_rank: int, transport) -> None:
    """The warm-spare loop: announce on the join channel, prefetch the
    newest verified checkpoint into this rank's own directory, repeat —
    no barrier, no data consumption, no training.  Terminated by the
    supervisor at the boundary that promotes (or retires) it; SIGTERM
    is a CLEAN exit (0) — a drained spare is not a failed worker."""
    import shutil
    import signal as _signal

    from distributed_machine_learning_tpu.train.checkpoint import (
        latest_checkpoint,
    )

    def _on_term(sig, frame):
        raise SystemExit(0)

    _signal.signal(_signal.SIGTERM, _on_term)
    own_dir = os.path.join(args.ckpt_dir, f"rank{orig_rank}")
    prefetched: int | None = None
    print(f"spare orig={orig_rank} standing by", flush=True)
    while True:
        newest_path, newest_step = None, -1
        try:
            names = sorted(os.listdir(args.ckpt_dir))
        except OSError:
            names = []
        for name in names:
            if not name.startswith("rank") or not name[4:].isdigit():
                continue
            if int(name[4:]) == orig_rank:
                continue
            # latest_checkpoint runs the full validity chain: a spare
            # must never prefetch a torn or corrupt save.
            found = latest_checkpoint(os.path.join(args.ckpt_dir, name))
            if found is None:
                continue
            step = int(os.path.basename(found)[5:])
            if step > newest_step:
                newest_path, newest_step = found, step
        if newest_path is not None and (prefetched is None
                                        or newest_step > prefetched):
            dst = os.path.join(own_dir, os.path.basename(newest_path))
            tmp = dst + f".prefetch{os.getpid()}"
            try:
                shutil.rmtree(tmp, ignore_errors=True)
                shutil.copytree(newest_path, tmp)
                shutil.rmtree(dst, ignore_errors=True)
                os.replace(tmp, dst)
                prefetched = newest_step
                print(f"spare prefetched step {newest_step}", flush=True)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
        # The refreshed announcement IS the spare's heartbeat: the
        # supervisor promotes only spares whose announcement is fresh.
        transport.announce_join(orig_rank, {
            "rank": int(orig_rank), "spare": True, "time": time.time(),
            "prefetched_step": prefetched, "pid": os.getpid(),
        })
        time.sleep(args.heartbeat_interval)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--orig-rank", type=int, default=None,
                    help="rank identity in the ORIGINAL (pre-shrink) "
                         "numbering; owns the checkpoint dir and the "
                         "consumed-example ledger (default: --rank)")
    ap.add_argument("--attempt", type=int, default=0,
                    help="supervisor attempt number (tags consumption "
                         "records so post-mortems can tell replays apart)")
    ap.add_argument("--gang-dir", required=True)
    ap.add_argument("--ckpt-dir", required=True,
                    help="checkpoint ROOT; this rank writes under "
                         "<ckpt-dir>/rank<orig> (per-host shard layout)")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--global-batch", type=int, default=24,
                    help="examples per GLOBAL step batch at the BASE "
                         "world; each rank consumes its exact shard, "
                         "so under the default pinned rule a shrink "
                         "rescales the per-host batch while the global "
                         "batch — and the LR schedule — is preserved")
    ap.add_argument("--scaling-rule", default="pinned",
                    choices=("pinned", "linear", "lars", "unscaled"),
                    help="how (global batch, LR) respond to a world-"
                         "size change (train/scaling.py): pinned keeps "
                         "both at the base point; linear/lars grow the "
                         "batch with the world and scale the LR with "
                         "the batch (linearly / by sqrt); unscaled is "
                         "the deliberately-wrong control that grows "
                         "the batch and never compensates")
    ap.add_argument("--base-world", type=int, default=None,
                    help="the LAUNCH world size anchoring the scaling "
                         "rule (default: --world; the supervisor "
                         "passes the launch value so the anchor stays "
                         "fixed across relaunches)")
    ap.add_argument("--base-lr", type=float, default=0.5,
                    help="learning rate at the base world")
    ap.add_argument("--feature-dim", type=int, default=8,
                    help="toy example dimensionality (the chaos "
                         "continuity proof uses a wider dim so the "
                         "per-step loss noise is small against the "
                         "floor shifts it measures)")
    ap.add_argument("--spare", action="store_true",
                    help="run as a WARM SPARE: announce on the join "
                         "channel and prefetch the newest verified "
                         "checkpoint into this rank's directory, but "
                         "never train or consume data; the supervisor "
                         "promotes it at a restart/grow boundary")
    ap.add_argument("--gang-transport", dest="gang_transport",
                    default="file", choices=("file", "tcp"),
                    help="control-plane backend (runtime/transport.py): "
                         "'file' = shared-directory channels in "
                         "--gang-dir (the historical default, on-disk "
                         "format unchanged); 'tcp' = a gang server at "
                         "--gang-addr, with per-op timeouts, retry + "
                         "backoff, and idempotent delivery.  ('inproc' "
                         "exists only inside one process — "
                         "cli/gang.py --gang-transport inproc runs "
                         "thread workers instead of spawning this "
                         "module.)")
    ap.add_argument("--gang-addr", dest="gang_addr", default=None,
                    help="host:port of the gang transport server "
                         "(required for --gang-transport tcp)")
    ap.add_argument("--tx-chaos", dest="tx_chaos", default=None,
                    help="transport-level fault injection (tcp only): "
                         "'partition@RANK:AFTER_OPS' severs the "
                         "targeted ORIGINAL rank's channel after N "
                         "transport ops on ATTEMPT 0 only (the relaunch "
                         "heals the link, like a repaired switch port) "
                         "— the chaos proof that connection loss is "
                         "treated as peer death")
    ap.add_argument("--faults", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--heartbeat-interval", type=float, default=0.25)
    ap.add_argument("--peer-timeout", type=float, default=15.0)
    ap.add_argument("--step-sleep", type=float, default=0.02)
    ap.add_argument("--telemetry-dir", default=None,
                    help="per-rank telemetry home (default: "
                         "<gang-dir>/telemetry — the gang plane "
                         "telemetry/aggregator.py reads)")
    ap.add_argument("--telemetry-instance", default=None,
                    help="artifact filename tag (default rank<orig>): "
                         "N ranks sharing one telemetry dir write "
                         "metrics.rank<r>.jsonl etc. so appends never "
                         "interleave")
    ap.add_argument("--no-telemetry", action="store_true",
                    help="disable the default-on per-rank telemetry")
    args = ap.parse_args(argv)
    orig_rank = args.rank if args.orig_rank is None else args.orig_rank
    if args.gang_transport == "tcp" and not args.gang_addr:
        ap.error("--gang-transport tcp requires --gang-addr host:port")

    if args.spare:
        # Spares never join the coordinator barrier or the data stream;
        # the loop is the checkpoint validity chain plus the join
        # channel, so a standing spare costs one idle process.
        _spare_main(args, orig_rank,
                    _make_transport(args, orig_rank, args.attempt))
        return

    # A drain/preemption SIGTERM becomes a SystemExit raised at the next
    # bytecode: the exception path below flushes telemetry before dying,
    # so the terminated attempt's rows and spans survive for the
    # post-mortem instead of dying in the sink buffer.
    def _on_term(sig, frame):
        raise SystemExit(128 + sig)

    signal.signal(signal.SIGTERM, _on_term)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.data.sharding import (
        exact_shard_indices,
    )
    from distributed_machine_learning_tpu.runtime.coordinator import (
        GangCoordinator,
    )
    from distributed_machine_learning_tpu.runtime.faults import (
        FaultEvents,
        FaultInjector,
    )
    from distributed_machine_learning_tpu.runtime.mesh import ShardSpec
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_chain_report,
        checkpoint_cursor,
        checkpoint_extra,
        latest_checkpoint,
        reshard_restore,
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.scaling import ScalingRule
    from distributed_machine_learning_tpu.train.state import TrainState
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    # Telemetry is ON by default (ISSUE 6): every rank streams into the
    # shared <gang-dir>/telemetry with a rank-suffixed instance tag, so
    # the per-rank artifacts land collision-free in ONE directory the
    # aggregator / gang_status / trace_merge tools read as a gang plane.
    telemetry = None
    if not args.no_telemetry:
        from distributed_machine_learning_tpu.telemetry import (
            Telemetry,
            set_telemetry,
        )

        tel_dir = args.telemetry_dir or os.path.join(args.gang_dir,
                                                     "telemetry")
        instance = (args.telemetry_instance
                    if args.telemetry_instance is not None
                    else f"rank{orig_rank}")
        telemetry = Telemetry(tel_dir, instance=instance or None)
        set_telemetry(telemetry)
        # Attempt tags must match the supervisor's numbering so the
        # merged timeline lines up across ranks (set_attempt never
        # moves backwards — a resumed stream keeps its disk offset).
        telemetry.set_attempt(args.attempt)
        telemetry.tracer.instant(
            "gang_worker_start", rank=args.rank, orig_rank=orig_rank,
            world=args.world, attempt=args.attempt,
        )

    ckpt_dir = os.path.join(args.ckpt_dir, f"rank{orig_rank}")
    events = FaultEvents()
    transport = _make_transport(args, orig_rank, args.attempt,
                                events=events)
    # Fault targeting is keyed on the ORIGINAL rank identity: a spec
    # written against the launch-time numbering must keep aiming at the
    # same host after a shrink renumbers the survivors — and the ledger
    # then records stable ids the supervisor can read without mapping.
    injector = FaultInjector.from_flags(
        args.faults, seed=args.seed, horizon=max(args.steps, 2),
        rank=orig_rank,
    )
    if injector is not None:
        # recover_rank is acted by whichever process holds CURRENT rank
        # 0 (the target host is dead); every other fault keys on the
        # original identity above.
        injector.current_rank = args.rank
        os.makedirs(args.gang_dir, exist_ok=True)
        # The exactly-once latch must survive the relaunch this very
        # fault will cause — without the ledger every attempt re-fires
        # the same kill and the gang can never finish.  The ledger is a
        # transport channel (file backend: the same faults_fired.jsonl
        # as always).
        injector.attach_ledger(transport)
    coord = GangCoordinator(
        args.gang_dir, rank=args.rank, world=args.world,
        heartbeat_interval_s=args.heartbeat_interval,
        peer_timeout_s=args.peer_timeout, events=events,
        transport=transport,
    ).start()

    # The scaling rule resolves (global batch, LR) for the CURRENT
    # world from the launch-time anchor: under the default "pinned"
    # this is exactly PR 5's world-invariant global batch; the grow
    # rules re-derive both at every relaunch boundary (train/scaling.py
    # has the contract).  This rank's shard of each step's batch is the
    # exact partition a reshape rebalances: union over ranks = every
    # example exactly once, padding-free.
    base_world = args.base_world if args.base_world else args.world
    rule = ScalingRule(args.scaling_rule, base_lr=args.base_lr,
                       base_global_batch=args.global_batch,
                       base_world=base_world)
    ws = rule.at_world(args.world)
    global_batch, lr = ws.global_batch, ws.lr
    local_ids = exact_shard_indices(global_batch, args.rank, args.world)

    def record_consumed(step: int, example_cursor: int) -> None:
        """One line per completed step: which global example ids THIS
        rank consumed, under which (attempt, world) — the exactly-once
        audit trail the elastic chaos test checks.  Ids are keyed on
        the cumulative example cursor, so they stay contiguous and
        non-overlapping even when a scaling rule changes the batch
        size across world transitions.  The transport's consumed
        channel keeps the durability discipline (file backend:
        flush+fsync per row, dmlcheck DML002 — the monitor thread may
        os._exit this process at any poll)."""
        transport.append_consumed(orig_rank, {
            "attempt": args.attempt, "world": args.world,
            "rank": args.rank, "orig_rank": orig_rank, "step": step,
            "example_cursor": example_cursor,
            "global_batch": global_batch,
            "ids": [example_cursor + int(j) for j in local_ids],
        })

    with coord.suspend():
        state = TrainState.create(
            params={"w": jnp.zeros((args.feature_dim,), jnp.float32)}
        )
        start = 0
        start_examples = 0
        latest = latest_checkpoint(ckpt_dir, events=events)
        if latest is not None:
            # reshard_restore tolerates a checkpoint saved under a
            # DIFFERENT world size (the shrink AND grow cases) — dp
            # params carry no padding, so this is a verified plain
            # restore plus a reshard_restores count when the worlds
            # differ.
            state, _spec = reshard_restore(latest, world=args.world,
                                           events=events,
                                           files_verified=True)
            restored_step = int(jax.device_get(state.step))
            cursor = checkpoint_cursor(latest)
            start = cursor if cursor is not None else restored_step
            # The cumulative example cursor rides the checkpoint: with
            # a batch-changing scaling rule the example position is NOT
            # derivable from the step count alone (earlier steps may
            # have consumed different batch sizes at other worlds).
            # Pre-extra checkpoints fall back to step x current batch —
            # exact under the pinned rule, which is all they ever ran.
            extra = checkpoint_extra(latest)
            ex = extra.get("example_cursor")
            start_examples = (int(ex) if isinstance(ex, int)
                              else start * global_batch)
            # The restore is this rank's proof the checkpoint is whole —
            # record it so the next election can agree on it even if no
            # further save ever lands.
            coord.record_valid_step(restored_step)
            print(f"resumed {latest} step {restored_step}", flush=True)
        else:
            report = checkpoint_chain_report(ckpt_dir)
            if report:
                # Candidates exist but none is restorable: say WHY per
                # candidate (the satellite fix for the bare "no
                # checkpoint found") before training from scratch —
                # the supervisor log is the post-mortem surface.
                print(f"no restorable checkpoint under {ckpt_dir}:",
                      flush=True)
                for p, verdict in report:
                    print(f"  {p}: {verdict}", flush=True)

        @jax.jit
        def step_fn(state, xs):
            # Mean-estimation SGD on the quadratic loss ||w - mu*||^2
            # with true optimum mu* = 0: the gradient is (w - mean of
            # the GLOBAL batch in canonical order) — the value a psum
            # over the per-rank shards would produce under ANY world
            # size, so replicated params stay bit-identical across
            # ranks, restarts, and world changes (asserted by digest
            # below).  The returned loss is ||w||^2 BEFORE the update —
            # distance-to-optimum at this step, the world-independent
            # observable the continuity proof reads (its stationary
            # floor is set by lr x gradient noise, i.e. lr/batch: the
            # quantity a scaling rule must keep invariant).
            w = state.params["w"]
            loss = jnp.sum(w * w)
            w = w - lr * (w - xs.mean(0))
            return (state.replace(params={"w": w}, step=state.step + 1),
                    loss)

        # AOT-compile inside the suspension: the first step's compile
        # must not read as a stall under short chaos-test timeouts.
        compiled = step_fn.lower(
            state, _global_batch_at(start_examples, global_batch,
                                    args.feature_dim)
        ).compile()
        # Publish the resumed position BEFORE the first barrier: peers
        # wait for our published step, and a gang resuming at step k
        # would otherwise deadlock at barrier k with everyone still
        # publishing step 0.
        coord.beat(step=start)

    print(f"ready rank={args.rank} orig={orig_rank} world={args.world} "
          f"start={start} examples={start_examples} "
          f"batch={global_batch} lr={lr:.6g}", flush=True)
    post_save = injector.post_save_hook(events) if injector else None
    batches = range(start, args.steps)
    if injector is not None:
        batches = injector.wrap_batches(batches, events, start=start)

    try:
        for idx in batches:
            t_start = time.perf_counter()
            # The lock-step barrier: the stand-in for the synchronous
            # collective — blocks until every peer has published step
            # idx (a dead peer blocks us here until the detector aborts
            # the gang, exactly like a hung psum).
            if not coord.wait_for_peers(idx):
                break  # test mode only; production aborts the process
            t_barrier = time.perf_counter()
            # Within one attempt the batch size is constant, so the
            # example cursor of step idx is affine in idx; across
            # attempts it re-anchors at the checkpointed cursor.
            ex_cursor = start_examples + (idx - start) * global_batch
            state, loss = compiled(
                state, _global_batch_at(ex_cursor, global_batch,
                                        args.feature_dim)
            )
            jax.block_until_ready(state.params["w"])
            t_end = time.perf_counter()
            loss = float(loss)
            record_consumed(idx, ex_cursor)
            iter_s = t_end - t_start
            phases = {"barrier_wait_s": t_barrier - t_start,
                      "compute_s": t_end - t_barrier}
            # One call publishes progress AND the heartbeat metric
            # snapshot (rolling step time + phase breakdown) the
            # supervisor's straggler detector compares across ranks.
            coord.observe_step(idx + 1, iter_s, phases)
            if telemetry is not None:
                telemetry.tracer.complete("barrier_wait", t_start,
                                          t_barrier, step=idx)
                telemetry.tracer.complete("compute", t_barrier, t_end,
                                          step=idx)
                reg = telemetry.registry
                reg.counter("steps_total").inc()
                reg.histogram("step_seconds").observe(iter_s)
                eps = len(local_ids) / iter_s if iter_s > 0 else 0.0
                reg.gauge("examples_per_s").set(eps)
                telemetry.log_step(idx, iter_s=iter_s, **phases,
                                   examples_per_s=eps, loss=loss,
                                   rank=args.rank, orig_rank=orig_rank,
                                   world=args.world)
            if args.rank == 0:
                print(f"step {idx} loss {loss:.6f}", flush=True)
            if (idx + 1) % args.save_every == 0 or idx + 1 == args.steps:
                # Saves are liveness, not progress: suspend the stall
                # clock exactly as the watchdog path does.
                with coord.suspend():
                    save_checkpoint(
                        ckpt_dir, state, cursor=idx + 1,
                        post_save_hook=post_save,
                        shard_spec=ShardSpec("dp", world=args.world),
                        extra_payload={
                            # The elastic-data position: where in the
                            # example stream step idx+1 begins — the
                            # anchor a relaunch at ANY world/batch
                            # resumes consumption from.
                            "example_cursor":
                                ex_cursor + global_batch,
                            "world": args.world,
                            "scaling_rule": rule.as_dict(),
                        },
                    )
                coord.record_valid_step(int(jax.device_get(state.step)))
            if args.step_sleep:
                time.sleep(args.step_sleep)
    except SystemExit:
        # Drained/preempted (the SIGTERM handler above): flush the
        # attempt's telemetry so its rows and spans reach disk, but
        # never finish() — a terminated rank is not a finished rank.
        if telemetry is not None:
            telemetry.flush()
        raise

    digest = hashlib.sha256(
        np.ascontiguousarray(np.asarray(state.params["w"])).tobytes()
    ).hexdigest()[:16]
    print(f"final_step {int(jax.device_get(state.step))}", flush=True)
    print(f"final_world {args.world}", flush=True)
    print(f"final {digest}", flush=True)
    if events.total():
        print(resilience_summary(events), flush=True)
    coord.finish()
    if telemetry is not None:
        telemetry.tracer.instant(
            "gang_worker_finish", rank=args.rank, orig_rank=orig_rank,
            world=args.world, attempt=args.attempt,
            step=int(jax.device_get(state.step)),
        )
        telemetry.close()


if __name__ == "__main__":
    main()
