"""Tensor-parallel LM step (parallel/tensor_parallel.py): GSPMD-sharded
params must produce the exact same training step as one device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.tensor_parallel import (
    make_tp_lm_train_step,
    shard_tp_batch,
    shard_tp_state,
    tp_spec_for,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
)

VOCAB, B, L = 64, 4, 16


def tiny_lm():
    return TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4)


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(11)
    toks = rng.integers(0, VOCAB, (B, L + 1))
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def test_tp_step_equals_single_device(batch):
    tokens, targets = batch
    model = tiny_lm()

    ref_state = init_lm_state(model)
    ref_step = make_lm_train_step(model, mesh=None)
    ref_state, ref_loss = ref_step(ref_state, jnp.asarray(tokens), jnp.asarray(targets))

    mesh = make_mesh(8, axis_names=("batch", "model"), axis_shape=(2, 4))
    state = shard_tp_state(init_lm_state(model), mesh)
    # Params really are sharded over the model axis.
    qkv = state.params["block_0"]["attn"]["qkv"]["kernel"]
    assert "model" in tuple(qkv.sharding.spec)
    step = make_tp_lm_train_step(model, mesh)
    x, y = shard_tp_batch(mesh, tokens, targets)
    state, loss = step(state, x, y)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_tp_multi_step_stays_consistent(batch):
    """Three TP steps track three single-device steps (momentum + wd active)."""
    tokens, targets = batch
    model = tiny_lm()
    ref_state = init_lm_state(model)
    ref_step = make_lm_train_step(model, mesh=None)
    mesh = make_mesh(4, axis_names=("batch", "model"), axis_shape=(1, 4))
    state = shard_tp_state(init_lm_state(model), mesh)
    step = make_tp_lm_train_step(model, mesh)
    x, y = shard_tp_batch(mesh, tokens, targets)
    for _ in range(3):
        ref_state, ref_loss = ref_step(
            ref_state, jnp.asarray(tokens), jnp.asarray(targets)
        )
        state, loss = step(state, x, y)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)


def test_tp_rejects_bad_configs():
    model = tiny_lm()
    mesh = make_mesh(8, axis_names=("batch", "model"), axis_shape=(1, 8))
    with pytest.raises(ValueError, match="divisible"):
        make_tp_lm_train_step(model, mesh)  # 4 heads over 8-way model axis
    ring = TransformerLM(vocab_size=VOCAB, d_model=32, n_heads=4, attn_impl="ring")
    mesh2 = make_mesh(4, axis_names=("batch", "model"), axis_shape=(1, 4))
    with pytest.raises(ValueError, match="dense"):
        make_tp_lm_train_step(ring, mesh2)


def test_tp_step_accepts_custom_sgd_config(batch):
    """Sharding declarations come from the caller's state, so a non-default
    SGDConfig (static pytree metadata) must not break the jit signature."""
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.state import TrainState

    tokens, targets = batch
    model = tiny_lm()
    base = init_lm_state(model)
    custom = TrainState.create(
        params=base.params, rng=base.rng,
        config=SGDConfig(learning_rate=0.01),
    )
    mesh = make_mesh(4, axis_names=("batch", "model"), axis_shape=(1, 4))
    state = shard_tp_state(custom, mesh)
    step = make_tp_lm_train_step(model, mesh)
    x, y = shard_tp_batch(mesh, tokens, targets)
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))


def test_tp_spec_rules():
    assert tp_spec_for(("block_0", "attn", "qkv", "kernel"), 4)[2] == "model"
    assert tp_spec_for(("block_0", "attn", "out", "kernel"), 3)[0] == "model"
    assert tp_spec_for(("block_0", "fc_in", "kernel"), 2)[1] == "model"
    assert tp_spec_for(("block_0", "fc_out", "kernel"), 2)[0] == "model"
    assert tp_spec_for(("embed", "embedding"), 2)[0] == "model"
    assert tp_spec_for(("ln_f", "scale"), 1) == (None,)
