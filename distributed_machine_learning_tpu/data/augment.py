"""Device-side normalization and augmentation.

The reference augments on the host per-sample through torchvision
transforms: RandomCrop(32, padding=4) + RandomHorizontalFlip, then
normalizes with fixed CIFAR statistics (``part1/main.py:82-89``).

TPU-first redesign: the batch crosses host→device as uint8 NHWC and both
normalization and augmentation run **inside the jitted train step** —
they're elementwise/gather ops XLA fuses into the first conv's input, so
augmentation is effectively free and the host pipeline has nothing to do
but slice contiguous uint8.  Randomness is stateless `jax.random` keyed
from the train-state PRNG (seed 69143 — ``part1/main.py:17``), which keeps
every rank's augmentation stream deterministic and reproducible, the
property the reference gets from per-rank torch seeding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_machine_learning_tpu.data.cifar10 import CIFAR10_MEAN, CIFAR10_STD


def normalize(images_u8: jax.Array) -> jax.Array:
    """uint8 NHWC → normalized fp32 (ToTensor + Normalize, part1/main.py:82-83)."""
    x = images_u8.astype(jnp.float32) / 255.0
    mean = jnp.asarray(CIFAR10_MEAN)
    std = jnp.asarray(CIFAR10_STD)
    return (x - mean) / std


def augment_batch(key: jax.Array, images_u8: jax.Array) -> jax.Array:
    """RandomCrop(32, pad=4) + RandomHorizontalFlip + normalize, whole batch.

    Each sample draws its own crop offset / flip coin, like torchvision's
    per-sample transforms — but the crop is NOT a per-image
    ``dynamic_slice`` (a batched gather, which serializes on TPU and cost
    more than the whole VGG fwd+bwd when measured): selecting 32 of 40
    rows/columns is a linear map, so the batch is cropped by two one-hot
    einsums that ride the MXU, with the horizontal flip folded into the
    column-selection operator for free.  uint8 values are exact in
    bfloat16 (<= 2^8), and one-hot selection only copies them, so the
    result is bit-identical to the gather formulation.
    """
    n, H, W, C = images_u8.shape
    padding = 4
    span = 2 * padding + 1  # 9 possible offsets per axis

    # Identical random draws to the per-image formulation: one key per
    # image split into (top, left), plus a batch flip key.
    crop_keys, flip_key = (
        jax.random.split(jax.random.fold_in(key, 0), n),
        jax.random.fold_in(key, 1),
    )

    def offsets(k):
        kx, ky = jax.random.split(k)
        return (
            jax.random.randint(kx, (), 0, span),
            jax.random.randint(ky, (), 0, span),
        )

    top, left = jax.vmap(offsets)(crop_keys)  # [n], [n]
    flip = jax.random.bernoulli(flip_key, 0.5, (n,))

    padded = jnp.pad(
        images_u8, ((0, 0), (padding, padding), (padding, padding), (0, 0))
    ).astype(jnp.bfloat16)

    rows = jnp.arange(H)  # output row index i selects padded row i + top
    rows_pad = jnp.arange(H + 2 * padding)
    sel_h = (
        rows[None, :, None] + top[:, None, None] == rows_pad[None, None, :]
    ).astype(jnp.bfloat16)  # [n, H, H+2p]
    # Column operator with the flip folded in: output column i reads
    # padded column left + (W-1-i when flipped else i).
    cols = jnp.arange(W)
    cols_pad = jnp.arange(W + 2 * padding)
    src_col = jnp.where(flip[:, None], W - 1 - cols[None, :], cols[None, :])
    sel_w = (
        src_col[:, :, None] + left[:, None, None] == cols_pad[None, None, :]
    ).astype(jnp.bfloat16)  # [n, W, W+2p]

    out = jnp.einsum(
        "nij,njwc->niwc", sel_h, padded, preferred_element_type=jnp.bfloat16
    )
    out = jnp.einsum(
        "nij,nhjc->nhic", sel_w, out, preferred_element_type=jnp.bfloat16
    )
    # normalize() divides by 255 after an astype(float32) — exact for the
    # 0..255-valued bf16 pixels the one-hot selection produced.
    return normalize(out)
