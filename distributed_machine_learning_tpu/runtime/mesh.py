"""Device-mesh construction.

The reference's "mesh" is a gloo process group over TCP
(``dist.init_process_group`` — ``part2/2a/main.py:197``).  Here the unit
of parallelism is a ``jax.sharding.Mesh`` over TPU chips; the data axis
(``"batch"``) plays the role of the gloo world, with XLA collectives
riding ICI.  The mesh is 1-D for the reference's data-parallel-only
capability surface (SURVEY.md §2.3) but constructed through a general
helper so additional axes (model/pipeline/sequence) slot in without
touching callers.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

try:  # jax>=0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_impl

BATCH_AXIS = "batch"


def shard_map_no_check(f, *, mesh, in_specs, out_specs, manual_axes=None):
    """shard_map with replication checking off, across the API rename
    (new jax: check_vma; the experimental API this falls back to: check_rep).

    ``manual_axes``: restrict manual sharding to a subset of mesh axes
    (jax's ``axis_names``); the rest stay under automatic GSPMD
    propagation — how the 3-D step composes a manual ppermute pipeline
    with compiler-derived tensor/data parallelism
    (``parallel/parallel3d.py``).  None (default) = fully manual.
    """
    kwargs = {} if manual_axes is None else {"axis_names": frozenset(manual_axes)}
    try:
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False, **kwargs,
        )
    except TypeError as e:  # pragma: no cover
        if manual_axes is not None:
            raise RuntimeError(
                "partial-manual shard_map (manual_axes=...) needs a jax "
                "version whose shard_map accepts the axis_names parameter; "
                "this jax only has the legacy check_rep API"
            ) from e
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def make_mesh(
    num_devices: int | None = None,
    axis_names: tuple[str, ...] = (BATCH_AXIS,),
    axis_shape: tuple[int, ...] | None = None,
    devices=None,
) -> Mesh:
    """Build a Mesh over (a prefix of) the available devices.

    With defaults: a 1-D data-parallel mesh over all devices.  Pass
    ``axis_names``/``axis_shape`` for multi-axis layouts, e.g.
    ``axis_names=("batch", "model"), axis_shape=(4, 2)``.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    if axis_shape is None:
        axis_shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(axis_shape)) != len(devices):
        raise ValueError(f"axis_shape {axis_shape} != {len(devices)} devices")
    mesh_devices = np.asarray(devices).reshape(axis_shape)
    return Mesh(mesh_devices, axis_names)
