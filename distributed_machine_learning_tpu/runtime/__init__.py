from distributed_machine_learning_tpu.runtime.mesh import make_mesh, BATCH_AXIS
from distributed_machine_learning_tpu.runtime.distributed import (
    initialize_from_flags,
    DistributedContext,
)
from distributed_machine_learning_tpu.runtime.coordinator import (
    GANG_ABORT_EXIT,
    GangCoordinator,
    elect_restore_step,
)

__all__ = [
    "make_mesh", "BATCH_AXIS", "initialize_from_flags",
    "DistributedContext", "GangCoordinator", "GANG_ABORT_EXIT",
    "elect_restore_step",
]
