"""TransformerLM + context-parallel train step (train/lm_step.py).

Key invariant: a ring-attention model sequence-sharded over a (data × seq)
mesh takes EXACTLY the same training step as the dense model on one device
with the same global batch — the transformer analogue of the CNN suite's
per-strategy equivalence tests (tests/test_train_step.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
    shard_lm_batch,
)

VOCAB, B, L = 64, 4, 32


def tiny_lm(**kw):
    return TransformerLM(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=4, **kw
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, VOCAB, (B, L + 1))
    return tokens[:, :-1].astype(np.int32), tokens[:, 1:].astype(np.int32)


def test_forward_shape_and_dtype():
    model = tiny_lm()
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, VOCAB)
    assert logits.dtype == jnp.float32


def test_ring_logits_match_dense(batch):
    """Sequence-sharded forward == unsharded forward (RoPE offsets + ring)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    tokens, _ = batch
    dense = tiny_lm(attn_impl="dense")
    params = dense.init(jax.random.PRNGKey(1), jnp.asarray(tokens))["params"]
    ref = dense.apply({"params": params}, jnp.asarray(tokens))

    ring = tiny_lm(attn_impl="ring")
    mesh = make_mesh(4, axis_names=("seq",))
    fwd = shard_map(
        lambda p, t: ring.apply({"params": p}, t),
        mesh=mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq"),
    )
    out = jax.jit(fwd)(params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_context_parallel_step_equals_single_device(batch):
    tokens, targets = batch
    dense = tiny_lm(attn_impl="dense")
    ring = tiny_lm(attn_impl="ring")

    ref_state = init_lm_state(dense)
    ref_step = make_lm_train_step(dense, mesh=None)
    ref_state, ref_loss = ref_step(ref_state, jnp.asarray(tokens), jnp.asarray(targets))

    mesh = make_mesh(8, axis_names=("batch", "seq"), axis_shape=(2, 4))
    dist_state = init_lm_state(ring)
    dist_step = make_lm_train_step(ring, mesh=mesh)
    x, y = shard_lm_batch(mesh, tokens, targets)
    dist_state, dist_loss = dist_step(dist_state, x, y)

    np.testing.assert_allclose(float(dist_loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(dist_state.params),
        jax.tree_util.tree_leaves(ref_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_lm_loss_decreases():
    """A few steps on a fixed batch must reduce the loss (end-to-end sanity)."""
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, VOCAB, (2, 17))
    x = jnp.asarray(tokens[:, :-1].astype(np.int32))
    y = jnp.asarray(tokens[:, 1:].astype(np.int32))
    model = tiny_lm()
    state = init_lm_state(model)
    step = make_lm_train_step(model, mesh=None)
    state, first = step(state, x, y)
    for _ in range(5):
        state, loss = step(state, x, y)
    assert float(loss) < float(first)


def test_ring_model_requires_seq_axis(batch):
    ring = tiny_lm(attn_impl="ring")
    mesh = make_mesh(4, axis_names=("batch",))
    with pytest.raises(ValueError, match="must have axes"):
        make_lm_train_step(ring, mesh=mesh)


def test_dense_model_rejects_sharded_seq_axis(batch):
    """Dense attention on a seq-sharded mesh would be silently wrong
    (local-chunk attention with offset-0 positions) — must refuse."""
    dense = tiny_lm(attn_impl="dense")
    mesh = make_mesh(8, axis_names=("batch", "seq"), axis_shape=(2, 4))
    with pytest.raises(ValueError, match="cannot shard the sequence"):
        make_lm_train_step(dense, mesh=mesh)
    # seq axis of size 1 is the pure-DP special case and must work.
    mesh_dp = make_mesh(4, axis_names=("batch", "seq"), axis_shape=(4, 1))
    tokens, targets = batch
    state = init_lm_state(dense)
    step = make_lm_train_step(dense, mesh=mesh_dp)
    x, y = shard_lm_batch(mesh_dp, tokens, targets)
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("policy", ["mlp", "block"])
def test_remat_policies_match_no_remat(batch, policy):
    """Both remat policies are pure memory/recompute trades: loss and
    gradients must match the un-rematted model exactly (same jaxpr
    numerics, just re-run in backward)."""
    tokens, targets = batch
    x, y = jnp.asarray(tokens), jnp.asarray(targets)
    base = tiny_lm(remat=False)
    rem = tiny_lm(remat=True, remat_policy=policy)
    params = base.init(jax.random.PRNGKey(5), x)["params"]

    def loss_fn(model):
        def f(p):
            logits = model.apply({"params": p}, x)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

        return jax.jit(jax.value_and_grad(f))

    l0, g0 = loss_fn(base)(params)
    l1, g1 = loss_fn(rem)(params)
    assert np.allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5,
                                   atol=1e-6)


def test_remat_policy_validated():
    model = tiny_lm(remat=True, remat_policy="bogus")
    with pytest.raises(ValueError, match="remat_policy"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
