#!/usr/bin/env python3
"""Fuse per-rank Chrome traces into ONE Perfetto timeline — stdlib-only.

Each gang worker streams its own trace (``trace.rank<r>.json`` under
the shared telemetry dir, or ``rank<r>/trace.json``), and each records
its events under its own local ``pid`` — every rank believes it is
process 0, so dragging the files into Perfetto one by one can never
show the thing cross-rank traces exist for: barrier convoys, skewed
phases, and which rank's stall the others were waiting on ("Automatic
Cross-Replica Sharding", arxiv 2004.13336, motivates exactly this
per-phase overlap proof).

The merge rewrites every event's ``pid`` to the rank that produced it
(one Perfetto process track per rank, named and sorted), keeps ``tid``
(worker-side threads stay distinct within a track), and carries the
events through otherwise untouched — attempt tags
(``gang_worker_start`` instants, ``restart_attempt``/``gang_attempt``
spans) stay in ``args``, so one timeline spans every attempt of a
supervised chaos run.  Ranks are ORIGINAL-numbering identities: a
renumbered survivor keeps appending to its original stream, so its
track is continuous across shrinks.  Torn final events (a killed rank)
and unterminated arrays are tolerated by construction — the readers
drop exactly the record the crash destroyed.

Serving streams (ISSUE 17): a serving fleet writes ``trace.router.json``
plus ``trace.replica<r>.json`` — and before this PR they ALL recorded
pid 0 and collided with each other (and with train rank 0) in a merged
timeline.  Serving streams now get their own pid block starting at
:data:`SERVING_PID_BASE` (router first, then replicas in rank order),
named ``serve router`` / ``serve replica <r>`` and sorted after the
train ranks.  ``request`` spans that share an ``args.rid`` across
processes (the router's end-to-end span and each replica's
take→outcome span) are flow-linked by rid, so Perfetto draws the
request hopping processes as one connected arrow chain.

Usage:  python tools/trace_merge.py <telemetry-dir> [-o OUT.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from distributed_machine_learning_tpu.telemetry.tracer import (  # noqa: E402,E501
    read_trace,
)

_TRACE_FILE_RE = re.compile(r"^trace\.rank(\d+)\.json$")
_RANK_DIR_RE = re.compile(r"^rank(\d+)$")
_SERVE_FILE_RE = re.compile(r"^trace\.(router|replica(\d+))\.json$")

# Serving tracks live in their own pid block so they can never collide
# with train-rank pids (rank == pid) in the same telemetry dir.
SERVING_PID_BASE = 1000


def discover_rank_traces(root: str) -> dict[int, str]:
    """rank -> trace path, over both layouts (rank-suffixed files win,
    mirroring ``telemetry/aggregator.py::discover_rank_streams``)."""
    out: dict[int, str] = {}
    if not os.path.isdir(root):
        return out
    names = sorted(os.listdir(root))
    for name in names:
        m = _TRACE_FILE_RE.match(name)
        if m:
            out.setdefault(int(m.group(1)), os.path.join(root, name))
    for name in names:
        m = _RANK_DIR_RE.match(name)
        if m:
            path = os.path.join(root, name, "trace.json")
            if os.path.isfile(path):
                out.setdefault(int(m.group(1)), path)
    return out


def discover_serving_traces(root: str) -> dict[str, str]:
    """``"router"``/``"replica<r>"`` -> trace path — the serving-fleet
    streams ``cli/serve.py`` writes via instance-tagged telemetry."""
    out: dict[str, str] = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        m = _SERVE_FILE_RE.match(name)
        if m:
            out.setdefault(m.group(1), os.path.join(root, name))
    return out


def _serving_pid(label: str) -> int:
    """router -> base; replica r -> base+1+r (stable, rank-ordered)."""
    if label == "router":
        return SERVING_PID_BASE
    return SERVING_PID_BASE + 1 + int(label[len("replica"):])


def _request_flow_links(events: list[dict]) -> list[dict]:
    """Flow events (ph ``s``/``f``) linking ``request`` spans that
    share an ``args.rid`` across DIFFERENT pids — the router's
    end-to-end span and each replica attempt become one arrow chain in
    Perfetto.  Spans confined to one process need no link."""
    by_rid: dict[str, list[dict]] = {}
    for e in events:
        args = e.get("args")
        if (e.get("ph") == "X" and e.get("name") == "request"
                and isinstance(args, dict)
                and args.get("rid") is not None):
            by_rid.setdefault(str(args["rid"]), []).append(e)
    links: list[dict] = []
    for rid, spans in sorted(by_rid.items()):
        if len({e.get("pid") for e in spans}) < 2:
            continue
        spans = sorted(spans, key=lambda e: e.get("ts", 0))
        fid = zlib.crc32(rid.encode())
        for i, e in enumerate(spans):
            links.append({
                "name": "request_flow", "cat": "serving", "id": fid,
                "ph": "s" if i == 0 else "f",
                **({} if i == 0 else {"bp": "e"}),
                "ts": e.get("ts", 0), "pid": e.get("pid", 0),
                "tid": e.get("tid", 0),
            })
    return links


def merge_traces(root: str) -> tuple[dict, dict[str, int]]:
    """(merged trace object, stream label -> event count).

    Labels are ``rank<r>`` for train streams and ``router`` /
    ``replica<r>`` for serving streams.  The result is the Chrome JSON
    Object Format (``{"traceEvents": [...]}``) — strictly-valid JSON
    whatever state the inputs were killed in, with one metadata-named
    process track per stream.
    """
    events: list[dict] = []
    counts: dict[str, int] = {}

    def _add_stream(label: str, pid: int, path: str, pname: str,
                    sort_index: int) -> None:
        stream = [e for e in read_trace(path) if isinstance(e, dict)]
        for e in stream:
            e = dict(e)
            e["pid"] = pid  # every stream thinks it's pid 0: re-home it
            events.append(e)
        counts[label] = len(stream)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": pname}})
        events.append({"name": "process_sort_index", "ph": "M",
                       "pid": pid, "args": {"sort_index": sort_index}})

    for rank, path in sorted(discover_rank_traces(root).items()):
        _add_stream(f"rank{rank}", rank, path, f"rank {rank}", rank)
    serving = discover_serving_traces(root)
    for label in sorted(serving, key=_serving_pid):
        pid = _serving_pid(label)
        pname = ("serve router" if label == "router"
                 else f"serve replica {label[len('replica'):]}")
        _add_stream(label, pid, serving[label], pname, pid)
    events.extend(_request_flow_links(events))
    # Chronological order is not required by the format but makes the
    # merged file diffable and stream-readable; metadata events carry
    # no ts and sort first.
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {"traceEvents": events}, counts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("telemetry_dir",
                        help="gang telemetry dir holding per-rank "
                             "traces (trace.rank<r>.json or "
                             "rank<r>/trace.json) and/or serving "
                             "streams (trace.router.json, "
                             "trace.replica<r>.json)")
    parser.add_argument("-o", "--out", default=None,
                        help="output path (default: "
                             "<telemetry-dir>/trace.merged.json)")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.telemetry_dir):
        print(f"not a directory: {args.telemetry_dir}", file=sys.stderr)
        return 2
    merged, counts = merge_traces(args.telemetry_dir)
    if not counts:
        print(f"no per-rank traces under {args.telemetry_dir} "
              "(expected trace.rank<r>.json, rank<r>/trace.json, "
              "trace.router.json or trace.replica<r>.json)",
              file=sys.stderr)
        return 2
    out = args.out or os.path.join(args.telemetry_dir,
                                   "trace.merged.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    spans = [e["ts"] for e in merged["traceEvents"] if "ts" in e]
    dur_s = (max(spans) - min(spans)) / 1e6 if spans else 0.0
    per_stream = "  ".join(f"{label}:{n}"
                           for label, n in sorted(counts.items()))
    print(f"merged {sum(counts.values())} event(s) from "
          f"{len(counts)} stream(s) spanning {dur_s:.1f}s -> {out}")
    print(f"  {per_stream}")
    print("  open in ui.perfetto.dev (one process track per stream)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
