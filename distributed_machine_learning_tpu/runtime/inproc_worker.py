"""In-proc gang members — thread ranks for 64-128-rank chaos campaigns.

``runtime/gang_worker.py`` proves the resilience stack end to end with
one OS process per rank, which caps tested worlds at ~5 on the 1-core
CI host.  This module is the same worker contract — lock-step barrier
over the coordinator, scaling-rule-resolved global batches, exact
per-rank shards with an exactly-once consumption ledger, verified
checkpoints with the cumulative example cursor, fault injection keyed
on the ORIGINAL rank — rebuilt as a function a daemon THREAD can run
against an :class:`~.transport.InProcHub`: no subprocess spawn, no
shared filesystem, no per-rank jit compile.  ``gang_supervise`` runs
these callables through the same restart/shrink/grow/replace policy it
applies to processes (``supervisor._ThreadWorker`` adapts the Popen
surface), which is what lets tier-1 storm a 64-128-rank gang with
concurrent ``lose_rank``/``stall_rank``/``recover_rank`` firings and
world trajectories like 64→48→96 in seconds
(``tests/test_chaos_campaign.py``).

Differences from the subprocess worker, all forced by thread rank
semantics and all documented where they bite:

- **exits are exceptions**: a thread cannot ``os._exit`` without
  killing every other rank, so the injector's ``exit_fn`` raises
  :class:`WorkerExit` (carrying the same exit codes) and stall sleeps
  are interruptible (``sleep_fn`` observes the drain event — a thread
  cannot be SIGKILLed out of a ``time.sleep``);
- **shared checkpoint directory, rank-0 save**: the gang trains
  replicated dp state that is bit-identical across ranks, so current
  rank 0 saves ONE verified checkpoint per boundary into the shared
  directory and broadcasts the commit over the hub box; every rank
  then records the step for the election.  Restores are likewise
  rank-0-restore-then-broadcast (on a real pod this is the host-side
  broadcast after rank 0 reads shared storage) — the checkpoint itself
  is a real ``save_checkpoint``/``reshard_restore`` artifact the
  campaign tests re-restore at other worlds;
- **numpy math**: the toy quadratic step is a handful of vector ops —
  64 per-thread jit compiles would cost more than the whole campaign.
  The gradient is still the mean over the GLOBAL batch in canonical
  order, so params stay bit-identical across ranks, restarts, and
  world changes, and the loss floor obeys the scaling rules
  (``train/scaling.py``) exactly as in the subprocess worker.
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from distributed_machine_learning_tpu.runtime.coordinator import (
    GANG_ABORT_EXIT,
    GangCoordinator,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    FaultInjector,
)
from distributed_machine_learning_tpu.runtime.transport import (
    InProcHub,
    InProcTransport,
    TransportError,
)


class WorkerExit(Exception):
    """An in-proc rank leaving with an exit code — the thread analogue
    of ``os._exit`` (``supervisor._ThreadWorker`` turns it back into
    the Popen-style returncode the gang policy reads)."""

    def __init__(self, code: int):
        super().__init__(f"worker exit {code}")
        self.code = int(code)


@dataclasses.dataclass
class InprocGangConfig:
    """One campaign's worker parameters — the ``--flags`` of
    ``gang_worker`` as a value the thread closures share."""

    ckpt_dir: str                  # SHARED checkpoint directory
    steps: int = 12
    save_every: int = 5
    global_batch: int = 64
    scaling_rule: str = "pinned"
    base_world: int | None = None  # anchor world (default: launch world)
    base_lr: float = 0.5
    feature_dim: int = 8
    heartbeat_interval: float = 0.05
    peer_timeout: float = 2.0
    faults: str | None = None
    seed: int = 0
    step_sleep: float = 0.0


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 arrays — a
    high-quality stateless hash, so every (example id, coordinate)
    cell is an independent draw (no cross-id structure a batch mean
    could cancel against)."""
    x = (x + np.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def example_batch(start: int, count: int, dim: int) -> np.ndarray:
    """The global batch whose row ``j`` is example ``start + j``,
    generated from the example id ALONE (world/batch-partition
    independent, like ``gang_worker._global_batch_at``) but fully
    vectorized: 128 ranks each regenerate the global batch every step,
    so per-row RNG construction would dominate the campaign.

    Cells are iid-like uniform draws scaled to zero mean and UNIT
    variance — the batch mean's variance must scale exactly 1/B, or
    the stationary loss floor stops obeying the scaling rules
    (``train/scaling.py``) the trajectory campaigns assert against."""
    ids = np.arange(start, start + count, dtype=np.uint64)[:, None]
    k = np.arange(dim, dtype=np.uint64)[None, :]
    cells = _splitmix64(ids * np.uint64(dim) + k
                        + np.uint64(0x5DEECE66D))
    u = cells.astype(np.float64) * (1.0 / 2.0 ** 64)  # uniform [0, 1)
    return (np.sqrt(12.0) * (u - 0.5)).astype(np.float32)


def _interruptible(stop_event, coord):
    def sleep(seconds: float) -> None:
        deadline = time.monotonic() + float(seconds)
        while time.monotonic() < deadline:
            if stop_event.is_set() or coord.aborted is not None:
                return  # the gang is coming down; the stall is moot
            time.sleep(min(0.05, max(deadline - time.monotonic(), 0.0)))

    return sleep


def _await_box(hub: InProcHub, key, stop_event, coord,
               timeout_s: float) -> object:
    """Wait for rank 0's broadcast under ``key`` — drain/abort-aware,
    bounded (rank 0 may be the rank a fault just killed; the abort
    machinery owns that case and this wait must not outlive it)."""
    deadline = time.monotonic() + timeout_s
    missing = object()
    while time.monotonic() < deadline:
        value = hub.box_get(key, missing)
        if value is not missing:
            return value
        if stop_event.is_set():
            raise WorkerExit(143)
        if coord.aborted is not None:
            raise WorkerExit(GANG_ABORT_EXIT)
        time.sleep(0.002)
    return None


def run_inproc_worker(cfg: InprocGangConfig, hub: InProcHub, rank: int,
                      attempt: int, world: int, orig_rank: int,
                      stop_event) -> int:
    """One thread rank of an in-proc gang, to completion — the
    ``gang_worker.main`` loop against the hub transport.  Returns 0 on
    a clean finish; raises :class:`WorkerExit` for every abort/fault
    exit path."""
    from distributed_machine_learning_tpu.runtime.mesh import ShardSpec
    from distributed_machine_learning_tpu.data.sharding import (
        exact_shard_indices,
    )
    from distributed_machine_learning_tpu.train.checkpoint import (
        checkpoint_cursor,
        checkpoint_extra,
        latest_checkpoint,
        reshard_restore,
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.scaling import ScalingRule

    tx = InProcTransport(hub, bind_epoch=True)
    events = FaultEvents()
    injector = FaultInjector.from_flags(
        cfg.faults, seed=cfg.seed, horizon=max(cfg.steps, 2),
        rank=orig_rank,
    )
    coord = GangCoordinator(
        None, rank=rank, world=world, transport=tx,
        heartbeat_interval_s=cfg.heartbeat_interval,
        peer_timeout_s=cfg.peer_timeout, events=events,
        on_abort=lambda reason: None,  # thread mode: flag, never exit
    )
    coord.modeled_time = hub.netmodel is not None
    if injector is not None:
        injector.current_rank = rank
        injector.exit_fn = _raise_worker_exit
        injector.sleep_fn = _interruptible(stop_event, coord)
        # The digital-twin seam: gray link faults mutate the
        # hub-scoped network model (None on non-twin campaigns — a
        # gray fault firing without a model is a loud config error).
        injector.netmodel = hub.netmodel
        injector.attach_ledger(tx)
    coord.start()

    base_world = cfg.base_world if cfg.base_world else world
    rule = ScalingRule(cfg.scaling_rule, base_lr=cfg.base_lr,
                       base_global_batch=cfg.global_batch,
                       base_world=base_world)
    ws = rule.at_world(world)
    global_batch, lr = ws.global_batch, ws.lr
    local_ids = exact_shard_indices(global_batch, rank, world)

    try:
        # -- resume: rank 0 restores the shared checkpoint, the hub box
        # broadcasts the result (the host-side broadcast of a pod).
        with coord.suspend():
            key = ("restore", attempt)
            if rank == 0:
                latest = latest_checkpoint(cfg.ckpt_dir, events=events)
                if latest is None:
                    bcast = {"step": 0}
                else:
                    state, _spec = reshard_restore(
                        latest, world=world, events=events,
                        files_verified=True)
                    step0 = int(np.asarray(state.step))
                    cursor = checkpoint_cursor(latest)
                    ex = checkpoint_extra(latest).get("example_cursor")
                    start = cursor if cursor is not None else step0
                    bcast = {
                        "step": start,
                        "restored_step": step0,
                        "example_cursor": (int(ex) if isinstance(ex, int)
                                           else start * global_batch),
                        "w": np.array(np.asarray(state.params["w"]),
                                      copy=True),
                    }
                hub.box_put(key, bcast)
            else:
                bcast = _await_box(hub, key, stop_event, coord,
                                   timeout_s=4 * cfg.peer_timeout)
                if bcast is None:
                    raise WorkerExit(GANG_ABORT_EXIT)
            start = int(bcast["step"])
            start_examples = int(bcast.get("example_cursor",
                                           start * global_batch))
            w = (np.array(bcast["w"], copy=True) if "w" in bcast
                 else np.zeros((cfg.feature_dim,), np.float32))
            if "restored_step" in bcast:
                # The broadcast is this rank's proof the checkpoint is
                # whole — record it so the next election can agree on
                # it even if no further save lands.
                coord.record_valid_step(int(bcast["restored_step"]))
            coord.beat(step=start)

        batches = range(start, cfg.steps)
        if injector is not None:
            batches = injector.wrap_batches(batches, events, start=start)

        for idx in batches:
            t_start = time.perf_counter()
            if not coord.wait_for_peers(idx, stop=stop_event.is_set):
                raise WorkerExit(GANG_ABORT_EXIT
                                 if coord.aborted is not None else 143)
            t_barrier = time.perf_counter()
            ex_cursor = start_examples + (idx - start) * global_batch
            xs = example_batch(ex_cursor, global_batch, cfg.feature_dim)
            loss = float(w @ w)  # ||w - w*||^2 BEFORE the update, w*=0
            w = w - lr * (w - xs.mean(0))
            t_end = time.perf_counter()
            tx.append_consumed(orig_rank, {
                "attempt": attempt, "world": world, "rank": rank,
                "orig_rank": orig_rank, "step": idx,
                "example_cursor": ex_cursor,
                "global_batch": global_batch,
                "ids": [ex_cursor + int(j) for j in local_ids],
                "loss": loss,
            })
            if hub.netmodel is not None:
                # Digital twin: report the MODELED step time — compute
                # plus this rank's ring send schedule over the modeled
                # links — instead of the measured thread CPU time.  A
                # gray-degraded rank's dt inflates while healthy ranks
                # hold baseline, which is the straggler detector's
                # input signal; rank 0 advances the gang's virtual
                # clock (and the twin gauge) by the gang-wide step
                # (the max over ranks is what a lock-step barrier
                # costs, but per-rank reporting must stay per-rank so
                # the detector can attribute the inflation).
                dt = hub.netmodel.step_time(orig_rank)
                coord.observe_step(idx + 1, dt, {
                    "barrier_wait_s": 0.0,
                    "compute_s": hub.netmodel.compute_s,
                    "modeled_net_s": dt - hub.netmodel.compute_s,
                })
                if rank == 0:
                    step_max = max(hub.netmodel.step_time(r)
                                   for r in range(world))
                    hub.netmodel.clock.advance(step_max)
                    _set_twin_gauge(step_max)
            else:
                coord.observe_step(idx + 1, t_end - t_start, {
                    "barrier_wait_s": t_barrier - t_start,
                    "compute_s": t_end - t_barrier,
                })
            if (idx + 1) % cfg.save_every == 0 or idx + 1 == cfg.steps:
                save_step = idx + 1
                with coord.suspend():
                    key = ("saved", attempt, save_step)
                    if rank == 0:
                        state = _train_state(w, save_step)
                        save_checkpoint(
                            cfg.ckpt_dir, state, cursor=save_step,
                            shard_spec=ShardSpec("dp", world=world),
                            extra_payload={
                                "example_cursor":
                                    ex_cursor + global_batch,
                                "world": world,
                                "scaling_rule": rule.as_dict(),
                            },
                        )
                        hub.box_put(key, True)
                        coord.record_valid_step(save_step)
                    elif _await_box(hub, key, stop_event, coord,
                                    timeout_s=4 * cfg.peer_timeout):
                        # Only a signaled commit is recorded: a vote
                        # for a save that never landed would be
                        # filtered by the election's on-disk validity
                        # check anyway, but there is no reason to cast
                        # it.
                        coord.record_valid_step(save_step)
            if cfg.step_sleep:
                injector_sleep = _interruptible(stop_event, coord)
                injector_sleep(cfg.step_sleep)
        coord.finish()
        return 0
    except TransportError as exc:
        # Stale epoch (this member was drained and the state cleared)
        # or a severed channel: die like the partitioned process the
        # supervisor already knows how to handle.
        raise WorkerExit(GANG_ABORT_EXIT) from exc
    finally:
        coord.stop()


def _raise_worker_exit(code: int) -> None:
    raise WorkerExit(code)


def _set_twin_gauge(step_s: float) -> None:
    """Publish the gang-wide modeled step time (the straggler-inclusive
    max) as the ``modeled_step_time_s`` gauge — the twin's one-number
    health readout on dashboards."""
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        tel.registry.gauge("modeled_step_time_s").set(step_s)


def _train_state(w: np.ndarray, step: int):
    """A real TrainState around the toy weight vector — what makes the
    campaign's checkpoints first-class ``save_checkpoint`` artifacts
    (manifested, verified, reshard-restorable at any world)."""
    from distributed_machine_learning_tpu.train.state import TrainState

    state = TrainState.create(
        params={"w": np.array(w, np.float32, copy=True)}
    )
    return state.replace(step=np.asarray(step, np.int32))


def run_inproc_spare(cfg: InprocGangConfig, hub: InProcHub,
                     orig_rank: int, attempt: int, stop_event) -> int:
    """The warm-spare loop, thread form: announce on the join channel
    (refresh = liveness) with the newest VERIFIED shared-directory
    checkpoint step as the prefetch cursor.  In the shared-directory
    layout the prefetch copy itself is a no-op — the data is already
    local — so a spare's promotion cost is exactly one restore, the
    same O(restore) contract as the subprocess spare."""
    from distributed_machine_learning_tpu.train.checkpoint import (
        latest_checkpoint,
    )

    tx = InProcTransport(hub, bind_epoch=True)
    prefetched: int | None = None
    seen_names: list[str] | None = None
    while not stop_event.is_set():
        try:
            names = sorted(
                n for n in os.listdir(cfg.ckpt_dir)
                if n.startswith("step_"))
        except OSError:
            names = []
        if names != seen_names:
            seen_names = names
            found = latest_checkpoint(cfg.ckpt_dir)
            if found is not None:
                prefetched = int(os.path.basename(found)[5:])
        try:
            tx.announce_join(orig_rank, {
                "rank": int(orig_rank), "spare": True,
                "prefetched_step": prefetched, "time": time.time(),
            })
        except TransportError:
            return 0  # drained attempt's epoch: retire quietly
        stop_event.wait(cfg.heartbeat_interval)
    return 0


def inproc_worker_cmds(cfg: InprocGangConfig, hub: InProcHub):
    """(worker_cmd, spare_cmd) factories for ``gang_supervise``: each
    returns a CALLABLE (not an argv list), which the supervisor runs
    as an in-proc daemon thread (``_ThreadWorker``)."""

    def worker_cmd(rank: int, attempt: int, world: int,
                   orig_rank: int):
        def run(stop_event):
            return run_inproc_worker(cfg, hub, rank, attempt, world,
                                     orig_rank, stop_event)

        run.__name__ = f"inproc-r{rank}-o{orig_rank}-a{attempt}"
        return run

    def spare_cmd(orig_rank: int, attempt: int):
        def run(stop_event):
            return run_inproc_spare(cfg, hub, orig_rank, attempt,
                                    stop_event)

        run.__name__ = f"inproc-spare{orig_rank}-a{attempt}"
        return run

    return worker_cmd, spare_cmd
