"""Multi-host bootstrap: the reference's CLI flags → JAX's coordination service.

The reference rendezvouses over raw TCP:
``dist.init_process_group("gloo", init_method="tcp://"+master_ip,
world_size=num_nodes, rank=rank)`` (``part2/2a/main.py:197``), with flags
``--master-ip`` (default ``127.0.1.1:8000``), ``--rank``, ``--num-nodes``
(``part2/2a/main.py:210-218``).  The north-star requires keeping those
flags verbatim; they map 1:1 onto ``jax.distributed.initialize``:

    --master-ip  → coordinator_address
    --num-nodes  → num_processes
    --rank       → process_id

Single-host multi-chip runs need none of this — the local mesh covers all
chips — so ``num_nodes == 1`` skips initialization entirely (exactly as
the reference's part1 never calls init_process_group).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

# Reference defaults (part2/2a/main.py:213-215).
DEFAULT_MASTER_IP = "127.0.1.1:8000"


@dataclass
class DistributedContext:
    num_nodes: int
    rank: int
    master_ip: str
    initialized: bool

    @property
    def process_index(self) -> int:
        return jax.process_index() if self.initialized else 0

    def shutdown(self) -> None:
        """Counterpart of ``dist.destroy_process_group()`` (part2/2a/main.py:207)."""
        if self.initialized:
            jax.distributed.shutdown()


def initialize_from_flags(
    master_ip: str = DEFAULT_MASTER_IP,
    rank: int = 0,
    num_nodes: int = 1,
) -> DistributedContext:
    """Bring up the JAX coordination service iff this is a multi-node run."""
    if num_nodes > 1:
        jax.distributed.initialize(
            coordinator_address=master_ip,
            num_processes=num_nodes,
            process_id=rank,
        )
        return DistributedContext(num_nodes, rank, master_ip, initialized=True)
    return DistributedContext(num_nodes, rank, master_ip, initialized=False)
