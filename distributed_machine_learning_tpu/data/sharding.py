"""Deterministic data sharding with DistributedSampler semantics.

The reference shards with
``DistributedSampler(training_set, rank=rank, num_replicas=nodes,
shuffle=False, seed=69143)`` (``part2/2a/main.py:158-159``).  torch's
sampler with shuffle off does:

    indices = [0, 1, ..., N-1]
    pad with the head of the list until len % num_replicas == 0
    take indices[rank::num_replicas]          # rank-strided

so rank r sees samples r, r+W, r+2W, ...  We reproduce exactly that, so a
step's global batch across W ranks is the same set of samples the
reference's W gloo workers consumed — the precondition for the
numerical-equivalence tests (SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np


def shard_indices(
    num_samples: int,
    rank: int,
    num_replicas: int,
    shuffle: bool = False,
    seed: int = 69143,
    epoch: int = 0,
) -> np.ndarray:
    """Indices this rank consumes, DistributedSampler-compatible."""
    if not 0 <= rank < num_replicas:
        raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
    if shuffle:
        # torch shuffles with a generator seeded seed+epoch.
        rng = np.random.default_rng(seed + epoch)
        indices = rng.permutation(num_samples)
    else:
        indices = np.arange(num_samples)
    # Pad by wrapping from the head so every rank gets the same count.
    total = ((num_samples + num_replicas - 1) // num_replicas) * num_replicas
    if total > num_samples:
        indices = np.concatenate([indices, indices[: total - num_samples]])
    return indices[rank::num_replicas]


def exact_shard_indices(
    num_samples: int,
    rank: int,
    num_replicas: int,
    shuffle: bool = False,
    seed: int = 69143,
    epoch: int = 0,
) -> np.ndarray:
    """Indices this rank consumes under an EXACT partition: no wrap
    padding, so across all ranks every index appears exactly once
    (per-rank counts differ by at most one when ``num_replicas`` does
    not divide ``num_samples``).

    The elastic-rebalance primitive: when a gang shrinks from N to M
    survivors, re-evaluating this with ``num_replicas=M`` redistributes
    the epoch so every example is still visited exactly once —
    :func:`shard_indices`'s DistributedSampler padding would instead
    visit the wrapped head twice, which is fine for parity with torch
    but breaks the exactly-once accounting an elastic epoch must keep.
    Shuffle semantics match :func:`shard_indices` (generator seeded
    ``seed + epoch``), so the GLOBAL epoch order is identical for every
    world size — only the assignment of indices to ranks changes.
    """
    if not 0 <= rank < num_replicas:
        raise ValueError(
            f"rank {rank} out of range for {num_replicas} replicas"
        )
    if shuffle:
        rng = np.random.default_rng(seed + epoch)
        indices = rng.permutation(num_samples)
    else:
        indices = np.arange(num_samples)
    return indices[rank::num_replicas]
