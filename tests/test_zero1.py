"""ZeRO-1 optimizer-state sharding: must take exactly the step the
replicated mean-semantics DP baseline takes, with 1/N momentum memory."""

import jax
import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.parallel.strategies import get_strategy
from distributed_machine_learning_tpu.parallel.zero1 import (
    make_zero1_train_step,
    shard_zero1_state,
    zero1_memory_footprint,
    zero1_params,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.step import (
    make_train_step,
    shard_batch,
)


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    x = rng.integers(0, 256, (16, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, 16).astype(np.int32)
    return x, y


@pytest.mark.parametrize(
    "use_bn", [False, pytest.param(True, marks=pytest.mark.slow)]
)
def test_zero1_matches_replicated_ring(data, use_bn):
    """Two ZeRO-1 steps == two replicated ring (mean) steps: params track
    bitwise-ish, momentum shards reassemble to the replicated buffers."""
    x, y = data
    model = VGGTest(use_bn=use_bn)
    mesh = make_mesh(8)
    mx, my = shard_batch(mesh, x, y)

    ref_step = make_train_step(
        model, get_strategy("ring"), mesh=mesh, augment=False
    )
    ref = init_model_and_state(model)

    z1, unravel, n_elems = shard_zero1_state(init_model_and_state(model), mesh)
    z1_step = make_zero1_train_step(model, mesh, unravel, n_elems,
                                    augment=False)

    for _ in range(2):
        ref, ref_loss = ref_step(ref, mx, my)
        z1, z1_loss = z1_step(z1, mx, my)

    np.testing.assert_allclose(float(z1_loss), float(ref_loss), rtol=1e-5)
    got = zero1_params(z1, unravel, n_elems)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref.params), jax.tree_util.tree_leaves(got)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )
    # momentum shards reassemble to the replicated baseline's buffers
    from jax.flatten_util import ravel_pytree

    ref_mom = np.asarray(ravel_pytree(ref.momentum)[0])
    z1_mom = np.asarray(z1.momentum_shards)[: ref_mom.shape[0]]
    np.testing.assert_allclose(z1_mom, ref_mom, rtol=1e-4, atol=1e-6)
    if use_bn:
        for a, b in zip(
            jax.tree_util.tree_leaves(ref.batch_stats),
            jax.tree_util.tree_leaves(z1.batch_stats),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )


def test_zero1_momentum_is_sharded(data):
    x, y = data
    model = VGGTest()
    mesh = make_mesh(8)
    z1, unravel, n_elems = shard_zero1_state(init_model_and_state(model), mesh)
    # momentum: one shard per device; params: replicated everywhere
    assert len(z1.momentum_shards.sharding.device_set) == 8
    mom_shard = z1.momentum_shards.addressable_shards[0]
    assert mom_shard.data.shape[0] * 8 == z1.momentum_shards.shape[0]
    p_shard = z1.param_flat.addressable_shards[0]
    assert p_shard.data.shape == z1.param_flat.shape  # replicated


def test_zero1_overlap_bit_identical_to_sync(data):
    """The ISSUE-9 parity acceptance: the overlap-aware build (update
    program + separately-dispatched bucketed-ring gather) must take
    EXACTLY the sync build's trajectory — the gather is pure data
    movement and the update math is shared, so every state leaf is
    bitwise equal after several fixed-seed steps."""
    x, y = data
    model = VGGTest()
    mesh = make_mesh(8)
    mx, my = shard_batch(mesh, x, y)

    def run(overlap):
        z1, unravel, n_elems = shard_zero1_state(
            init_model_and_state(model), mesh
        )
        step = make_zero1_train_step(model, mesh, unravel, n_elems,
                                     augment=False, overlap=overlap)
        losses = []
        for _ in range(3):
            z1, loss = step(z1, mx, my)
            losses.append(float(loss))
        return z1, losses, unravel, n_elems

    sync, sync_losses, unravel, n_elems = run(False)
    ov, ov_losses, _, _ = run(True)
    assert sync_losses == ov_losses
    np.testing.assert_array_equal(
        np.asarray(sync.param_flat), np.asarray(ov.param_flat)
    )
    np.testing.assert_array_equal(
        np.asarray(sync.momentum_shards), np.asarray(ov.momentum_shards)
    )
    # The overlapped state's param_flat is the (in-flight) gather
    # output and must still be the replicated full vector checkpoints
    # and eval expect.
    from jax.sharding import PartitionSpec as P

    assert tuple(ov.param_flat.sharding.spec) in ((), (None,))
    for a, b in zip(
        jax.tree_util.tree_leaves(zero1_params(sync, unravel, n_elems)),
        jax.tree_util.tree_leaves(zero1_params(ov, unravel, n_elems)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero1_overlap_param_gather_telemetry(data, tmp_path):
    """With telemetry installed, the overlap step records a
    ``param_gather`` span per step (dispatch → observed ready, closed
    at the next consume), the train loop forwards it into the metrics
    rows as ``param_gather_s``, and ``tools/trace_summary.py`` renders
    the phase as overlapped."""
    from distributed_machine_learning_tpu.telemetry import (
        Telemetry,
        set_telemetry,
    )
    from distributed_machine_learning_tpu.train.loop import train_epoch

    x, y = data
    model = VGGTest()
    mesh = make_mesh(8)
    mx, my = shard_batch(mesh, x, y)
    z1, unravel, n_elems = shard_zero1_state(
        init_model_and_state(model), mesh
    )
    step = make_zero1_train_step(model, mesh, unravel, n_elems,
                                 augment=False, overlap=True)
    tel = Telemetry(tmp_path, flush_every=1)
    prev = set_telemetry(tel)
    try:
        train_epoch(step, z1, [(mx, my)] * 4, max_iters=4, telemetry=tel)
    finally:
        set_telemetry(prev)
        tel.close()

    import json as _json

    trace = (tmp_path / "trace.json").read_text()
    spans = [_json.loads(line.rstrip(",\n")) for line in
             trace.splitlines() if '"param_gather"' in line]
    assert spans, "no param_gather spans in the trace"
    rows = [_json.loads(line) for line in
            (tmp_path / "metrics.jsonl").read_text().splitlines()]
    gather_rows = [r for r in rows if "param_gather_s" in r]
    # The span closes at the NEXT step's consume: rows 1..3 carry it.
    assert gather_rows, "no param_gather_s metrics column"

    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "tools/trace_summary.py", str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "param_gather" in out.stdout
    assert "overlapped" in out.stdout


def test_zero1_memory_footprint():
    fp = zero1_memory_footprint(1000, 8)
    assert fp["replicated"] == 2 * 1000 * 4
    assert fp["zero1"] == (1000 + 1000 // 8) * 4  # params + 1/8 momentum
    assert fp["fsdp"] == 2 * (1000 // 8) * 4
    assert fp["fsdp"] < fp["zero1"] < fp["replicated"]
