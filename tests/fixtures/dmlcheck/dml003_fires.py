# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/fixture.py
"""DML003 firing case: raw orbax restore handed straight to a donating
step — the ISSUE 1 segfault class."""


def resume(ckptr, path, train_step, x, y):
    state = ckptr.restore(path)      # zero-copy tensorstore aliases
    return train_step(state, x, y)   # step donates: use-after-free
