"""Draft-from-target distillation — one command from a trained target
checkpoint to a servable speculative-decoding draft.

The measured speculative speedups (docs/PERF.md: 2.1× end-to-end)
require a draft that actually agrees with the target; round 4 got one
by hand-writing a second training run.  This entrypoint makes that a
single command (VERDICT r4 item 6)::

    python -m distributed_machine_learning_tpu.cli.distill \
        --target-ckpt-dir runs/lm  --d-model 512 --n-layers 8 \
        --draft-d-model 256 --draft-n-layers 2 \
        --data-dir corpus/ --ckpt-dir runs/draft

then serve both::

    python -m distributed_machine_learning_tpu.cli.generate \
        --ckpt-dir runs/lm --draft-ckpt-dir runs/draft --spec-gamma 4 ...

Training objective: Hinton logit distillation — soft cross-entropy
against the teacher's temperature-softened distribution (scaled T², so
gradients keep their magnitude as T grows) mixed with the hard
next-token CE on the same stream the target was trained on
(``--kd-weight`` / ``--ce-weight``).  The teacher runs frozen inside
the same jitted step; its params enter as ARGUMENTS (a closure-captured
tree of this size would be baked into the program as constants — the
tunnel's remote_compile rejects ≳100 MB of them).

The loop keeps the reference's measurement surface (loss print every
20, iteration-0-excluded timing — ``part1/main.py:32-58``); data comes
from ``--data-dir`` (byte-level corpus, ``data/text.py``) or the
deterministic synthetic stream, exactly as ``cli.lm``.
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target-ckpt-dir", dest="target_ckpt_dir", required=True,
                   help="cli.lm checkpoint of the TARGET (teacher) model")
    # Target architecture — must match the checkpoint (same contract as
    # cli.generate: checkpoints store arrays, not architecture).
    p.add_argument("--d-model", dest="d_model", default=256, type=int)
    p.add_argument("--n-layers", dest="n_layers", default=4, type=int)
    p.add_argument("--n-heads", dest="n_heads", default=8, type=int)
    p.add_argument("--n-kv-heads", dest="n_kv_heads", default=None, type=int)
    p.add_argument("--vocab", default=None, type=int,
                   help="default: byte-level 257 (data/text.py)")
    # Draft architecture — defaults give a ~4x-thinner 2-layer student.
    p.add_argument("--draft-d-model", dest="draft_d_model", default=None,
                   type=int, help="default: d_model // 2")
    p.add_argument("--draft-n-layers", dest="draft_n_layers", default=2,
                   type=int)
    p.add_argument("--draft-n-heads", dest="draft_n_heads", default=None,
                   type=int, help="default: n_heads // 2 (min 1)")
    p.add_argument("--draft-n-kv-heads", dest="draft_n_kv_heads",
                   default=None, type=int)
    # Distillation objective.
    p.add_argument("--kd-temperature", dest="kd_temperature", default=2.0,
                   type=float,
                   help="soften teacher/student logits by this factor for "
                        "the KD term (Hinton et al.); the KD loss scales "
                        "by T^2 to keep gradient magnitude T-invariant")
    p.add_argument("--kd-weight", dest="kd_weight", default=1.0, type=float)
    p.add_argument("--ce-weight", dest="ce_weight", default=0.5, type=float,
                   help="weight of the hard next-token CE mixed into the "
                        "objective (0 = pure distillation)")
    # Data + loop (cli.lm conventions).
    p.add_argument("--data-dir", dest="data_dir", default=None,
                   help="byte-level text corpus (data/text.py) — use the "
                        "TARGET's training corpus so the draft models the "
                        "distribution it will draft for; default: the "
                        "deterministic synthetic stream")
    p.add_argument("--seq-len", dest="seq_len", default=256, type=int)
    p.add_argument("--batch-size", dest="batch_size", default=8, type=int)
    p.add_argument("--max-iters", dest="max_iters", default=400, type=int)
    p.add_argument("--lr", default=None, type=float,
                   help="AdamW learning-rate override")
    p.add_argument("--compute-dtype", default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--ckpt-dir", dest="ckpt_dir", required=True,
                   help="write the distilled draft checkpoint here "
                        "(cli.generate --draft-ckpt-dir loads it)")
    return p


def make_distill_step(student_model, teacher_model, kd_weight: float,
                      ce_weight: float, kd_temperature: float):
    """Jitted ``step(state, teacher_params, tokens, targets) ->
    (state, (loss, kd, ce))``.  The teacher forward runs frozen in the
    same program (one HBM round-trip for its logits, no host sync); the
    student updates through the state's optimizer config."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.train.losses import (
        lm_cross_entropy,
    )
    from distributed_machine_learning_tpu.train.optimizers import (
        update_fn_for_config,
    )

    if kd_temperature <= 0:
        raise ValueError(
            f"kd_temperature must be > 0, got {kd_temperature}"
        )
    T = kd_temperature

    def step(state, tparams, tokens, targets):
        t_logits = teacher_model.apply({"params": tparams}, tokens)
        t_probs = jax.nn.softmax(
            t_logits.astype(jnp.float32) / T, axis=-1
        )
        t_probs = jax.lax.stop_gradient(t_probs)

        def loss_fn(params):
            s_logits = student_model.apply({"params": params}, tokens)
            # Soft cross-entropy H(teacher_T, student_T)·T² — equal to
            # KL(t‖s)·T² up to the teacher-entropy constant, so the
            # gradients are identical.
            s_logp = jax.nn.log_softmax(
                s_logits.astype(jnp.float32) / T, axis=-1
            )
            kd = -jnp.mean(jnp.sum(t_probs * s_logp, axis=-1)) * T * T
            ce = lm_cross_entropy(s_logits, targets)
            return kd_weight * kd + ce_weight * ce, (kd, ce)

        (loss, (kd, ce)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        new_params, new_momentum = update_fn_for_config(state.config)(
            state.params, state.momentum, grads, state.config,
            step=state.step,
        )
        new_state = state.replace(
            params=new_params, momentum=new_momentum, step=state.step + 1
        )
        return new_state, (loss, kd, ce)

    return jax.jit(step, donate_argnums=(0,))


def main(argv=None) -> None:
    args = make_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.cli.common import SEED
    from distributed_machine_learning_tpu.cli.generate import (
        _restore_lm_params,
    )
    from distributed_machine_learning_tpu.data.text import VOCAB_SIZE
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig
    from distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    vocab = args.vocab or VOCAB_SIZE
    dtype = (jnp.bfloat16 if args.compute_dtype == "bfloat16"
             else jnp.float32)
    teacher = TransformerLM(
        vocab_size=vocab, d_model=args.d_model, n_layers=args.n_layers,
        n_heads=args.n_heads, n_kv_heads=args.n_kv_heads,
        compute_dtype=dtype,
    )
    draft_heads = args.draft_n_heads or max(1, args.n_heads // 2)
    student = TransformerLM(
        vocab_size=vocab,
        d_model=args.draft_d_model or args.d_model // 2,
        n_layers=args.draft_n_layers,
        n_heads=draft_heads,
        n_kv_heads=args.draft_n_kv_heads,
        compute_dtype=dtype,
    )
    tparams = _restore_lm_params(args.target_ckpt_dir, args.n_layers)
    # Serving-dtype teacher: its logits are targets, not gradients.
    tparams = jax.tree_util.tree_map(
        lambda p: p.astype(dtype) if p.dtype == jnp.float32 else p, tparams
    )

    cfg = AdamWConfig()
    if args.lr is not None:
        cfg = cfg.replace(learning_rate=args.lr)
    state = init_lm_state(student, config=cfg)
    step = make_distill_step(student, teacher, args.kd_weight,
                             args.ce_weight, args.kd_temperature)

    if args.data_dir is not None:
        from distributed_machine_learning_tpu.data.text import (
            TextWindowLoader,
            load_corpus,
        )

        corpus = load_corpus(args.data_dir)
        print(f"corpus: {len(corpus)} tokens from {args.data_dir}")
        batches = iter(TextWindowLoader(
            corpus, args.batch_size, args.seq_len, seed=SEED,
        ))
    else:
        from distributed_machine_learning_tpu.cli.lm import synthetic_tokens

        rng = np.random.default_rng(SEED)

        def _synthetic():
            # cli.lm's canonical stream — the one the target trained on.
            while True:
                block = synthetic_tokens(rng, args.batch_size,
                                         args.seq_len, vocab)
                yield block[:, :-1], block[:, 1:]

        batches = _synthetic()

    n_student = sum(
        int(np.prod(p.shape))
        for p in jax.tree_util.tree_leaves(state.params)
    )
    print(f"distill: teacher d{args.d_model}x{args.n_layers}L -> "
          f"draft d{student.d_model}x{student.n_layers}L "
          f"({n_student / 1e6:.2f}M params), T={args.kd_temperature}, "
          f"kd={args.kd_weight}, ce={args.ce_weight}")

    total = 0.0
    t_prev = None
    loss = kd = ce = None
    for it in range(args.max_iters):
        x, y = next(batches)
        state, (loss, kd, ce) = step(
            state, tparams, jnp.asarray(x), jnp.asarray(y)
        )
        # Reference timing protocol: fetch the loss (real step time on a
        # tunneled chip), exclude iteration 0 (part1/main.py:53-58).
        loss_v = float(loss)
        # Monotonic clock for the iteration deltas (dmlcheck DML001):
        # wall clocks step under NTP slew and make timing rows lie.
        now = time.perf_counter()
        if t_prev is not None:
            total += now - t_prev
        t_prev = now
        if it % 20 == 0:
            print(f"iter {it}: loss {loss_v:.4f} "
                  f"(kd {float(kd):.4f}, ce {float(ce):.4f})", flush=True)
    if args.max_iters > 1:
        print(f"Total execution time: {total:.2f}s  "
              f"Average: {total / (args.max_iters - 1):.4f}s/iter")
    path = save_checkpoint(args.ckpt_dir, jax.block_until_ready(state))
    print(f"draft checkpoint: {path}")


if __name__ == "__main__":
    main()
