"""Ring FLASH attention (ops/pallas/ring_flash_attention.py, interpret
mode on the CPU mesh): the carry-threaded flash-kernel ring must match
dense attention — forward and all three gradients — and the einsum ring
it upgrades."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.ops.pallas.ring_flash_attention import (
    ring_flash_self_attention,
)
from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
)
from distributed_machine_learning_tpu.runtime.mesh import (
    make_mesh,
    shard_map_no_check,
)

B, L, H, D = 2, 64, 2, 8


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(69143)
    return tuple(
        jnp.asarray(rng.standard_normal((B, L, H, D), dtype=np.float32))
        for _ in range(3)
    )


def _ring_fn(n_shards):
    mesh = make_mesh(n_shards, ("seq",))

    def local(q, k, v):
        return ring_flash_self_attention(q, k, v, "seq", n_shards)

    spec = P(None, "seq")
    return jax.jit(shard_map_no_check(
        local, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
    ))


@pytest.mark.parametrize(
    "n_shards",
    [2, pytest.param(4, marks=pytest.mark.slow)],
)
def test_ring_flash_matches_dense_forward(qkv, n_shards):
    q, k, v = qkv
    np.testing.assert_allclose(
        np.asarray(_ring_fn(n_shards)(q, k, v)),
        np.asarray(dense_self_attention(q, k, v)),
        rtol=2e-5,
        atol=2e-6,
    )


def test_ring_flash_backward_matches_dense(qkv):
    q, k, v = qkv
    n_shards = 2
    cot = jnp.asarray(
        np.random.default_rng(1).standard_normal((B, L, H, D),
                                                 dtype=np.float32)
    )
    ring = _ring_fn(n_shards)

    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(ring(q, k, v) * cot), argnums=(0, 1, 2)
    )(q, k, v)
    g_dense = jax.grad(
        lambda q, k, v: jnp.sum(dense_self_attention(q, k, v) * cot),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want, name in zip(g_ring, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=f"d{name} mismatch",
        )


def test_ring_flash_gqa_matches_repeated_dense():
    """Narrow-KV ring: GQA chunks rotate unrepeated; output and all three
    grads must match dense attention over explicitly repeated K/V."""
    rng = np.random.default_rng(11)
    Hq, Hkv = 4, 2
    n_shards = 2
    q = jnp.asarray(rng.standard_normal((B, L, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, L, Hkv, D)), jnp.float32)
    g = jnp.asarray(rng.standard_normal((B, L, Hq, D)), jnp.float32)

    mesh = make_mesh(n_shards, ("seq",))
    spec = P(None, "seq")
    ring = jax.jit(shard_map_no_check(
        lambda a, b, c: ring_flash_self_attention(a, b, c, "seq", n_shards),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    ))

    def rep(t):
        return jnp.repeat(t, Hq // Hkv, axis=2)

    out, ring_vjp = jax.vjp(ring, q, k, v)
    ref, dense_vjp = jax.vjp(
        lambda q, k, v: dense_self_attention(q, rep(k), rep(v)), q, k, v
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    for got, want, name in zip(ring_vjp(g), dense_vjp(g), "qkv"):
        assert got.shape == want.shape, name
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5,
            err_msg=f"d{name} mismatch",
        )


def test_ring_flash_model_trains(mesh8):
    """attn_impl='ring_flash' end to end: a context-parallel LM train step
    on a (batch × seq) mesh produces a finite loss and updated params."""
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
        make_lm_train_step,
        shard_lm_batch,
    )

    lm_mesh = make_mesh(8, ("batch", "seq"), (2, 4))
    model = TransformerLM(
        vocab_size=32, d_model=16, n_layers=1, n_heads=2,
        attn_impl="ring_flash",
    )
    state = init_lm_state(model)
    step = make_lm_train_step(model, mesh=lm_mesh)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 32, (4, 33)).astype(np.int32)
    x, y = shard_lm_batch(lm_mesh, toks[:, :-1], toks[:, 1:])
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))
