"""Flash attention (causal) as a Pallas TPU kernel.

The hot op of the transformer family, written for the hardware per the
Pallas playbook (/opt/skills/guides/pallas_guide.md): the L×L score
matrix never hits HBM — each grid step holds one Q block in VMEM, streams
K/V blocks through the MXU, and maintains the online-softmax running
(max, normalizer, accumulator) triple in fp32 registers.  Causal blocks
entirely above the diagonal are skipped via the loop bound, so the kernel
does ~half the FLOPs of dense attention.

Differentiation: Pallas kernels are not auto-differentiable, so the op
carries a ``jax.custom_vjp`` whose backward recomputes attention with the
standard XLA einsum formulation (flash-style forward memory savings, dense
backward — the usual first-rung trade; a full Pallas backward kernel is a
later optimization).

On non-TPU backends the kernel runs in interpreter mode, so tests on the
CPU mesh exercise the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports only resolve fully on TPU-capable installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, scale):
    """One Q block vs all causally-visible K/V blocks, online softmax."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, D]
    D = q.shape[-1]
    q_start = qi * block_q
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)

    # K blocks at or below the diagonal: indices [0, num_k).
    num_k = (q_start + block_q + block_k - 1) // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_start = kb * block_k
        k = k_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_fwd(q, k, v, block_q: int, block_k: int):
    """q/k/v: [BH, L, D] → [BH, L, D]."""
    BH, L, D = q.shape
    scale = 1.0 / (D**0.5)
    grid = (BH, L // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, scale=scale
    )
    if _HAS_PLTPU:
        q_spec = pl.BlockSpec(
            (1, block_q, D), lambda bh, qi: (bh, qi, 0),
            memory_space=pltpu.VMEM,
        )
        kv_spec = pl.BlockSpec(
            (1, L, D), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
        )
    else:  # pragma: no cover
        q_spec = pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0))
        kv_spec = pl.BlockSpec((1, L, D), lambda bh, qi: (bh, 0, 0))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        interpret=_interpret(),
    )(q, k, v)


def _dense_bwd(q, k, v, g):
    """Standard causal-softmax attention VJP in XLA ops ([BH, L, D])."""
    BH, L, D = q.shape
    scale = 1.0 / (D**0.5)
    qf, kf, vf, gf = (a.astype(jnp.float32) for a in (q, k, v, g))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    pos = jnp.arange(L)
    causal = pos[:, None] >= pos[None, :]
    s = jnp.where(causal[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    dp = jnp.einsum("bqd,bkd->bqk", gf, vf)
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _pick_block(L: int, target: int = 128) -> int:
    for b in (target, 64, 32, 16, 8, 4, 2, 1):
        if b <= L and L % b == 0:
            return b
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _flash_core(q, k, v):
    B, L, H, D = q.shape
    blk = _pick_block(L)
    fold = lambda a: a.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    out = _flash_fwd(fold(q), fold(k), fold(v), blk, blk)
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _flash_core_fwd(q, k, v):
    return _flash_core(q, k, v), (q, k, v)


def _flash_core_bwd(res, g):
    q, k, v = res
    B, L, H, D = q.shape
    fold = lambda a: a.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    dq, dk, dv = _dense_bwd(fold(q), fold(k), fold(v), fold(g))
    unfold = lambda a: a.reshape(B, H, L, D).transpose(0, 2, 1, 3)
    return unfold(dq), unfold(dk), unfold(dv)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_self_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal flash attention: [B, L, H, D] in and out.

    Drop-in for ``ops.ring_attention.dense_self_attention`` on contiguous
    (offset-0) sequences — the unsharded model path.
    """
    return _flash_core(q, k, v)
