"""Checkpoint / resume via orbax.

The reference has no checkpointing at all — no ``state_dict``/save/load
anywhere in its 908 LoC (SURVEY.md §5: runs are 40 iterations, results
transcribed by hand).  This subsystem goes beyond parity: save the full
:class:`TrainState` (params, momentum buffers, BN running stats, step
counter, augmentation PRNG key) plus the SGD hyperparameters, and resume
bit-exactly.

TPU-native notes: orbax's OCDBT-backed PyTree checkpointing writes each
host's addressable shards, so the same API covers single-chip and
multi-host pod saves; ``restore`` takes an ``abstract_state`` template so
arrays come back with the correct shardings placed onto the mesh (or as
host arrays when no template is given).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from distributed_machine_learning_tpu.train.state import TrainState

_CONFIG_FILE = "sgd_config.json"
_STATE_DIR = "state"


def _tree_bytes(tree) -> int:
    """Total array payload of a pytree — the telemetry "bytes" figure
    for save/restore spans (shard-local on multi-host runs: each host
    writes its own addressable shards)."""
    return sum(
        int(getattr(leaf, "nbytes", 0) or 0)
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def _record_ckpt_io(tel, kind: str, start_s: float, end_s: float,
                    step: int, nbytes: int) -> None:
    """Span + registry entries for one checkpoint save/restore.  Callers
    guard on ``get_telemetry()`` BEFORE computing ``step``/``nbytes`` —
    both cost a host sync / pytree walk that the telemetry-off default
    must not pay."""
    dur = end_s - start_s
    tel.tracer.complete(f"checkpoint_{kind}", start_s, end_s, step=step,
                        bytes=nbytes)
    tel.registry.histogram(f"checkpoint_{kind}_seconds").observe(dur)
    tel.registry.counter(f"checkpoint_{kind}_bytes_total").inc(nbytes)
    tel.registry.counter(f"checkpoint_{kind}s_total").inc()
    if dur > 0:
        tel.registry.gauge(f"checkpoint_{kind}_mb_per_s").set(
            nbytes / dur / 1e6
        )


@jax.jit
def _copy_arrays(arrays: list) -> list:
    """Identity copy through XLA — every output is a jit-owned buffer.

    Non-donating by construction, so the inputs are left intact.
    """
    import jax.numpy as jnp

    return [jnp.asarray(a).copy() for a in arrays]


def fresh_buffers(tree):
    """Re-materialize every array leaf of ``tree`` into an XLA-owned
    buffer (via a non-donating jitted copy); non-array leaves pass
    through untouched.

    The ONE sanctioned conversion before handing arrays to a
    ``donate_argnums`` step.  Arrays from orbax/tensorstore restores, or
    zero-copied host numpy (the CPU backend aliases any 64-byte-aligned
    numpy buffer), are backed by memory XLA does not own; donating them
    frees that memory with XLA's allocator — heap corruption that
    segfaults at some LATER free.  Jit outputs are the same ownership
    class init states come from, which donation handles correctly.
    Uncommitted inputs stay uncommitted (the dp/ring shard_map paths
    rely on this).  Used by :func:`restore_checkpoint`, the
    supervisor's init-state copy, and the LM CLI's commitment fix-up.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = list(leaves)
    idx = [i for i, x in enumerate(leaves)
           if isinstance(x, (jax.Array, np.ndarray))]
    if idx:
        copied = _copy_arrays([leaves[i] for i in idx])
        for i, c in zip(idx, copied):
            out[i] = c
    return jax.tree_util.tree_unflatten(treedef, out)


def _state_pytree(state: TrainState) -> dict:
    """The array-valued part of TrainState (SGDConfig is static metadata)."""
    return {
        "params": state.params,
        "momentum": state.momentum,
        "batch_stats": state.batch_stats,
        "step": state.step,
        "rng": state.rng,
    }


def save_checkpoint(directory: str | os.PathLike, state: TrainState,
                    layout: str | None = None, cursor: int | None = None,
                    mid_save_hook=None, keep_last_n: int | None = None) -> str:
    """Write `state` under `directory/step_<n>/`; returns the path written.

    Only process 0's metadata file is written once; array shards are saved
    by every host (orbax handles the multi-host coordination).

    ``layout``: optional tag naming the PARAMETER layout (e.g. the
    pipeline schedules' block-stacking orders, which share one tree
    structure but permute the layers) — recorded so a resume under a
    different layout can be rejected instead of silently loading
    permuted weights (``checkpoint_layout``).

    ``cursor``: optional data-stream position (batches consumed).  The
    step counter alone under-counts it once the non-finite-gradient
    guard has skipped a batch, so the supervisor records the true
    position for exact replay (``checkpoint_cursor``).  Stored in the
    config payload — written last — so a checkpoint is never complete
    with a missing cursor.

    ``mid_save_hook``: test/chaos hook called between the state write
    and the config write — the crash window ``_is_complete`` guards
    (``runtime/faults.py`` kills here to prove resume falls back).

    ``keep_last_n``: if set, garbage-collect older checkpoints after
    this save completes (``gc_checkpoints``) so supervised long runs
    don't fill the disk.
    """
    directory = os.path.abspath(os.fspath(directory))
    step = int(jax.device_get(state.step))
    path = os.path.join(directory, f"step_{step}")
    t0 = time.perf_counter()
    with ocp.PyTreeCheckpointer() as ckptr:
        # force=True: re-saving the same step (e.g. rerunning a crashed job
        # into the same --ckpt-dir) overwrites instead of raising.
        ckptr.save(os.path.join(path, _STATE_DIR), _state_pytree(state),
                   force=True)
    if mid_save_hook is not None:
        mid_save_hook()
    if jax.process_index() == 0:
        with open(os.path.join(path, _CONFIG_FILE), "w") as f:
            # Record the config class so restore rebuilds the right
            # optimizer config (LARSConfig carries extra fields that
            # SGDConfig(**...) would reject).
            payload = {"__class__": type(state.config).__name__,
                       **dataclasses.asdict(state.config)}
            if layout is not None:
                payload["__layout__"] = layout
            if cursor is not None:
                payload["__cursor__"] = int(cursor)
            json.dump(payload, f)
        if keep_last_n is not None:
            gc_checkpoints(directory, keep_last_n)
    # A save that died above (e.g. the injected kill) records no span —
    # the torn attempt is visible as the fault instant + missing save.
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        _record_ckpt_io(tel, "save", t0, time.perf_counter(), step,
                        _tree_bytes(_state_pytree(state)))
    return path


def gc_checkpoints(directory: str | os.PathLike, keep_last_n: int
                   ) -> list[str]:
    """Delete old checkpoints, keeping the newest ``keep_last_n``
    *complete* ones; returns the paths removed.

    The newest complete checkpoint is never deleted (it is the resume
    anchor — losing it turns every later fault into a from-scratch
    restart).  Incomplete directories are removed only when a complete
    checkpoint with a HIGHER step exists: an older incomplete dir is a
    crash leftover, but a newer one may be an in-flight async save that
    simply hasn't committed yet.
    """
    import shutil

    if keep_last_n < 1:
        raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    complete = [
        s for s in sorted(steps, reverse=True)
        if _is_complete(os.path.join(directory, f"step_{s}"))
    ]
    keep = set(complete[:keep_last_n])
    newest_complete = complete[0] if complete else None
    removed = []
    for s in steps:
        if s in keep:
            continue
        is_complete = s in complete
        if not is_complete and (newest_complete is None
                                or s >= newest_complete):
            continue  # possibly an in-flight save — leave it alone
        path = os.path.join(directory, f"step_{s}")
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed


class AsyncCheckpointWriter:
    """Non-blocking checkpoint saves — training continues while orbax
    serializes in a background thread.

    At LM scale a synchronous save stalls every step for seconds; the
    async writer hides that behind compute (the standard production
    setup).  Layout and completeness semantics are identical to
    :func:`save_checkpoint`: orbax writes the state dir to a temp name
    and renames atomically on finish, and the config file alone does not
    satisfy ``_is_complete`` — so an in-flight or crashed async save is
    invisible to ``latest_checkpoint`` until it actually lands.

    Call :meth:`wait` before process exit (or rely on ``close``); a new
    ``save`` transparently waits for the previous one (orbax serializes
    saves on one thread).

    Write-order invariant: the config file is deferred until
    ``wait_until_finished`` of ITS OWN save has returned (flushed at the
    next ``save``/``wait``/``close``).  Writing it eagerly would break
    the ``_is_complete`` contract — a crash after the config landed but
    before orbax committed the state dir... cannot happen (orbax renames
    atomically), but the converse ordering CAN: an eager config plus a
    crashed orbax *rename race* would present a complete-looking
    checkpoint with no state.  More concretely: ``_is_complete``
    documents "config written after the state dir", and the async path
    must honor the same ordering the sync path does.  The cost is that
    an async checkpoint becomes visible to ``latest_checkpoint`` only at
    the next sync point — which is exactly when the caller can first
    rely on it anyway.
    """

    def __init__(self):
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        self._pending: tuple[str, dict, str, int | None] | None = None
        # (start_s, step, nbytes) of the in-flight save, when telemetry
        # is on — recorded as a checkpoint_save span at the flush that
        # commits it (the span covers dispatch → durable-on-disk, the
        # honest window for an async save).
        self._inflight_telemetry: tuple[float, int, int] | None = None

    def save(self, directory: str | os.PathLike, state: TrainState,
             cursor: int | None = None,
             keep_last_n: int | None = None) -> str:
        directory = os.path.abspath(os.fspath(directory))
        step = int(jax.device_get(state.step))
        path = os.path.join(directory, f"step_{step}")
        # Flush the PREVIOUS save's config first: this also orders saves
        # (orbax would serialize them anyway) and guarantees at most one
        # pending config at a time.
        self._flush_pending()
        from distributed_machine_learning_tpu.telemetry import (
            get_telemetry,
        )

        if get_telemetry() is not None:
            self._inflight_telemetry = (
                time.perf_counter(), step,
                _tree_bytes(_state_pytree(state)),
            )
        self._ckptr.save(
            os.path.join(path, _STATE_DIR), _state_pytree(state), force=True
        )
        if jax.process_index() == 0:
            payload = {"__class__": type(state.config).__name__,
                       **dataclasses.asdict(state.config)}
            if cursor is not None:
                payload["__cursor__"] = int(cursor)
            self._pending = (path, payload, directory, keep_last_n)
        return path

    def _flush_pending(self) -> None:
        self._ckptr.wait_until_finished()
        if self._inflight_telemetry is not None:
            from distributed_machine_learning_tpu.telemetry import (
                get_telemetry,
            )

            t0, step, nbytes = self._inflight_telemetry
            self._inflight_telemetry = None
            tel = get_telemetry()
            if tel is not None:
                _record_ckpt_io(tel, "save", t0, time.perf_counter(),
                                step, nbytes)
        if self._pending is not None:
            path, payload, directory, keep_last_n = self._pending
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, _CONFIG_FILE), "w") as f:
                json.dump(payload, f)
            self._pending = None
            # GC only after the save is complete: the just-flushed
            # checkpoint is now the newest complete one and therefore
            # protected, same as the sync path.
            if keep_last_n is not None:
                gc_checkpoints(directory, keep_last_n)

    def wait(self) -> None:
        """Block until the in-flight save (if any) is fully on disk AND
        its config file (completeness marker) is written."""
        self._flush_pending()

    def close(self) -> None:
        self.wait()
        self._ckptr.close()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _is_complete(path: str) -> bool:
    """A checkpoint is complete iff both halves landed: the orbax state dir
    (orbax writes to a tmp dir and renames atomically, so a crashed save
    never leaves a final-named `state/`) and the config file written after
    it.  An interrupted save therefore fails this check."""
    return os.path.isdir(os.path.join(path, _STATE_DIR)) and os.path.isfile(
        os.path.join(path, _CONFIG_FILE)
    )


def latest_checkpoint(directory: str | os.PathLike) -> str | None:
    """Highest-step *complete* `step_<n>` subdirectory of `directory`, or
    None.  Incomplete checkpoints (crash mid-save) are skipped so resume
    falls back to the newest complete one."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_") and name[5:].isdigit():
            steps.append(int(name[5:]))
    for step in sorted(steps, reverse=True):
        path = os.path.join(directory, f"step_{step}")
        if _is_complete(path):
            return path
    return None


def checkpoint_config(path: str | os.PathLike):
    """The optimizer config instance a checkpoint was saved with — lets a
    resume build its abstract template with the *saved* momentum layout
    (AdamW's moment dict vs SGD's buffer tree) before restoring."""
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        payload = json.load(f)
    from distributed_machine_learning_tpu.train.optimizers import (
        config_class_by_name,
    )

    # "SGDConfig" default: checkpoints written before the class tag existed.
    payload.pop("__layout__", None)  # layout tag is checkpoint_layout's
    payload.pop("__cursor__", None)  # data cursor is checkpoint_cursor's
    return config_class_by_name(payload.pop("__class__", "SGDConfig"))(
        **payload
    )


def checkpoint_cursor(path: str | os.PathLike) -> int | None:
    """The data-stream position (batches consumed) a checkpoint was saved
    at, or None for checkpoints saved without one.  Diverges from the
    step counter once the non-finite-gradient guard has skipped a batch;
    the supervisor replays from the cursor so the post-restart stream is
    exactly the pre-crash one."""
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        cursor = json.load(f).get("__cursor__")
    return None if cursor is None else int(cursor)


def checkpoint_layout(path: str | os.PathLike) -> str | None:
    """The parameter-layout tag a checkpoint was saved with (see
    ``save_checkpoint``); None for plain layouts or pre-tag checkpoints."""
    with open(os.path.join(os.fspath(path), _CONFIG_FILE)) as f:
        return json.load(f).get("__layout__")


def checkpoint_array_shapes(path: str | os.PathLike) -> dict:
    """Shapes of the arrays a checkpoint holds — a pure metadata read
    (no array IO).  For callers that must pick a restore template by the
    SAVED layout (e.g. ``--unsync-bn``'s stacked ``[world, C]`` BN stats
    vs a pre-quirk checkpoint's plain ``[C]``) instead of fishing
    structure mismatches out of a blanket except."""
    path = os.path.abspath(os.fspath(path))
    with ocp.PyTreeCheckpointer() as ckptr:
        meta = ckptr.metadata(os.path.join(path, _STATE_DIR))
    tree = meta.item_metadata
    tree = tree.tree if hasattr(tree, "tree") else tree
    return jax.tree_util.tree_map(lambda m: tuple(m.shape), tree)


def restore_checkpoint(
    path: str | os.PathLike, abstract_state: TrainState | None = None
) -> TrainState:
    """Load the TrainState saved at `path` (a `step_<n>` directory).

    `abstract_state` (e.g. the freshly initialized state, possibly with
    sharded arrays) restores each leaf with matching dtype/sharding; without
    it, arrays land unsharded on the default device.
    """
    path = os.path.abspath(os.fspath(path))
    t0 = time.perf_counter()
    restore_args: Any = None
    if abstract_state is not None:
        template = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, _state_pytree(abstract_state)
        )
        restore_args = ocp.args.PyTreeRestore(
            item=template,
            restore_args=ocp.checkpoint_utils.construct_restore_args(template),
        )
    with ocp.PyTreeCheckpointer() as ckptr:
        if restore_args is not None:
            tree = ckptr.restore(os.path.join(path, _STATE_DIR), args=restore_args)
        else:
            tree = ckptr.restore(os.path.join(path, _STATE_DIR))
    # Re-materialize every leaf into an XLA-owned buffer (see
    # fresh_buffers: restored tensorstore/zero-copy-aliased leaves fed
    # to a donating step are a deferred heap corruption — this
    # reproducibly segfaulted resume paths on CPU).  Host-side
    # round-trips (np.array + device_put / jnp.asarray) do NOT work:
    # they re-enter the zero-copy path whenever malloc hands back a
    # 64-byte-aligned block, which is why the failure was flaky.  One
    # copy of the state per restore is noise next to training; losing a
    # run to a heap corruption after a restart is the exact failure the
    # resilience layer exists to prevent.
    tree = fresh_buffers(tree)
    config = checkpoint_config(path)
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        _record_ckpt_io(
            tel, "restore", t0, time.perf_counter(),
            int(jax.device_get(tree["step"])), _tree_bytes(tree),
        )
    return TrainState(
        params=tree["params"],
        momentum=tree["momentum"],
        batch_stats=tree.get("batch_stats") or {},
        step=tree["step"],
        rng=tree["rng"],
        config=config,
    )
