# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/x.py
"""DML012 clean cases: every socket/HTTP op carries an explicit bound
— the transport robustness-layer discipline."""
import socket
import urllib.request


def fetch_state(address):
    with socket.create_connection(address, timeout=2.0) as sock:
        sock.settimeout(2.0)
        sock.sendall(b"{}\n")
        return sock.recv(4096)


def fetch_page(url):
    return urllib.request.urlopen(url, timeout=5.0).read()


def raw_channel(host, port):
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.settimeout(2.0)
    sock.connect((host, port))
    return sock
