"""Hand-rolled bucketed ring all-reduce on ``lax.ppermute``.

The north-star (BASELINE.json): reimplement part3's bucketed ring
all-reduce — which the reference delegates to PyTorch DDP's C++ reducer
with ``bucket_cap_mb=25`` (``part3/main.py:137``) — as an *explicit*
``lax.ppermute`` ring over the device axis.

Algorithm (classic two-phase ring, 2·(N−1) steps total):

  1. The flattened gradient vector is padded and viewed as N chunks.
  2. **reduce-scatter** (N−1 steps): at step s, device r sends its running
     partial sum of chunk ``(r − s) mod N`` to its right neighbor
     ``(r+1) mod N`` and adds the chunk it receives from the left into its
     local copy.  After N−1 steps device r holds the *complete* sum of
     chunk ``(r+1) mod N``.
  3. **all-gather** (N−1 steps): the completed chunks circulate around the
     same ring until every device holds the full reduced vector.

Each device moves 2·(N−1)/N of the gradient bytes — the bandwidth-optimal
schedule DDP's ring uses, here riding ICI links via ``ppermute``.

Bucketing: gradients are flattened once (``ravel_pytree``) and split into
``bucket_bytes`` buckets (default 25 MB — the reference's
``bucket_cap_mb=25``).  Buckets are independent rings, so XLA's async
collective scheduler overlaps bucket k's ppermutes with bucket k+1's
adds — the same comm/compute overlap DDP's autograd hooks implement in
C++ (``part3/main.py:59``, group25.pdf p.6), obtained from the compiler
instead of hand-written callbacks.  **Verified, not assumed** (round 4,
``bench/overlap_audit.py``): AOT-compiling the full part3 step for a
real v5e 2×4 target shows 28 async ``collective-permute-start/done``
pairs (= 2 buckets × 2·(N−1) steps), 21 of which have the *other*
bucket's ``slice_add``/``slice_reduce`` fusions scheduled inside their
in-flight window, with up to 2 ppermutes concurrently in flight and the
two buckets' rings interleaved step-for-step — docs/PERF.md "Ring
overlap audit" for the numbers and protocol.

The ring steps use *static* chunk indices (the loop over steps is unrolled;
N is a compile-time mesh constant), so every slice is a static-shape
``lax.slice`` the TPU backend can lay out without dynamic-update overhead.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

DEFAULT_BUCKET_BYTES = 25 * 2**20  # part3/main.py:137 (bucket_cap_mb=25)


def _right_shift_perm(n: int) -> list[tuple[int, int]]:
    """Ring permutation: every device sends to its right neighbor."""
    return [(i, (i + 1) % n) for i in range(n)]


def ring_all_reduce_flat(
    x: jax.Array,
    axis_name: str,
    axis_size: int,
    mean: bool = False,
    wire_dtype=None,
) -> jax.Array:
    """All-reduce a flat vector via an explicit ppermute ring.

    Must be called inside ``shard_map`` (or any context where ``axis_name``
    is bound).  ``axis_size`` is the static ring size (mesh axis length).

    ``wire_dtype`` (e.g. ``jnp.bfloat16``): compress every hop's payload
    to this dtype on the wire, upcasting before the fp32 accumulation —
    the gradient-compression trick of the multi-hop compressed all-reduce
    literature (see PAPERS.md): halves ring bytes for fp32 gradients at
    the cost of quantizing each partial sum once per hop.  None = exact.
    """
    n = axis_size
    if n == 1:
        return x

    def hop(v):
        if wire_dtype is None:
            return lax.ppermute(v, axis_name, perm)
        return lax.ppermute(v.astype(wire_dtype), axis_name, perm).astype(
            x.dtype
        )

    orig_len = x.shape[0]
    chunk = -(-orig_len // n)  # ceil division
    padded = jnp.pad(x, (0, n * chunk - orig_len))
    chunks = padded.reshape(n, chunk)
    perm = _right_shift_perm(n)
    rank = lax.axis_index(axis_name)

    # Phase 1 — reduce-scatter.  The chunk index each rank touches at step s
    # is rank-dependent (r−s mod n), but ppermute needs every rank to execute
    # the same program; we roll the chunk axis by the (traced) rank once so
    # that the per-step indices become static: after rolling by −r, rank r's
    # "send chunk (r−s)" is row (−s mod n) for every rank.
    chunks = jnp.roll(chunks, -rank, axis=0)  # row i ≡ global chunk (i + r) mod n
    for s in range(n - 1):
        send_row = (-s) % n
        recv_row = (-s - 1) % n
        recvd = hop(chunks[send_row])
        chunks = chunks.at[recv_row].add(recvd)
    # Rank r now owns the full sum of global chunk (r+1) mod n == row 1.
    own = chunks[1 % n]
    if mean:
        own = own / n
    if wire_dtype is not None:
        # Quantize the completed chunk ONCE before phase 2, including the
        # owner's own stored copy: receivers see bf16(own), so the owner
        # must too, or ranks end the all-reduce with slightly different
        # "synced" gradients and replicated params silently drift apart
        # (further hops re-quantize the same values — idempotent).
        own = own.astype(wire_dtype).astype(x.dtype)

    # Phase 2 — all-gather the completed chunks around the same ring.
    out = jnp.zeros_like(chunks)
    out = out.at[1 % n].set(own)
    cur = own
    for s in range(n - 1):
        cur = hop(cur)
        # After s+1 hops, the chunk arriving at rank r was completed by rank
        # (r − s − 1), i.e. global chunk (r − s) mod n == local row (−s) mod n.
        out = out.at[(-s) % n].set(cur)
    # Undo the roll to restore global chunk order.
    out = jnp.roll(out, rank, axis=0)
    return out.reshape(-1)[:orig_len]


def ring_all_reduce(
    grads,
    axis_name: str,
    axis_size: int,
    mean: bool = True,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    wire_dtype=None,
) -> object:
    """Bucketed ring all-reduce over a gradient pytree.

    ``mean=True`` reproduces DDP's averaging (part3 semantics — SURVEY.md
    §2.4); ``mean=False`` gives the SUM semantics of parts 2a/2b.
    ``wire_dtype``: optional on-the-wire compression (see
    :func:`ring_all_reduce_flat`).
    """
    flat, unravel = ravel_pytree(grads)
    if axis_size == 1 or flat.shape[0] == 0:
        return grads
    bucket_elems = max(1, int(bucket_bytes) // flat.dtype.itemsize)
    num_buckets = -(-flat.shape[0] // bucket_elems)
    reduced = [
        ring_all_reduce_flat(
            flat[i * bucket_elems : min((i + 1) * bucket_elems, flat.shape[0])],
            axis_name,
            axis_size,
            mean=mean,
            wire_dtype=wire_dtype,
        )
        for i in range(num_buckets)
    ]
    return unravel(reduced[0] if num_buckets == 1 else jnp.concatenate(reduced))
