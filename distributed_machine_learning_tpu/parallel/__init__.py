from distributed_machine_learning_tpu.parallel.strategies import (
    SyncStrategy,
    NoSync,
    AllReduce,
    GatherScatter,
    RingAllReduce,
    get_strategy,
    STRATEGIES,
)

from distributed_machine_learning_tpu.parallel.fsdp import (
    FSDPState,
    make_fsdp_train_step,
    shard_fsdp_state,
    gather_fsdp_params,
)

from distributed_machine_learning_tpu.parallel.parallel3d import (
    make_3d_mesh,
    make_3d_lm_train_step,
    shard_3d_state,
    shard_3d_batch,
)

from distributed_machine_learning_tpu.parallel.zero1 import (
    Zero1State,
    make_zero1_train_step,
    shard_zero1_state,
    zero1_params,
)

__all__ = [
    "Zero1State",
    "make_zero1_train_step",
    "shard_zero1_state",
    "zero1_params",
    "make_3d_mesh",
    "make_3d_lm_train_step",
    "shard_3d_state",
    "shard_3d_batch",
    "SyncStrategy",
    "NoSync",
    "AllReduce",
    "GatherScatter",
    "RingAllReduce",
    "get_strategy",
    "STRATEGIES",
    "FSDPState",
    "make_fsdp_train_step",
    "shard_fsdp_state",
    "gather_fsdp_params",
]
