"""Jitted train/eval steps over a device mesh.

Replaces the reference's training driver + torch autograd + gloo stack
(``train_model`` at ``part1/main.py:19-58`` and clones): one pure function
per step — forward, loss, ``jax.grad``, the pluggable gradient-sync
strategy, and the SGD update — compiled by XLA as a single program.
Distribution is SPMD: the step is ``shard_map``-ed over the mesh's
``"batch"`` axis with the batch sharded and the state replicated, so the
sync strategy's collectives (psum / all-gather / ppermute ring) lower to
ICI ops scheduled and overlapped by the compiler — the work DDP's C++
reducer and autograd hooks do by hand in the reference (part3).

Augmentation runs inside the step (see ``data/augment.py``), keyed per
step and per mesh position, so each shard draws independent crops/flips
the way each reference node draws from its own torch RNG.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.data.augment import augment_batch, normalize
from distributed_machine_learning_tpu.parallel.strategies import NoSync, SyncStrategy
from distributed_machine_learning_tpu.runtime.mesh import (
    BATCH_AXIS,
    shard_map_no_check as _shard_map,
)
from distributed_machine_learning_tpu.train.common import make_loss_fn, step_rng
from distributed_machine_learning_tpu.train.losses import cross_entropy_loss, count_correct
from distributed_machine_learning_tpu.train.state import TrainState


def _train_step_impl(
    model,
    strategy: SyncStrategy,
    state: TrainState,
    images_u8,
    labels,
    sync_state=None,
    *,
    axis_name: str | None,
    axis_size: int,
    augment: bool,
    sync_bn: bool,
    schedule=None,
    clip_norm: float | None = None,
    accum_steps: int = 1,
    update_fn=None,
    local_loss: bool = False,
    guard: bool = False,
):
    # Unsynced-BN quirk mode (reference part3: per-node running stats,
    # part3/model.py:24 + group25.pdf p.3-4): the replicated state holds
    # a [world, *S]-stacked stats tree; each device reads/writes its own
    # row, and an all_gather of the (tiny) stats restores replication.
    unsync_bn = axis_name is not None and not sync_bn
    stats_in = state.batch_stats
    if unsync_bn and stats_in:
        dev_idx = lax.axis_index(axis_name)
        stats_in = jax.tree_util.tree_map(lambda s: s[dev_idx], stats_in)
    if update_fn is None:
        # Dispatch on the state's (static) optimizer config at trace time.
        from distributed_machine_learning_tpu.train.optimizers import (
            update_fn_for_config,
        )

        update_fn = update_fn_for_config(state.config)
    rng = step_rng(state.rng, state.step, axis_name)
    if accum_steps == 1:
        x = augment_batch(rng, images_u8) if augment else normalize(images_u8)
        loss_fn = make_loss_fn(model, stats_in, x, labels, train=True)
        (loss, (_, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
    else:
        # Gradient accumulation: split the (local) batch into microbatches
        # and scan, accumulating gradients — the program stays one
        # microbatch big, peak activation memory drops accum_steps-fold,
        # and with equal microbatches mean-of-means == the full-batch mean
        # so the update is identical (BN-free; BN running stats update
        # per microbatch, sequentially, like small-batch torch training).
        B = images_u8.shape[0]
        if B % accum_steps:
            raise ValueError(
                f"per-device batch {B} not divisible by accum_steps="
                f"{accum_steps}"
            )
        micro_imgs = images_u8.reshape(
            accum_steps, B // accum_steps, *images_u8.shape[1:]
        )
        micro_labels = labels.reshape(accum_steps, B // accum_steps)
        micro_rngs = jax.random.split(rng, accum_steps)

        def body(carry, xs):
            stats, grads_acc, loss_acc = carry
            mi, ml, r = xs
            x = augment_batch(r, mi) if augment else normalize(mi)
            loss_fn = make_loss_fn(model, stats, x, ml, train=True)
            (loss, (_, new_stats)), g = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params)
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, g)
            return (new_stats if new_stats else stats, grads_acc,
                    loss_acc + loss), None

        zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
        (new_stats, grads, loss), _ = lax.scan(
            body,
            (stats_in, zeros, jnp.zeros((), jnp.float32)),
            (micro_imgs, micro_labels, micro_rngs),
        )
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grads)
        loss = loss / accum_steps

    new_sync_state = None
    if axis_name is not None:
        if sync_state is not None:
            # Stateful strategy (error-feedback compressed ring): the
            # state rides OUTSIDE TrainState, sharded P(batch) on a
            # leading [world, ...] axis so each device carries its OWN
            # residual — error feedback is rank-local; replicating it
            # would both waste world× memory and be semantically wrong.
            local = jax.tree_util.tree_map(lambda r: r[0], sync_state)
            grads, new_local = strategy.apply(
                grads, local, axis_name, axis_size
            )
            new_sync_state = jax.tree_util.tree_map(
                lambda r: r[None], new_local
            )
        else:
            grads = strategy(grads, axis_name, axis_size)
        if new_stats and sync_bn:
            # part3's reference leaves BN running stats unsynced per node (a
            # documented quirk — SURVEY.md §7.3); the TPU-idiomatic default
            # axis-means them so replicated state stays bit-identical across
            # devices (the framework's cross-replica invariant).
            new_stats = jax.tree_util.tree_map(
                lambda s: lax.pmean(s, axis_name), new_stats
            )
        elif new_stats and unsync_bn:
            # Re-stack every device's locally-updated stats so the
            # replicated out_spec stays truthful: all devices hold the
            # identical [world, *S] array whose row d is device d's stats.
            new_stats = jax.tree_util.tree_map(
                lambda s: lax.all_gather(s, axis_name), new_stats
            )

    if clip_norm is not None:
        # After sync: clip the global gradient (DDP-semantics order).
        from distributed_machine_learning_tpu.train.schedule import (
            clip_by_global_norm,
        )

        grads = clip_by_global_norm(grads, clip_norm)
    new_params, new_momentum = update_fn(
        state.params,
        state.momentum,
        grads,
        state.config,
        lr=None if schedule is None else schedule(state.step),
        step=state.step,
    )
    new_state = state.replace(
        params=new_params,
        momentum=new_momentum,
        batch_stats=new_stats,
        step=state.step + 1,
    )
    if guard:
        # Non-finite-gradient guard: a NaN/Inf anywhere in the (synced)
        # gradients skips the whole update — params, momentum, BN stats,
        # and the step counter all stay exactly as they were, so one bad
        # batch costs one step, not the run.  Checked on the post-sync
        # gradients (identical on every device), so the skip decision is
        # replicated and cross-device state stays bit-identical.
        from distributed_machine_learning_tpu.train.common import (
            guard_update,
            tree_all_finite,
        )

        ok = tree_all_finite(grads)
        new_state = guard_update(ok, new_state, state)
        if new_sync_state is not None:
            # A skipped update must also freeze the residual: feeding a
            # non-finite error back into the next step would poison it.
            new_sync_state = jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), new_sync_state, sync_state
            )
    if axis_name is not None:
        if local_loss:
            # Reference print-surface parity mode: each rank prints its
            # OWN shard's loss (part2/2a/main.py:58-61).  Out spec is
            # P(axis), so the step returns the [world] per-device vector.
            loss = loss[None]
        else:
            # Default: the global mean loss (SPMD has one print stream,
            # so surface the mean).
            loss = lax.pmean(loss, axis_name)
    if sync_state is not None:
        return new_state, loss, new_sync_state
    return new_state, loss


def make_train_step(
    model,
    strategy: SyncStrategy | None = None,
    mesh: Mesh | None = None,
    axis_name: str = BATCH_AXIS,
    augment: bool = True,
    sync_bn: bool = True,
    schedule=None,
    clip_norm: float | None = None,
    accum_steps: int = 1,
    jit: bool = True,
    optimizer: str | None = None,
    local_loss: bool = False,
    guard_nonfinite: bool = False,
):
    """Build the jitted train step.

    Without a mesh: the part1 path — plain ``jit``, no collectives.
    With a mesh: ``shard_map`` over ``axis_name``; batch sharded on axis 0,
    state replicated; `strategy` decides how gradients synchronize.

    ``schedule``: optional ``step -> lr`` fn (``train/schedule.py``)
    overriding the static config rate; ``clip_norm``: optional global-norm
    gradient clip, applied after sync; ``accum_steps``: split each batch
    into this many sequential microbatches, accumulating gradients
    (identical update for BN-free models, accum-fold lower activation
    memory).

    ``sync_bn``: True (default) axis-means BN running stats so replicated
    state stays bit-identical; False reproduces the reference part3's
    per-node unsynced stats (part3/model.py:24) — pass state through
    ``broadcast_bn_stats(state, mesh.shape[axis_name])`` first, and eval
    with ``make_eval_step(..., sync_bn=False)``.

    ``local_loss`` (mesh only): return the [world] vector of per-device
    losses instead of the pmean — each reference rank prints its own
    local loss (part2/2a/main.py:58-61); this is that print surface.

    ``optimizer``: None (default) dispatches on the TrainState's config
    type — SGDConfig → sgd (reference parity), LARSConfig → lars,
    AdamWConfig → adamw; an explicit registry name pins the update fn.

    ``jit=False`` returns the un-jitted step function (no donation) — for
    callers that embed the step in a larger compiled program, e.g. the
    benchmark's ``lax.scan``-ed epoch (bench.py) where per-step dispatch
    would dominate on a remote/tunneled device.

    Stateful strategies (``strategy.stateful``, e.g. the error-feedback
    compressed ring — ``RingAllReduce(compress="int8")``): the compiled
    step threads the strategy's per-device state (the EF residual)
    through the program — state in, state out, donated, sharded
    P(batch).  With ``jit=True`` the returned callable keeps the
    ``step(state, x, y) -> (state, loss)`` signature and manages the
    residual buffers itself (``step.sync_state()`` /
    ``step.set_sync_state(res)`` / ``step.reset_sync_state()`` /
    ``step.fresh_sync_state(params)``; ``step.inner`` is the raw 4-ary
    jitted fn for AOT lowering).  The wrapper is world-change-safe: a
    residual stacked for a different world (an elastic shrink/grow
    carried it across a gang reshape) is rebuilt as zeros at this
    mesh's world — logged/counted as ``ring_residual_reset`` — never a
    shape crash inside the compiled program.  With
    ``jit=False`` the raw 4-ary fn is returned and the caller threads
    the state.  Stateless strategies compile the exact program they
    always did — zero overhead.

    ``guard_nonfinite``: compile the non-finite-gradient guard into the
    step — an all-leaves ``isfinite`` reduction over the (synced)
    gradients; when any gradient blew up, the update is skipped wholesale
    (state unchanged, step NOT incremented) and the returned loss is the
    non-finite value so the host can count the event
    (``runtime/faults.FaultEvents.skipped_steps``).  Off by default:
    reference-parity runs must not mask numeric bugs.

    Returns ``step(state, images_u8, labels) -> (state, loss)``.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if local_loss and mesh is None:
        raise ValueError("local_loss requires a mesh (it is the per-device "
                         "loss vector; the part1 path has one device)")
    from distributed_machine_learning_tpu.train.optimizers import get_optimizer

    # optimizer=None → dispatch from the TrainState's config at trace time
    # (the natural path); an explicit name pins the update fn regardless.
    update_fn = None if optimizer is None else get_optimizer(optimizer)[2]
    strategy = strategy or NoSync()
    if mesh is not None and isinstance(strategy, NoSync):
        # Unsynced gradients under a replicated-state shard_map would let
        # params silently diverge per device (out_specs claims replication).
        # part1 semantics on a mesh is simply mesh=None.
        raise ValueError(
            "strategy 'none' (part1) cannot run on a mesh: gradients would "
            "not be synchronized and replicated state would diverge; use "
            "mesh=None, or pick all_reduce/gather_scatter/ring"
        )

    if mesh is None:
        impl = partial(
            _train_step_impl,
            model,
            strategy,
            axis_name=None,
            axis_size=1,
            augment=augment,
            sync_bn=sync_bn,
            schedule=schedule,
            clip_norm=clip_norm,
            accum_steps=accum_steps,
            update_fn=update_fn,
            guard=guard_nonfinite,
        )
        return jax.jit(impl, donate_argnums=(0,)) if jit else impl

    axis_size = mesh.shape[axis_name]
    # sync_bn=False is the reference part3 quirk mode: per-device BN
    # running stats (part3/model.py:24, <1% cross-node accuracy drift —
    # group25.pdf p.3-4).  State must carry [world, *S]-stacked stats —
    # build it with ``broadcast_bn_stats(state, world)``; each device
    # reads/writes its own row (see _train_step_impl).
    impl = partial(
        _train_step_impl,
        model,
        strategy,
        axis_name=axis_name,
        axis_size=axis_size,
        augment=augment,
        sync_bn=sync_bn,
        schedule=schedule,
        clip_norm=clip_norm,
        accum_steps=accum_steps,
        update_fn=update_fn,
        local_loss=local_loss,
        guard=guard_nonfinite,
    )
    state_spec = P()  # replicated
    batch_spec = P(axis_name)  # sharded along the data axis
    loss_spec = P(axis_name) if local_loss else P()
    if not getattr(strategy, "stateful", False):
        sharded = _shard_map(
            impl,
            mesh=mesh,
            in_specs=(state_spec, batch_spec, batch_spec),
            out_specs=(state_spec, loss_spec),
        )
        return jax.jit(sharded, donate_argnums=(0,)) if jit else sharded

    # Stateful strategy (error-feedback compressed ring): the compiled
    # step threads the strategy's per-device state through the program —
    # state in, state out, DONATED, sharded P(batch) on a leading
    # [world, ...] axis (each device owns its residual row).  The
    # stateless path above compiles the exact program it always did:
    # the uncompressed ring pays zero overhead for this feature.
    res_spec = P(axis_name)
    sharded = _shard_map(
        impl,
        mesh=mesh,
        in_specs=(state_spec, batch_spec, batch_spec, res_spec),
        out_specs=(state_spec, loss_spec, res_spec),
    )
    if not jit:
        # Un-jitted stateful form: the caller threads the state
        # explicitly — step(state, x, y, sync_state) →
        # (state, loss, sync_state) — e.g. a scanned-epoch bench
        # carrying it alongside TrainState.
        return sharded
    inner = jax.jit(sharded, donate_argnums=(0, 3))

    def fresh_sync_state(params):
        """[world, *leaf] stacked zeros, sharded P(batch) over the mesh
        — each device's row is its own (initially empty) residual.
        Shapes come from an abstract eval of the strategy's init (no
        throwaway full-size zeros tree is ever materialized)."""
        res0 = jax.eval_shape(strategy.init_state, params)
        stacked = jax.tree_util.tree_map(
            lambda r: jnp.zeros((axis_size,) + r.shape, r.dtype), res0
        )
        return jax.device_put(
            stacked, NamedSharding(mesh, P(axis_name))
        )

    holder = {"res": None}

    def _residual_world(res) -> int | None:
        """The world size a stacked residual was built for — its leading
        axis (every leaf is ``[world, *leaf]``)."""
        leaves = jax.tree_util.tree_leaves(res)
        return int(leaves[0].shape[0]) if leaves else None

    def _check_world(res):
        """Accept ``res`` only if its stacked world matches THIS step's
        mesh; a mismatch (an elastic shrink/grow carried the residual
        across a world change) resets to fresh zeros instead of shape-
        crashing inside the compiled program, and says so: a silent
        reset would weaken the EF-exactness story, a crash would turn a
        planned reshape into a failure.  Returns the residual to use."""
        got = _residual_world(res)
        if got is None or got == axis_size:
            return res
        from distributed_machine_learning_tpu.telemetry import (
            get_telemetry,
        )

        tel = get_telemetry()
        if tel is not None:
            tel.registry.counter("ring_residual_reset").inc()
            tel.tracer.instant("ring_residual_reset", from_world=got,
                               to_world=axis_size)
        print(
            f"[ring] ring_residual_reset: error-feedback residual was "
            f"stacked for world {got}, mesh is world {axis_size} — "
            "rebuilding at the new world with zeros (one step of EF "
            "warmup)", flush=True,
        )
        return None

    def step(state, images_u8, labels):
        # Caller-facing signature unchanged (state, x, y) → (state,
        # loss): the wrapper owns the residual buffers, lazily zeroed
        # from the first state's param shapes and re-donated each call.
        if holder["res"] is not None:
            holder["res"] = _check_world(holder["res"])
        if holder["res"] is None:
            holder["res"] = fresh_sync_state(state.params)
        new_state, loss, holder["res"] = inner(
            state, images_u8, labels, holder["res"]
        )
        return new_state, loss

    def sync_state():
        """The CURRENT residual pytree — the live buffers the next
        ``step()`` call donates back into the program, so a kept
        reference dies with that call (Array deleted).  Copy before
        holding across steps: ``jax.tree_util.tree_map(jnp.copy, ...)``."""
        return holder["res"]

    def set_sync_state(res):
        """Install a carried residual — the elastic-rebind hook: a
        caller that preserved the residual across a step rebuild (same
        params, possibly a DIFFERENT world after a gang reshape) hands
        it to the new step here.  A world mismatch resets to fresh
        zeros at the new world (logged as ``ring_residual_reset``)
        rather than shape-crashing; a matching one is re-placed onto
        this step's mesh sharding."""
        res = _check_world(res)
        if res is not None:
            res = jax.device_put(res, NamedSharding(mesh, P(axis_name)))
        holder["res"] = res

    step.inner = inner  # AOT/lowering access: inner.lower(state, x, y, res)
    step.fresh_sync_state = fresh_sync_state
    step.sync_state = sync_state
    step.set_sync_state = set_sync_state
    step.reset_sync_state = lambda: holder.__setitem__("res", None)
    return step


def broadcast_bn_stats(state: TrainState, world: int) -> TrainState:
    """Stack ``world`` copies of the BN running stats ([world, *S] per
    leaf) — the state layout the unsynced-BN quirk mode
    (``make_train_step(..., sync_bn=False)``) reads and writes.  The
    stacked tree stays replicated across devices; row d is device d's
    private running stats, the TPU encoding of the reference's per-node
    BN state (part3/model.py:24)."""
    if not state.batch_stats:
        return state
    stacked = jax.tree_util.tree_map(
        lambda s: jnp.tile(s[None], (world,) + (1,) * s.ndim),
        state.batch_stats,
    )
    return state.replace(batch_stats=stacked)


def make_eval_step(model, mesh: Mesh | None = None, axis_name: str = BATCH_AXIS,
                   sync_bn: bool = True):
    """Jitted eval step: (params, batch_stats, images_u8, labels) →
    (batch mean loss, correct count) — ``test_model`` parity
    (``part1/main.py:62-77``): normalize only (no augmentation), BN in
    inference mode, loss averaged per batch, top-1 correct counts.

    With a mesh, evaluation is *sharded*: each device scores its slice of
    the batch and the per-batch mean loss / correct count come back via
    ``pmean``/``psum`` — an N-fold speedup over the reference's
    every-rank-evaluates-everything protocol (SURVEY.md §3.5) with
    identical results (equal shards ⇒ pmean of shard means == the global
    batch mean).

    ``sync_bn=False`` (quirk-mode eval, mesh only): ``batch_stats`` is
    the [world, *S]-stacked tree from the unsynced-BN train step; each
    device scores its shard with its own stats row, so the reported
    numbers mix per-device models exactly the way the reference's
    per-node evals do.
    """

    def eval_impl(params, batch_stats, images_u8, labels, *, axis=None):
        x = normalize(images_u8)
        if batch_stats and axis is not None and not sync_bn:
            batch_stats = jax.tree_util.tree_map(
                lambda s: s[lax.axis_index(axis)], batch_stats
            )
        variables: dict[str, Any] = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, x, train=False)
        loss = cross_entropy_loss(logits, labels)
        correct = count_correct(logits, labels)
        if axis is not None:
            loss = lax.pmean(loss, axis)
            correct = lax.psum(correct, axis)
        return loss, correct

    if mesh is None:
        return jax.jit(eval_impl)

    sharded = _shard_map(
        partial(eval_impl, axis=axis_name),
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P(axis_name)),
        out_specs=(P(), P()),
    )
    return jax.jit(sharded)


def shard_batch(mesh: Mesh, images_u8, labels, axis_name: str = BATCH_AXIS):
    """Place a host batch onto the mesh, sharded along the batch axis."""
    sharding = NamedSharding(mesh, P(axis_name))
    return (
        jax.device_put(jnp.asarray(images_u8), sharding),
        jax.device_put(jnp.asarray(labels), sharding),
    )
