"""Flash attention (causal) as a Pallas TPU kernel.

The hot op of the transformer family, written for the hardware per the
Pallas playbook (/opt/skills/guides/pallas_guide.md): the L×L score
matrix never hits HBM, and on-chip memory is O(block), not O(L) — the
grid is (batch·heads, Q blocks, K blocks) with the K dimension innermost,
so Pallas streams one [block_k, D] K/V tile into VMEM per step while the
online-softmax running (max, normalizer, accumulator) triple persists in
VMEM scratch across the K steps of each Q block.  Blocks entirely above
the causal diagonal skip their compute via ``pl.when``.

Differentiation: Pallas kernels are not auto-differentiable, so the op
carries a ``jax.custom_vjp`` whose backward is ``jax.vjp`` of the XLA
dense reference (``ops.ring_attention.dense_self_attention``) — one
source of truth for the semantics, flash-style memory only on the
forward (a full Pallas backward kernel is a later optimization).

On non-TPU backends the kernel runs in interpreter mode, so tests on the
CPU mesh exercise the identical code path the TPU compiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports only resolve fully on TPU-capable installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

from distributed_machine_learning_tpu.ops.ring_attention import (
    dense_self_attention,
)

NEG_INF = -1e30
_LANES = 128  # VMEM lane width: m/l scratch is (block_q, _LANES)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, block_q, block_k, scale
):
    """One (Q block, K block) tile of the online-softmax recurrence."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Skip blocks entirely above the causal diagonal.
    @pl.when(k_start <= q_start + block_q - 1)
    def _update():
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        k = k_ref[0].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m = m_ref[:, 0]  # [block_q]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s > 0.5 * NEG_INF, p, 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, block_q: int, block_k: int):
    """q/k/v: [BH, L, D] → [BH, L, D]."""
    BH, L, D = q.shape
    scale = 1.0 / (D**0.5)
    grid = (BH, L // block_q, L // block_k)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, scale=scale
    )
    if not _HAS_PLTPU:  # pragma: no cover — pltpu ships with jax[tpu]/cpu alike
        raise RuntimeError("pallas TPU support (jax.experimental.pallas.tpu) "
                           "is unavailable; use attn_impl='dense'")
    q_spec = pl.BlockSpec(
        (1, block_q, D), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
    )
    k_spec = pl.BlockSpec(
        (1, block_k, D), lambda bh, qi, kb: (bh, kb, 0), memory_space=pltpu.VMEM
    )
    scratch = [
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # running normalizer
        pltpu.VMEM((block_q, D), jnp.float32),  # output accumulator
    ]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        grid=grid,
        in_specs=[q_spec, k_spec, k_spec],
        out_specs=q_spec,
        scratch_shapes=scratch,
        interpret=_interpret(),
    )(q, k, v)


def _pick_block(L: int, target: int = 128) -> int:
    for b in (target, 64, 32, 16, 8, 4, 2, 1):
        if b <= L and L % b == 0:
            return b
    return 1


@jax.custom_vjp
def _flash_core(q, k, v):
    B, L, H, D = q.shape
    blk = _pick_block(L)
    fold = lambda a: a.transpose(0, 2, 1, 3).reshape(B * H, L, D)
    out = _flash_fwd(fold(q), fold(k), fold(v), blk, blk)
    return out.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def _flash_core_fwd(q, k, v):
    return _flash_core(q, k, v), (q, k, v)


def _flash_core_bwd(res, g):
    # Backward = VJP of the dense XLA reference: one source of truth for
    # the attention semantics (ops/ring_attention.py).
    q, k, v = res
    _, vjp = jax.vjp(dense_self_attention, q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_self_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal flash attention: [B, L, H, D] in and out.

    Drop-in for ``ops.ring_attention.dense_self_attention`` on contiguous
    (offset-0) sequences — the unsharded model path.
    """
    return _flash_core(q, k, v)
