"""Two-process worker for the multi-host test (tests/test_multihost.py).

Run as a subprocess (NOT collected by pytest): each of two OS processes
brings up the coordination service through the reference's exact flag
path (``--master-ip``/``--rank``/``--num-nodes`` →
``jax.distributed.initialize`` — runtime/distributed.py:46-59, the TPU
analogue of ``dist.init_process_group`` at part2/2a/main.py:197), then
runs lock-step psum training steps over a 2-process CPU mesh, agrees
on a SIGTERM-triggered stop via ``agree_stop``'s process_allgather
branch (runtime/resilience.py), and finishes with a cross-process
GSPMD step (per-layer-FSDP leaves sharded over the two processes by
jit in_shardings alone) — the code paths single-process tests can
never exercise.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--port", type=int, required=True)
    args = ap.parse_args()

    from distributed_machine_learning_tpu.runtime.distributed import (
        initialize_from_flags,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        make_mesh,
        shard_map_no_check,
    )
    from distributed_machine_learning_tpu.runtime.resilience import (
        PreemptionHandler,
        agree_stop,
    )

    ctx = initialize_from_flags(f"127.0.0.1:{args.port}", args.rank, 2)
    assert jax.process_count() == 2, jax.process_count()
    print(f"ready rank={jax.process_index()} devices={jax.device_count()}",
          flush=True)

    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(2)  # one CPU device per process
    sharding = NamedSharding(mesh, P("batch"))
    # Each process contributes its own local shard — the per-host data
    # path of a real multi-host run.
    local = np.full((1, 8), float(jax.process_index() + 1), np.float32)
    x = jax.make_array_from_process_local_data(sharding, local)
    w = jax.device_put(
        jnp.zeros((8,), jnp.float32), NamedSharding(mesh, P())
    )

    def local_step(w, xs):
        # pmean over the cross-process axis: the part3 mean-gradient
        # semantics, riding gloo instead of ICI on this CPU mesh.
        g = jax.lax.pmean(xs[0], "batch")
        return w - 0.1 * g

    step = jax.jit(shard_map_no_check(
        local_step, mesh=mesh, in_specs=(P(), P("batch")), out_specs=P()
    ))

    pre = PreemptionHandler().install()
    stopped_at = -1
    for i in range(200):
        w = step(w, x)
        jax.block_until_ready(w)
        if args.rank == 0:
            print(f"step {i}", flush=True)
        # Collective agreement every step: both ranks must leave the loop
        # at the same boundary even though only rank 0 gets the signal.
        if agree_stop(pre.requested):
            stopped_at = i
            break
        time.sleep(0.05)

    # w is fully replicated, so np.asarray is legal on both hosts; the
    # digest proves bit-identical final params across processes.
    digest = hashlib.sha256(np.asarray(w).tobytes()).hexdigest()[:16]
    print(f"stopped_at {stopped_at}", flush=True)
    print(f"final {digest}", flush=True)

    # Per-host strided data path (the loaders are otherwise only tested
    # single-process): rank-major DistributedSampler batches, each host
    # contributing ITS OWN rank's slice to the global array, summed by a
    # cross-process psum — must equal the plain host-side global sum.
    from distributed_machine_learning_tpu.data.cifar10 import Dataset
    from distributed_machine_learning_tpu.data.distributed_loader import (
        DistributedBatchLoader,
    )

    rng2 = np.random.default_rng(11)
    ds = Dataset(
        images=rng2.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8),
        labels=rng2.integers(0, 10, 32).astype(np.int32),
        synthetic=True,
    )
    _, labels = next(iter(DistributedBatchLoader(ds, 4, 2)))
    rows = labels.reshape(2, 4).astype(np.float32)  # row r = rank r's batch
    gl = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("batch")), rows[jax.process_index()][None]
    )
    total = jax.jit(shard_map_no_check(
        lambda xs: jax.lax.psum(xs.sum(), "batch"),
        mesh=mesh, in_specs=(P("batch"),), out_specs=P(),
    ))(gl)
    print(f"data_sum {float(total)} {float(rows.sum())}", flush=True)

    # Cross-process GSPMD: one per-layer-FSDP LM step whose parameter
    # leaves are sharded ACROSS THE TWO PROCESSES by the jit's
    # in_shardings (no shard_map — the partitioner derives the
    # gathers/reduce-scatters over the gloo backend).  The single-
    # process suite can only shard across local devices; this is the
    # real multi-host layout.  Both ranks must agree bit-for-bit on the
    # updated (all-gathered) params.
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.fsdp_perlayer import (
        make_fsdp_pl_lm_train_step,
        shard_fsdp_pl_state,
    )
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state
    from jax.experimental import multihost_utils

    lm = TransformerLM(vocab_size=64, d_model=16, n_layers=1, n_heads=2,
                       attn_impl="dense")
    lm_state = shard_fsdp_pl_state(init_lm_state(lm), mesh)
    lm_step = make_fsdp_pl_lm_train_step(lm, mesh)
    rng3 = np.random.default_rng(5)
    toks = rng3.integers(0, 64, (2, 17)).astype(np.int32)  # same both ranks
    tok_sharding = NamedSharding(mesh, P("batch", None))
    gx = jax.make_array_from_process_local_data(
        tok_sharding, toks[jax.process_index()][None, :-1]
    )
    gy = jax.make_array_from_process_local_data(
        tok_sharding, toks[jax.process_index()][None, 1:]
    )
    lm_state, lm_loss = lm_step(lm_state, gx, gy)
    host_loss = multihost_utils.process_allgather(lm_loss, tiled=True)
    host_params = multihost_utils.process_allgather(lm_state.params,
                                                    tiled=True)
    pdigest = hashlib.sha256(
        b"".join(np.asarray(leaf).tobytes()
                 for leaf in jax.tree_util.tree_leaves(host_params))
    ).hexdigest()[:16]
    print(f"gspmd_loss {float(np.asarray(host_loss).reshape(-1)[0]):.6f}",
          flush=True)
    print(f"gspmd_params {pdigest}", flush=True)
    ctx.shutdown()


if __name__ == "__main__":
    main()
