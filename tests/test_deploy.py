"""Train-to-serve continuous deployment (ISSUE 18): the verified
reshard→requantize chain, the two-phase fenced weight hot-swap, the
canary judge, and the chaos-proven auto-rollback.

Fast half: ``load_serving_weights`` restores train-layout checkpoints
(dp / zero1@8 / fsdp@8) onto the serving world bit-exactly with the
per-leaf logical digests re-verified POST-requantize (a tampered
restore and a corrupted checkpoint both fail loudly and quarantine),
the worker's drain-then-commit swap seam versions every post, and the
controller's promote / quality-rollback / SLO-burn-rollback /
watcher-skips-corrupt paths.

Tier-1 keystones: ``test_chaos_replica_killed_mid_swap_rolls_back``
(the acceptance campaign — a fleet under sustained load, a deploy
rolled mid-load, the canary replica killed mid-swap; the controller
must time out the commit, roll back counted-and-ledgered, the fleet
must heal by spare promotion, a follow-up deploy must promote on the
healed fleet, and every admitted request completes exactly once inside
the wall-clock cap) and the offline-observability test (serve_status /
gang_status render the deployment state machine, trace_merge shows the
``weight_swap`` instants).  The multi-deploy endurance variant rides
behind ``slow``.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.deploy import (
    checksum_token,
    quality_probe,
    versioned_step,
    write_demo_checkpoint,
)
from distributed_machine_learning_tpu.runtime.deploy import (
    DeployConfig,
    DeployController,
    load_serving_weights,
    tree_digest,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    corrupt_checkpoint_data,
)
from distributed_machine_learning_tpu.runtime.mesh import ShardSpec
from distributed_machine_learning_tpu.runtime.serving import (
    Overloaded,
    ServingConfig,
    ServingRouter,
)
from distributed_machine_learning_tpu.runtime.serving_worker import (
    ServingWorkerConfig,
    start_worker_thread,
)
from distributed_machine_learning_tpu.runtime.transport import (
    FileTransport,
    InProcHub,
    InProcTransport,
    TransportError,
)
from distributed_machine_learning_tpu.telemetry import Telemetry
from distributed_machine_learning_tpu.train.checkpoint import (
    CheckpointVerifyError,
    latest_checkpoint,
    save_checkpoint,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

CHAOS_BUDGET_S = 150.0


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# load_serving_weights: the reshard-to-serving verified chain
# ---------------------------------------------------------------------------


def _lm_state():
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig
    from distributed_machine_learning_tpu.train.state import TrainState

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=1,
                          n_heads=2)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return TrainState.create(params=params, rng=jax.random.PRNGKey(9),
                             config=AdamWConfig())


@pytest.fixture(scope="module")
def lm_base():
    return _lm_state()


def _params_equal(a, b) -> bool:
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _has_int8_leaf(tree) -> bool:
    import jax

    return any(np.asarray(leaf).dtype == np.int8
               for leaf in jax.tree_util.tree_leaves(tree))


def test_load_serving_weights_dp_checkpoint(tmp_path):
    """dp save → serving load: params bit-exact, int8 requantize ran,
    and the meta row is the transport-ready set_weights payload."""
    path = write_demo_checkpoint(str(tmp_path), step=7)
    events = FaultEvents()
    out = load_serving_weights(path, events=events)
    assert out["spec"].layout == "dp"
    assert out["meta"]["step"] == 7
    assert out["meta"]["layout"] == "dp"
    assert out["meta"]["path"] == os.path.abspath(path)
    assert out["meta"]["digest"] == tree_digest(out["quantized"])
    assert len(out["meta"]["digest"]) == 64
    assert _has_int8_leaf(out["quantized"])
    assert events.ckpt_verify_failures == 0
    # Same checkpoint, second load: identical weights identity.
    again = load_serving_weights(path)
    assert again["meta"]["digest"] == out["meta"]["digest"]
    assert _params_equal(out["params"], again["params"])


@pytest.mark.parametrize("layout", ["zero1", "fsdp"])
def test_load_serving_weights_reshards_train_layout(tmp_path, mesh8,
                                                    lm_base, layout):
    """save@{zero1,fsdp} world 8 → serving world 1: the flat shards
    fold back into the exact params tree the trainer held (bit-exact
    vs the pre-shard leaves), requantized through the serving
    quantizer with the manifest's logical digest re-verified after."""
    from distributed_machine_learning_tpu.parallel.fsdp import (
        shard_fsdp_state,
    )
    from distributed_machine_learning_tpu.parallel.zero1 import (
        shard_zero1_state,
    )

    shard = shard_zero1_state if layout == "zero1" else shard_fsdp_state
    state8, _, n_elems = shard(lm_base, mesh8)
    spec8 = ShardSpec(layout, world=8, n_elems=n_elems)
    path = save_checkpoint(tmp_path / "train", state8, shard_spec=spec8)
    events = FaultEvents()
    out = load_serving_weights(path, lm_base.params, events=events)
    assert out["spec"].layout == layout
    assert out["meta"]["layout"] == layout
    assert _params_equal(out["params"], lm_base.params)
    assert _has_int8_leaf(out["quantized"])
    assert events.ckpt_verify_failures == 0
    assert events.reshard_restores == 1


def test_load_serving_weights_needs_template_for_flat_layouts(
        tmp_path, mesh8, lm_base):
    from distributed_machine_learning_tpu.parallel.zero1 import (
        shard_zero1_state,
    )

    state8, _, n = shard_zero1_state(lm_base, mesh8)
    path = save_checkpoint(tmp_path / "t", state8,
                           shard_spec=ShardSpec("zero1", world=8,
                                                n_elems=n))
    with pytest.raises(ValueError, match="template_params"):
        load_serving_weights(path)


def test_cross_world_corruption_never_reaches_serving(tmp_path, mesh8,
                                                      lm_base):
    """A corrupted train-side checkpoint fails verification inside the
    reshard, is quarantined + counted, and the watcher's next walk
    skips it — no unverified bytes ever reach a replica."""
    from distributed_machine_learning_tpu.parallel.zero1 import (
        shard_zero1_state,
    )

    state8, _, n = shard_zero1_state(lm_base, mesh8)
    path = save_checkpoint(tmp_path / "t", state8,
                           shard_spec=ShardSpec("zero1", world=8,
                                                n_elems=n))
    corrupt_checkpoint_data(path)
    events = FaultEvents()
    with pytest.raises(CheckpointVerifyError):
        load_serving_weights(path, lm_base.params, events=events)
    assert events.ckpt_verify_failures >= 1
    # Quarantined: the verified-chain walk skips the dir entirely.
    assert latest_checkpoint(tmp_path / "t") is None


def test_post_requantize_digest_catches_tampered_restore(
        tmp_path, mesh8, lm_base, monkeypatch):
    """The end-to-end chain: flip ONE element between the (passing)
    restore and the quantizer, and the post-requantize digest check
    against the manifest's logical leaf sha256 fails loudly, counted,
    with the checkpoint quarantined."""
    import jax.numpy as jnp

    import distributed_machine_learning_tpu.runtime.deploy as deploy_mod
    from distributed_machine_learning_tpu.parallel.zero1 import (
        shard_zero1_state,
    )

    state8, _, n = shard_zero1_state(lm_base, mesh8)
    path = save_checkpoint(tmp_path / "t", state8,
                           shard_spec=ShardSpec("zero1", world=8,
                                                n_elems=n))
    real = deploy_mod.reshard_restore

    def tampered(p, world=1, events=None):
        state, spec = real(p, world=world, events=events)
        vec = np.asarray(state.param_flat).copy()
        vec[spec.n_elems // 2] += 1.0  # in-memory bit-flip post-restore
        return state.replace(param_flat=jnp.asarray(vec)), spec

    monkeypatch.setattr(deploy_mod, "reshard_restore", tampered)
    events = FaultEvents()
    with pytest.raises(CheckpointVerifyError, match="post-requantize"):
        load_serving_weights(path, lm_base.params, events=events)
    assert events.ckpt_verify_failures == 1
    assert latest_checkpoint(tmp_path / "t") is None


# ---------------------------------------------------------------------------
# Fleet plumbing for the swap / canary / chaos campaigns
# ---------------------------------------------------------------------------


def _default_on_swap_for(rank):
    def on_swap(version, rec):
        return versioned_step(version)

    return on_swap


def _deploy_fleet(tmp_path, *, replicas, world, on_swap_for=None,
                  telemetry_dir=None, replica_timeout_s=2.0,
                  micro_batch=2, service_time=0.0, backend="inproc"):
    """Router + workers over a dir-mirrored in-proc hub (or the file
    backend, whose serving records the offline tools can read); every
    worker carries the ISSUE 18 ``on_swap`` seam (default: rebuild the
    version-tagged synthetic step)."""
    gang = str(tmp_path / "gang")
    if backend == "inproc":
        hub = InProcHub(mirror_dir=gang)
        make_tx = lambda: InProcTransport(hub)  # noqa: E731
    else:
        os.makedirs(gang, exist_ok=True)
        make_tx = lambda: FileTransport(gang)  # noqa: E731
    events = FaultEvents()
    tels = []
    router_tel = None
    if telemetry_dir:
        router_tel = Telemetry(telemetry_dir, instance="router",
                               enabled=True)
        tels.append(router_tel)
    router = ServingRouter(
        make_tx(),
        ServingConfig(replicas=replicas, max_queue=64,
                      micro_batch=micro_batch,
                      replica_timeout_s=replica_timeout_s, poll_s=0.002),
        events=events, telemetry=router_tel)
    on_swap_for = on_swap_for or _default_on_swap_for
    wcfg = ServingWorkerConfig(heartbeat_interval=0.02,
                               micro_batch=micro_batch)
    fleet = []
    for rank in range(world):
        stop = threading.Event()
        tel = None
        if telemetry_dir:
            tel = Telemetry(telemetry_dir, instance=f"replica{rank}",
                            enabled=True)
            tels.append(tel)
        t, out = start_worker_thread(
            make_tx(), rank,
            versioned_step(0, service_time), stop, wcfg,
            on_swap=on_swap_for(rank), telemetry=tel)
        fleet.append((rank, stop, t, out))
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          name="deploy-router", daemon=True)
    rt.start()
    return {"make_tx": make_tx, "gang": gang, "events": events,
            "router": router, "fleet": fleet, "tels": tels,
            "stop_router": stop_router, "rt": rt}


def _teardown_fleet(f):
    verdict = f["router"].close()
    f["stop_router"].set()
    for _, stop, t, _ in f["fleet"]:
        stop.set()
        t.join(5.0)
    f["rt"].join(5.0)
    for tel in f["tels"]:
        tel.close()
    return verdict


def _wait_live(router, n, deadline_s=30.0):
    deadline = time.monotonic() + deadline_s
    while True:
        with router._lock:
            live = len(router._replicas)
        if live >= n:
            return
        assert time.monotonic() < deadline, "fleet never warmed up"
        time.sleep(0.01)


def _start_load(router, *, min_requests, done):
    """Sustained synthetic load (the cli/deploy.py client shape):
    traffic keeps flowing until ``done`` is set AND at least
    ``min_requests`` were admitted — canary windows need completions.
    Returns ``(thread, stop_event, counter)``."""
    stop = threading.Event()
    counter = {"n": 0}

    def load():
        rng = 12345
        while not stop.is_set():
            if done.is_set() and counter["n"] >= min_requests:
                return
            rng = (1103515245 * rng + 12345) % (1 << 31)
            prompt = [1 + (rng >> s) % 13 for s in (3, 7, 11)][
                :1 + rng % 3]
            try:
                router.submit(prompt)
                counter["n"] += 1
            except Overloaded:
                time.sleep(0.002)

    t = threading.Thread(target=load, name="deploy-load", daemon=True)
    t.start()
    return t, stop, counter


def _controller(f, ckpt_dir, **over):
    cfg = dict(checkpoint_dir=str(ckpt_dir), canary_replicas=1,
               canary_every_n=2, canary_window=8,
               commit_timeout_s=10.0, judge_timeout_s=30.0,
               poll_s=0.005)
    cfg.update(over)
    return DeployController(
        f["make_tx"](), f["router"], DeployConfig(**cfg),
        events=f["events"], quality_fn=quality_probe)


# ---------------------------------------------------------------------------
# The worker's drain-then-commit swap seam
# ---------------------------------------------------------------------------


def test_worker_hot_swap_commits_and_versions_every_post(tmp_path):
    """Transport-level swap against one live replica: ``set_weights``
    stages (no fence — old work keeps completing), the worker drains,
    calls ``on_swap`` with the staged record, commits, and every later
    post carries the new version; its summary counts the swap."""
    calls = []

    def on_swap_for(rank):
        def on_swap(version, rec):
            calls.append((rank, version, rec))
            return versioned_step(version)

        return on_swap

    f = _deploy_fleet(tmp_path, replicas=1, world=1,
                      on_swap_for=on_swap_for)
    router, tx = f["router"], f["make_tx"]()
    try:
        _wait_live(router, 1)
        rid_old = router.submit([1, 2, 3])
        assert router.wait_idle(30.0), router.audit()
        tx.set_weights(0, 1, {"step": 5, "digest": "d" * 64})
        deadline = time.monotonic() + 10.0
        while True:
            rec = tx.read_serving(0).get("weights") or {}
            if int(rec.get("version", 0)) == 1:
                assert rec.get("pending") is None
                break
            assert time.monotonic() < deadline, rec
            time.sleep(0.005)
        rid_new = router.submit([4, 5])
        assert router.wait_idle(30.0), router.audit()
        assert router.result(rid_old)["version"] == 0
        new_rec = router.result(rid_new)
        assert new_rec["version"] == 1
        # The swapped step really serves: echo + checksum contract.
        assert new_rec["result"] == [4, 5, checksum_token([4, 5])]
    finally:
        verdict = _teardown_fleet(f)
    assert verdict["exactly_once"], verdict
    assert len(calls) == 1
    swap_rank, swap_version, swap_rec = calls[0]
    assert swap_rank == 0 and swap_version == 1
    assert swap_rec["pending"] == 1 and swap_rec["step"] == 5
    (_, _, _, out), = f["fleet"]
    assert out["swaps"] == 1 and out["weight_version"] == 1


# ---------------------------------------------------------------------------
# The deploy state machine: watcher → canary → promote / roll back
# ---------------------------------------------------------------------------


def test_watcher_deploys_promotes_and_skips_corrupt(tmp_path,
                                                    monkeypatch):
    """The full promote arc through the watcher: ``poll_once`` picks up
    a fresh verified checkpoint, canaries it under live load, and
    promotes the whole fleet.  Then both bad-checkpoint paths: on-disk
    corruption is quarantined inside the ``latest_checkpoint`` chain
    walk (the watcher falls back, counted, fleet untouched), a
    load-time verify failure surfaces as ``deploy_verify_failed`` in
    the ledger — and the next good step still deploys fine."""
    import distributed_machine_learning_tpu.runtime.deploy as deploy_mod

    ckpts = tmp_path / "ckpts"
    f = _deploy_fleet(tmp_path, replicas=3, world=3)
    router, events = f["router"], f["events"]
    ctl = _controller(f, ckpts)
    done = threading.Event()
    lt, lstop, _ = _start_load(router, min_requests=60, done=done)
    try:
        _wait_live(router, 3)
        assert ctl.poll_once() is None  # empty dir: nothing to deploy
        write_demo_checkpoint(str(ckpts), step=100)
        out = ctl.poll_once()
        assert out["outcome"] == "promoted", out
        assert out["step"] == 100
        assert out["canary"]["count"] >= 8 and out["canary"]["bad"] == 0
        assert ctl.state == "promoted"
        assert ctl.deployed_version == 1
        assert ctl.deployed_meta["step"] == 100
        assert ctl.poll_once() is None  # same step: not redeployed
        versions = router.audit()["weight_versions"]
        assert set(versions.values()) == {1}, versions
        assert events.weight_swaps == 3
        assert events.canary_promotions == 1
        assert events.canary_rollbacks == 0
        assert [h["why"] for h in ctl.history] == [
            "canary", "promote", "promote"]
        # On-disk corruption: the verified-chain walk quarantines the
        # step and falls back — nothing to deploy, fleet untouched.
        bad = write_demo_checkpoint(str(ckpts), step=150)
        corrupt_checkpoint_data(bad)
        assert ctl.poll_once() is None
        assert events.ckpt_verify_failures >= 1
        assert set(router.audit()["weight_versions"].values()) == {1}
        # A load-time verify failure (the post-requantize class): the
        # watcher surfaces it as deploy_verify_failed, counted in the
        # deploy row, and the fleet stays on the deployed version.
        real_load = deploy_mod.load_serving_weights

        def flaky(path, template_params=None, *, events=None):
            if os.path.basename(path) == "step_200":
                raise CheckpointVerifyError(
                    "injected: post-requantize digest mismatch")
            return real_load(path, template_params, events=events)

        monkeypatch.setattr(deploy_mod, "load_serving_weights", flaky)
        write_demo_checkpoint(str(ckpts), step=200)
        out = ctl.poll_once()
        assert out["outcome"] == "verify_failed" and out["step"] == 200
        assert set(router.audit()["weight_versions"].values()) == {1}
        # The chain recovers: the next good step deploys as v2.
        write_demo_checkpoint(str(ckpts), step=300)
        out = ctl.poll_once()
        assert out["outcome"] == "promoted" and out["step"] == 300
        assert set(router.audit()["weight_versions"].values()) == {2}
        done.set()
        lt.join(30.0)
        assert router.wait_idle(60.0), router.audit()
    finally:
        done.set()
        lstop.set()
        verdict = _teardown_fleet(f)
    assert verdict["exactly_once"], verdict
    summary = ctl.summary()
    assert summary["state"] == "promoted"
    assert summary["deployed_version"] == 2
    assert summary["swaps"] == 6
    assert [d["outcome"] for d in summary["deploys"]] == [
        "promoted", "promoted"]
    # Health ledger carries the whole state machine for the tools.
    kinds = [e.get("kind")
             for e in FileTransport(f["gang"]).snapshot()["health"]]
    assert kinds.count("deploy_canary") == 2
    assert kinds.count("deploy_promote") == 2
    assert kinds.count("deploy_verify_failed") == 1
    assert kinds.count("weight_swap") == 6


def test_canary_quality_regression_rolls_back(tmp_path):
    """The injected-regression arc: v1's step mis-computes the checksum
    token, the canary probe fails inside the window, and the controller
    re-swaps the canary back to v0 — counted, ledgered, with zero
    dropped requests and the fleet back on the prior version."""

    def on_swap_for(rank):
        def on_swap(version, rec):
            return versioned_step(version, corrupt=version == 1)

        return on_swap

    ckpts = tmp_path / "ckpts"
    f = _deploy_fleet(tmp_path, replicas=3, world=3,
                      on_swap_for=on_swap_for)
    router, events = f["router"], f["events"]
    ctl = _controller(f, ckpts)
    done = threading.Event()
    lt, lstop, _ = _start_load(router, min_requests=60, done=done)
    try:
        _wait_live(router, 3)
        write_demo_checkpoint(str(ckpts), step=100)
        out = ctl.poll_once()
        assert out["outcome"] == "rolled_back", out
        assert "quality regression" in out["reason"]
        assert out["to_version"] == 0 and out["unrecovered"] == []
        assert ctl.state == "rolled_back"
        assert ctl.deployed_version == 0  # never promoted
        assert set(router.audit()["weight_versions"].values()) == {0}
        assert events.canary_rollbacks == 1
        assert events.canary_promotions == 0
        assert events.weight_swaps == 2  # canary out + rollback home
        assert [h["why"] for h in ctl.history] == ["canary", "rollback"]
        done.set()
        lt.join(30.0)
        assert router.wait_idle(60.0), router.audit()
    finally:
        done.set()
        lstop.set()
        verdict = _teardown_fleet(f)
    # Zero requests dropped across swap + rollback.
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"]
    kinds = [e.get("kind")
             for e in FileTransport(f["gang"]).snapshot()["health"]]
    assert "deploy_rollback" in kinds


def test_canary_slo_burn_rolls_back(tmp_path):
    """The deploy-scoped SLO engine (telemetry/slo.py burn-rate rule)
    judges the canary's outcomes alone: a correct-but-slow v1 burns a
    tight latency objective and rolls back even though every probe
    passed."""

    def on_swap_for(rank):
        def on_swap(version, rec):
            # Correct answers, 20ms service: quality clean, SLO burns.
            return versioned_step(version, service_time_s=0.02)

        return on_swap

    ckpts = tmp_path / "ckpts"
    f = _deploy_fleet(tmp_path, replicas=2, world=2,
                      on_swap_for=on_swap_for)
    router, events = f["router"], f["events"]
    ctl = _controller(f, ckpts, canary_window=6, slo=("p99<=1ms",))
    done = threading.Event()
    lt, lstop, _ = _start_load(router, min_requests=40, done=done)
    try:
        _wait_live(router, 2)
        write_demo_checkpoint(str(ckpts), step=100)
        out = ctl.poll_once()
        assert out["outcome"] == "rolled_back", out
        assert out["reason"].startswith("SLO burn on canary: p99<=1ms")
        assert events.canary_rollbacks == 1
        assert set(router.audit()["weight_versions"].values()) == {0}
        done.set()
        lt.join(30.0)
        assert router.wait_idle(60.0), router.audit()
    finally:
        done.set()
        lstop.set()
        verdict = _teardown_fleet(f)
    assert verdict["exactly_once"], verdict


# ---------------------------------------------------------------------------
# Offline observability: the tools render the deployment state machine
# ---------------------------------------------------------------------------


def test_deployment_renders_in_status_tools_and_trace(tmp_path):
    """Satellites 2 + 4: after a promote-then-rollback run, (a)
    serve_status shows per-replica weight versions, the swap history,
    and the rollback reason; (b) gang_status's serving section renders
    the same edges; (c) the merged Perfetto timeline carries the
    ``weight_swap`` instants on the replica tracks."""

    def on_swap_for(rank):
        def on_swap(version, rec):
            return versioned_step(version, corrupt=version == 2)

        return on_swap

    ckpts = tmp_path / "ckpts"
    teldir = str(tmp_path / "telemetry")
    # File backend: the tools read the REAL serving records (per-
    # replica weight versions) off disk, not just the mirrored ledger.
    f = _deploy_fleet(tmp_path, replicas=2, world=2, backend="file",
                      on_swap_for=on_swap_for, telemetry_dir=teldir)
    router = f["router"]
    ctl = _controller(f, ckpts)
    done = threading.Event()
    lt, lstop, _ = _start_load(router, min_requests=40, done=done)
    try:
        _wait_live(router, 2)
        write_demo_checkpoint(str(ckpts), step=100)
        assert ctl.poll_once()["outcome"] == "promoted"
        write_demo_checkpoint(str(ckpts), step=200)
        out = ctl.poll_once()
        assert out["outcome"] == "rolled_back", out
        done.set()
        lt.join(30.0)
        assert router.wait_idle(60.0), router.audit()
    finally:
        done.set()
        lstop.set()
        verdict = _teardown_fleet(f)
    assert verdict["exactly_once"], verdict

    serve_status = _load_tool("serve_status")
    status = serve_status.collect(f["gang"], teldir)
    dep = status["deployment"]
    assert dep["state"] == "rolled_back"
    assert dep["promotions"] == 1 and dep["rollbacks"] == 1
    assert len(dep["swaps"]) >= 3  # 2 promote swaps + canary + rollback
    rendered = serve_status.render(status)
    assert "Continuous deployment" in rendered
    assert "weights v1" in rendered       # replicas back on v1
    assert "swap: replica" in rendered
    assert "rollback" in rendered and "quality regression" in rendered

    gang_status = _load_tool("gang_status")
    grendered = gang_status.render(gang_status.collect(f["gang"],
                                                       teldir))
    assert "weight_swap" in grendered or "swap" in grendered
    assert "deploy_rollback" in grendered or "rollback" in grendered

    trace_merge = _load_tool("trace_merge")
    merged, counts = trace_merge.merge_traces(teldir)
    swap_instants = [e for e in merged["traceEvents"]
                     if e.get("name") == "weight_swap"]
    # v1 on both replicas, v2 canary, rollback-to-v1: >= 4 instants,
    # re-homed onto the serving pid block.
    assert len(swap_instants) >= 4, json.dumps(counts)
    assert all(e["pid"] >= trace_merge.SERVING_PID_BASE
               for e in swap_instants)


# ---------------------------------------------------------------------------
# Tier-1 chaos campaign: replica killed mid-swap
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_chaos_replica_killed_mid_swap_rolls_back(tmp_path):
    """The ISSUE 18 acceptance campaign: 6 live replicas + 1 warm
    spare under sustained load; a deploy rolls mid-load and the canary
    replica DIES inside ``on_swap`` (staged, never committed).  The
    controller must time out the commit and roll back — counted and
    ledgered, never silent; the fleet must heal by spare promotion
    (the dead rank's work requeued, exactly once); and a follow-up
    deploy must promote cleanly on the healed fleet.  Wall-clock
    capped."""
    t_start = time.monotonic()
    victim = {"rank": None}

    def on_swap_for(rank):
        def on_swap(version, rec):
            if version == 1 and rank == victim["rank"]:
                raise TransportError("injected: replica died mid-swap")
            return versioned_step(version)

        return on_swap

    ckpts = tmp_path / "ckpts"
    f = _deploy_fleet(tmp_path, replicas=6, world=7,
                      on_swap_for=on_swap_for, replica_timeout_s=0.4,
                      micro_batch=4)
    router, events = f["router"], f["events"]
    ctl = _controller(f, ckpts, commit_timeout_s=1.0,
                      judge_timeout_s=20.0)
    done = threading.Event()
    lt, lstop, _ = _start_load(router, min_requests=300, done=done)
    try:
        _wait_live(router, 6)
        deadline = time.monotonic() + 30.0
        while router.completed < 30:
            assert time.monotonic() < deadline, "fleet never warmed up"
            time.sleep(0.01)
        # The canary is the lowest live rank: aim the kill at it.
        victim["rank"] = min(router.audit()["weight_versions"])
        write_demo_checkpoint(str(ckpts), step=100)
        out = ctl.poll_once()
        assert out["outcome"] == "rolled_back", out
        assert "failed to commit v1" in out["reason"]
        assert out["unrecovered"] == []  # nothing committed to undo
        assert events.canary_rollbacks == 1
        assert events.weight_swaps == 0  # the stage never committed
        # Heal: the dead canary stops beating, is evicted, the spare
        # promotes, and the orphaned work re-dispatches.
        deadline = time.monotonic() + 30.0
        while events.replica_evictions < 1 or len(
                router.audit()["weight_versions"]) < 6:
            assert time.monotonic() < deadline, router.audit()
            time.sleep(0.01)
        live = router.audit()["weight_versions"]
        assert victim["rank"] not in live
        assert set(live.values()) == {0}  # everyone on the old version
        # The healed fleet still deploys: the next step promotes.
        write_demo_checkpoint(str(ckpts), step=200)
        out = ctl.poll_once()
        assert out["outcome"] == "promoted", out
        assert set(router.audit()["weight_versions"].values()) == {2}
        assert events.canary_promotions == 1
        assert events.weight_swaps == 6
        done.set()
        lt.join(60.0)
        assert router.wait_idle(60.0), router.audit()
    finally:
        done.set()
        lstop.set()
        verdict = _teardown_fleet(f)
    elapsed = time.monotonic() - t_start
    # Exactly-once across the kill, the rollback, and the redeploy.
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] >= 300
    assert verdict["unknown_results"] == 0
    assert verdict["evictions"] >= 1
    assert verdict["promotions"] >= 7  # 6 initial + the heal
    kinds = [e.get("kind")
             for e in FileTransport(f["gang"]).snapshot()["health"]]
    assert "deploy_rollback" in kinds and "deploy_promote" in kinds
    assert elapsed < CHAOS_BUDGET_S, (
        f"deploy chaos campaign took {elapsed:.1f}s (cap "
        f"{CHAOS_BUDGET_S:.0f}s)")


@pytest.mark.slow
@pytest.mark.faultinject
def test_chaos_endurance_multi_deploy_with_kills(tmp_path):
    """Endurance variant: 8 replicas + 2 spares, three deploys rolled
    under continuous load — promote, injected quality rollback, then a
    non-canary replica killed mid-canary-window before a final
    promote.  Exactly-once throughout."""
    t_start = time.monotonic()

    def on_swap_for(rank):
        def on_swap(version, rec):
            return versioned_step(version, corrupt=version == 2)

        return on_swap

    ckpts = tmp_path / "ckpts"
    f = _deploy_fleet(tmp_path, replicas=8, world=10,
                      on_swap_for=on_swap_for, replica_timeout_s=0.4,
                      micro_batch=4)
    router, events = f["router"], f["events"]
    ctl = _controller(f, ckpts, judge_timeout_s=30.0)
    done = threading.Event()
    lt, lstop, _ = _start_load(router, min_requests=600, done=done)
    try:
        _wait_live(router, 8)
        write_demo_checkpoint(str(ckpts), step=100)
        assert ctl.poll_once()["outcome"] == "promoted"
        write_demo_checkpoint(str(ckpts), step=200)
        out = ctl.poll_once()
        assert out["outcome"] == "rolled_back"
        assert "quality regression" in out["reason"]
        assert set(router.audit()["weight_versions"].values()) == {1}
        # Kill a non-canary replica, then deploy through the churn.
        live = sorted(router.audit()["weight_versions"])
        target = live[-1]
        for rank, stop, _, _ in f["fleet"]:
            if rank == target:
                stop.set()
        write_demo_checkpoint(str(ckpts), step=300)
        out = ctl.poll_once()
        # Promote unless the dying rank was caught mid-promote-swap;
        # either way the outcome is explicit and counted.
        assert out["outcome"] in ("promoted", "rolled_back"), out
        deadline = time.monotonic() + 30.0
        while len(router.audit()["weight_versions"]) < 8:
            assert time.monotonic() < deadline, router.audit()
            time.sleep(0.01)
        done.set()
        lt.join(60.0)
        assert router.wait_idle(90.0), router.audit()
    finally:
        done.set()
        lstop.set()
        verdict = _teardown_fleet(f)
    elapsed = time.monotonic() - t_start
    assert verdict["exactly_once"], verdict
    assert verdict["admitted"] == verdict["completed"] >= 600
    assert events.canary_promotions >= 1
    assert events.canary_rollbacks >= 1
    assert elapsed < 2 * CHAOS_BUDGET_S, elapsed


# ---------------------------------------------------------------------------
# ISSUE 19: the engine-mode swap fence (hot swap during active decode)
# ---------------------------------------------------------------------------


def test_engine_hot_swap_during_active_decode_no_mixing(tmp_path):
    """A weight version staged while continuous-batching sequences are
    mid-decode waits for the engine drain: zero requests dropped, and
    every completion decodes token-for-token under exactly ONE weights
    version — in-flight sequences finish under the old weights, every
    post-commit request serves the new ones.  The engine-step-boundary
    fence, proven through the worker's real swap path."""
    import jax
    import jax.numpy as jnp

    from distributed_machine_learning_tpu.inference.continuous import (
        ContinuousEngine,
        EngineConfig,
    )
    from distributed_machine_learning_tpu.inference.generate import (
        generate,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )

    MAX_NEW = 8
    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                          n_heads=4, n_kv_heads=2)
    params1 = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))["params"]
    params2 = model.init(jax.random.PRNGKey(7),
                         jnp.zeros((1, 8), jnp.int32))["params"]

    def ref(params, prompt):
        return np.asarray(generate(
            model, params, np.asarray([prompt], np.int32), MAX_NEW
        ))[0].tolist()

    engine = ContinuousEngine(model, params1, EngineConfig(
        max_lanes=2, block_size=4, num_blocks=32, max_len=16,
        max_new=MAX_NEW, levers=("latency",)))
    # Compile BEFORE the replica starts heartbeating: XLA tracing
    # inside the first live step would look like a stale beat.
    engine.warmup(prompt_lens=(3,))
    swap_calls = []

    def on_swap(version, rec):
        # The production shape: load the staged weights into the SAME
        # engine.  swap_params would raise if the worker had not
        # drained first — the fence under test.
        swap_calls.append((version, engine.in_flight()))
        engine.swap_params(params2, version=version)
        return None

    hub = InProcHub(mirror_dir=str(tmp_path / "gang"))
    make_tx = lambda: InProcTransport(hub)  # noqa: E731
    router = ServingRouter(
        make_tx(), ServingConfig(replicas=1, micro_batch=4,
                                 poll_s=0.002))
    stop = threading.Event()
    t, out = start_worker_thread(
        make_tx(), 0, None, stop,
        ServingWorkerConfig(heartbeat_interval=0.02, micro_batch=4),
        on_swap=on_swap, engine=engine)
    stop_router = threading.Event()
    rt = threading.Thread(target=router.run, args=(stop_router,),
                          name="engine-swap-router", daemon=True)
    rt.start()
    try:
        _wait_live(router, 1)
        prompts = {}
        for i in range(6):
            p = [1 + i, 2, 3]
            prompts[router.submit(list(p))] = p
        # Stage the new version the moment sequences are mid-decode.
        deadline = time.monotonic() + 60.0
        while engine.in_flight() == 0:
            assert time.monotonic() < deadline, "engine never started"
            time.sleep(0.002)
        tx = make_tx()
        tx.set_weights(0, 1, {"step": 5, "digest": "d" * 64})
        while int((tx.read_serving(0).get("weights") or {})
                  .get("version", 0) or 0) != 1:
            assert time.monotonic() < deadline, "commit never landed"
            time.sleep(0.005)
        late = {}
        for i in range(3):
            p = [9 + i, 2, 3]
            late[router.submit(list(p))] = p
        assert router.wait_idle(60.0), router.audit()
        seen_versions = set()
        for rid, p in {**prompts, **late}.items():
            entry = router.result(rid)
            assert entry is not None and entry["state"] == "done"
            v = entry["version"]
            seen_versions.add(v)
            want = ref(params1 if v == 0 else params2, p)
            assert entry["result"] == want, (
                f"{rid} mixed weight versions (posted v{v})")
        for rid in late:
            assert router.result(rid)["version"] == 1
        # Both versions actually served: the drain finished the
        # in-flight work under v0, the backlog + late work under v1.
        assert seen_versions == {0, 1}
    finally:
        verdict = router.close()
        stop_router.set()
        stop.set()
        t.join(10.0)
        rt.join(10.0)
    assert verdict["exactly_once"], verdict
    assert [v for v, _ in swap_calls] == [1]
    # The fence held: on_swap saw a fully drained engine.
    assert swap_calls[0][1] == 0
    assert out["swaps"] == 1 and out["aborted"] == 0
    assert engine.in_flight() == 0 and engine.queued() == 0
    engine.allocator.check_invariants()
