"""ResNet family (18/34/50) for CIFAR-10 and ImageNet-class inputs.

BASELINE.json's headline configs name **ResNet-18/CIFAR-10** (with
ResNet-50/ImageNet as the scale-out stretch) even though the reference
code ships VGG-11 (`part1/model.py:49-50`; discrepancy recorded in
SURVEY.md §0.1).  This module provides that model family so both the
reference's actual model (VGG) and its metadata's model (ResNet) are
first-class flagship workloads.

Architecture follows the standard torchvision layout — BasicBlock for
18/34, Bottleneck (4× expansion) for 50 — with a `cifar_stem` flag:

- `cifar_stem=True` (default): 3×3 stride-1 stem, no max-pool — the
  standard CIFAR adaptation for 32×32 inputs (a 7×7/2 stem + pool would
  collapse a 32×32 image to 8×8 before the first block).
- `cifar_stem=False`: the ImageNet stem (7×7 stride-2 conv + 3×3
  stride-2 max-pool) for 224×224-class inputs.

TPU-first notes: NHWC layout, optional bfloat16 trunk (params fp32;
casts fuse into the convs so the MXU runs bf16), BatchNorm running stats
in the `batch_stats` collection (axis-synced by the distributed train
step), global average pool + Dense head.  No data-dependent Python
control flow — the forward traces to a single fusable XLA graph.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# (block, layers-per-stage) per torchvision's resnet cfg table.
_cfg: dict[str, tuple[str, Sequence[int]]] = {
    "ResNet18": ("basic", (2, 2, 2, 2)),
    "ResNet34": ("basic", (3, 4, 6, 3)),
    "ResNet50": ("bottleneck", (3, 4, 6, 3)),
}

_STAGE_FEATURES = (64, 128, 256, 512)


class _BasicBlock(nn.Module):
    features: int
    strides: int
    compute_dtype: Any

    @nn.compact
    def __call__(self, x, *, train: bool):
        norm = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.compute_dtype,
            name=name,
        )
        residual = x
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides),
                    padding=1, use_bias=False, dtype=self.compute_dtype,
                    name="conv1")(x)
        y = norm("bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), (1, 1), padding=1, use_bias=False,
                    dtype=self.compute_dtype, name="conv2")(y)
        y = norm("bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               dtype=self.compute_dtype, name="downsample")(residual)
            residual = norm("bn_down")(residual)
        return nn.relu(y + residual)


class _Bottleneck(nn.Module):
    features: int  # inner width; output is 4× this
    strides: int
    compute_dtype: Any

    @nn.compact
    def __call__(self, x, *, train: bool):
        norm = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.compute_dtype,
            name=name,
        )
        out_features = self.features * 4
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False,
                    dtype=self.compute_dtype, name="conv1")(x)
        y = nn.relu(norm("bn1")(y))
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides),
                    padding=1, use_bias=False, dtype=self.compute_dtype,
                    name="conv2")(y)
        y = nn.relu(norm("bn2")(y))
        y = nn.Conv(out_features, (1, 1), use_bias=False,
                    dtype=self.compute_dtype, name="conv3")(y)
        y = norm("bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(out_features, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               dtype=self.compute_dtype, name="downsample")(residual)
            residual = norm("bn_down")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet for NHWC input, `num_classes` logits.

    Attributes:
      name_cfg: one of ResNet18/ResNet34/ResNet50.
      num_classes: classifier width (CIFAR-10: 10).
      cifar_stem: 3×3/1 stem without max-pool (for 32×32 inputs) vs the
        ImageNet 7×7/2 stem + pool.
      compute_dtype: trunk dtype; bfloat16 targets the MXU.
    """

    name_cfg: str = "ResNet18"
    num_classes: int = 10
    cifar_stem: bool = True
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        block_kind, stage_sizes = _cfg[self.name_cfg]
        block_cls = _BasicBlock if block_kind == "basic" else _Bottleneck
        x = x.astype(self.compute_dtype)

        if self.cifar_stem:
            x = nn.Conv(64, (3, 3), (1, 1), padding=1, use_bias=False,
                        dtype=self.compute_dtype, name="stem_conv")(x)
        else:
            x = nn.Conv(64, (7, 7), (2, 2), padding=3, use_bias=False,
                        dtype=self.compute_dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.compute_dtype,
                         name="stem_bn")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for stage, (features, n_blocks) in enumerate(
            zip(_STAGE_FEATURES, stage_sizes)
        ):
            for block in range(n_blocks):
                strides = 2 if (stage > 0 and block == 0) else 1
                x = block_cls(
                    features=features,
                    strides=strides,
                    compute_dtype=self.compute_dtype,
                    name=f"stage{stage + 1}_block{block + 1}",
                )(x, train=train)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype, name="fc")(x)
        # Logits in fp32 for the loss's logsumexp even with a bf16 trunk.
        return x.astype(jnp.float32)


def ResNet18(**kw) -> ResNet:
    return ResNet(name_cfg="ResNet18", **kw)


def ResNet34(**kw) -> ResNet:
    return ResNet(name_cfg="ResNet34", **kw)


def ResNet50(**kw) -> ResNet:
    return ResNet(name_cfg="ResNet50", **kw)
