"""Training state pytree.

The reference keeps its state implicitly inside torch Modules and the
optimizer (``part1/main.py:117-121``).  Here state is an explicit,
immutable pytree so the whole train step is a pure function XLA can
compile and shard: params, momentum buffers, BatchNorm running stats
(part3's model is the only one with BN — ``part3/model.py:24``), and the
step counter / PRNG key for data augmentation.
"""

from __future__ import annotations

import jax
from flax import struct

from distributed_machine_learning_tpu.train.sgd import SGDConfig


@struct.dataclass
class TrainState:
    params: dict
    momentum: dict
    batch_stats: dict  # empty dict for BN-free models (part1/2a/2b parity)
    step: jax.Array
    rng: jax.Array
    config: SGDConfig = struct.field(pytree_node=False)

    @classmethod
    def create(cls, params, batch_stats=None, rng=None, config: SGDConfig | None = None):
        import jax.numpy as jnp

        from distributed_machine_learning_tpu.train.optimizers import (
            init_for_config,
        )

        if rng is None:
            rng = jax.random.PRNGKey(0)
        config = config or SGDConfig()
        return cls(
            params=params,
            momentum=init_for_config(config)(params),
            batch_stats={} if batch_stats is None else batch_stats,
            step=jnp.zeros((), jnp.int32),
            rng=rng,
            config=config,
        )
