"""Shared GSPMD machinery for the sharded-parameter strategies
(TP / EP / per-layer FSDP).

All three follow the same recipe — a ``spec_for(path, shape)`` rule
table mapped over the param tree (TP/EP rules key on the path, the
per-layer FSDP rule on the shape), a TrainState-shaped sharding pytree,
and a jit cache keyed by the state's tree structure (SGDConfig is
*static* pytree metadata, so differently configured states need
distinct jitted signatures).  This module is that recipe, written once.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.train.state import TrainState

SpecFor = Callable[[tuple[str, ...], tuple[int, ...]], P]


def param_specs(params, spec_for: SpecFor):
    """Map a (path, shape)→PartitionSpec rule over a param tree.
    TP/EP rules key on the path; the per-layer FSDP rule keys on the
    shape (which dim is divisible) — both get both."""

    def spec(path, leaf):
        keys = tuple(k.key if hasattr(k, "key") else str(k) for k in path)
        return spec_for(keys, tuple(leaf.shape))

    return jax.tree_util.tree_map_with_path(spec, params)


def state_shardings(state: TrainState, mesh: Mesh, spec_for: SpecFor) -> TrainState:
    """NamedSharding pytree for a TrainState: params and momentum follow
    the rule table, everything else replicates.

    The momentum slot is either params-shaped (SGD/LARS) or a dict of
    params-shaped trees (AdamW's ``{"mu","nu"}`` — train/adamw.py);
    each moment tree inherits its parameter's spec."""
    from distributed_machine_learning_tpu.train.optimizers import moment_layout

    specs = param_specs(state.params, spec_for)
    to_sharding = lambda s: NamedSharding(mesh, s)
    spec_shardings = jax.tree_util.tree_map(to_sharding, specs)
    mom_shardings = moment_layout(spec_shardings, state.params, state.momentum)
    return TrainState(
        params=spec_shardings,
        momentum=mom_shardings,
        batch_stats=jax.tree_util.tree_map(
            lambda _: to_sharding(P()), state.batch_stats
        ),
        step=to_sharding(P()),
        rng=to_sharding(P()),
        config=state.config,
    )


def shard_state(state: TrainState, mesh: Mesh, spec_for: SpecFor) -> TrainState:
    """Place a host/replicated TrainState into the rule table's layout."""
    return jax.tree_util.tree_map(
        jax.device_put, state, state_shardings(state, mesh, spec_for)
    )


def make_cached_sharded_step(impl, mesh: Mesh, spec_for: SpecFor, batch_sharding):
    """jit ``impl(state, tokens, targets)`` with shardings derived from the
    first call's actual state, cached per state tree structure."""
    jitted: dict = {}

    def build(state):
        shardings = state_shardings(state, mesh, spec_for)
        return jax.jit(
            impl,
            in_shardings=(shardings, batch_sharding, batch_sharding),
            out_shardings=(shardings, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        )

    def step(state: TrainState, tokens, targets):
        key = jax.tree_util.tree_structure(state)
        fn = jitted.get(key)
        if fn is None:
            fn = jitted[key] = build(state)
        return fn(state, tokens, targets)

    # AOT access for the Layer-2 HLO audits and benches: lower without
    # executing (abstract ShapeDtypeStruct states work — the sharding
    # derivation only reads shapes).
    step.lower = lambda state, tokens, targets: build(state).lower(
        state, tokens, targets)
    return step
