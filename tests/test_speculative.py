"""Speculative decoding (inference/speculative.py): the draft must
change SPEED, never the distribution — greedy output is pinned bitwise
to the target-only stream."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.inference.generate import (
    make_generate_fn,
)
from distributed_machine_learning_tpu.inference.speculative import (
    make_speculative_generate_fn,
)
from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import init_lm_state

VOCAB = 48


def _models():
    target = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=3,
                           n_heads=4)
    draft = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                          n_heads=2)
    return (target, init_lm_state(target).params,
            draft, init_lm_state(draft, seed=7).params)


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_greedy_speculative_bitwise_equals_vanilla(rng, gamma):
    """Any draft — here an unrelated random model with terrible
    acceptance — must produce EXACTLY the target's greedy stream."""
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 6)), jnp.int32)
    ref = make_generate_fn(target, 12)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, draft, 12, gamma=gamma)
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_greedy_speculative_with_target_as_draft(rng):
    """draft == target: every proposal accepted, output still the exact
    greedy stream (the all-accept + bonus path)."""
    target, tparams, _, _ = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    ref = make_generate_fn(target, 10)(
        tparams, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(target, target, 10, gamma=4)
    out = fn(tparams, tparams, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_sampled_speculative_runs_and_stays_in_vocab(rng):
    target, tparams, draft, dparams = _models()
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    fn = make_speculative_generate_fn(
        target, draft, 10, gamma=3, temperature=0.8, top_p=0.9
    )
    out = fn(tparams, dparams, prompt, jax.random.PRNGKey(3))
    assert out.shape == (1, 15)
    o = np.asarray(out)
    assert (o >= 0).all() and (o < VOCAB).all()
    np.testing.assert_array_equal(o[:, :5], np.asarray(prompt))


def test_speculative_guards(rng):
    target, tparams, draft, dparams = _models()
    with pytest.raises(ValueError, match="gamma"):
        make_speculative_generate_fn(target, draft, 8, gamma=0)
    with pytest.raises(ValueError, match="vocabulary"):
        make_speculative_generate_fn(
            target,
            TransformerLM(vocab_size=VOCAB + 1, d_model=16, n_layers=1,
                          n_heads=2),
            8,
        )
    fn = make_speculative_generate_fn(target, draft, 8)
    with pytest.raises(ValueError, match="batch-1"):
        fn(tparams, dparams, jnp.zeros((2, 4), jnp.int32),
           jax.random.PRNGKey(0))


def test_greedy_speculative_with_int8_target(rng):
    """Speculative composes with int8 serving: an int8-quantized target
    (and/or draft) still produces its own exact greedy stream — the
    reference is vanilla int8 decode, so quantization error and the
    speculative machinery are isolated from each other."""
    from distributed_machine_learning_tpu.ops.quant import quantize_lm_params

    target, tparams, draft, dparams = _models()
    qt = quantize_lm_params(tparams)
    qd = quantize_lm_params(dparams)
    prompt = jnp.asarray(rng.integers(0, VOCAB, (1, 5)), jnp.int32)
    ref = make_generate_fn(target, 10, quantize="int8")(
        qt, prompt, jax.random.PRNGKey(0)
    )
    fn = make_speculative_generate_fn(
        target, draft, 10, gamma=3, quantize="int8", draft_quantize="int8"
    )
    out = fn(qt, qd, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
