"""Shared pieces of the train-step implementations.

Both the replicated-DP step (``train/step.py``) and the ZeRO-3/FSDP step
(``parallel/fsdp.py``) need the same forward/loss/mutable-BatchNorm
plumbing and the same per-step, per-mesh-position RNG keying — factored
here (dependency-free of ``parallel/``) so the two cannot drift apart and
break the FSDP-vs-replicated-DP equivalence the tests assert.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from distributed_machine_learning_tpu.train.losses import cross_entropy_loss


def tree_all_finite(tree) -> jax.Array:
    """Scalar bool: every element of every leaf is finite.

    The reduction the non-finite-gradient guard runs on the (synced)
    gradients inside the compiled step — a handful of tiny ``isfinite``
    reductions XLA fuses into the backward epilogue, so the guard costs
    nothing measurable.  Computed on post-sync gradients: every device
    reduces the identical values, so the skip decision is replicated by
    construction and the cross-replica state invariant holds.
    """
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
    out = finite[0]
    for f in finite[1:]:
        out = jnp.logical_and(out, f)
    return out


def guard_update(finite, new_state, old_state):
    """Select ``new_state`` where the gradients were finite, else keep
    ``old_state`` untouched (update skipped, step NOT incremented).

    A ``jnp.where`` per leaf instead of ``lax.cond``: both branches are
    already computed (the update is cheap next to the backward pass) and
    ``where`` keeps the program branch-free — the only control flow TPUs
    like.  The skipped step is observable on the host as an unchanged
    step counter (``train/loop.py`` counts these into ``FaultEvents``).
    """
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(finite, n, o), new_state, old_state
    )


def step_rng(rng, step_ctr, axis_name: str | None):
    """Per-step augmentation key; folds in the mesh position so each data
    shard draws independent crops/flips the way each reference node draws
    from its own torch RNG (``part2/2a/main.py:199``)."""
    r = jax.random.fold_in(rng, step_ctr)
    if axis_name is not None:
        r = jax.random.fold_in(r, lax.axis_index(axis_name))
    return r


def make_loss_fn(model, batch_stats, x, labels, train: bool):
    """Build ``loss_fn(params) -> (loss, (logits, new_batch_stats))``.

    Handles the three BatchNorm cases: BN model in train mode (mutable
    running stats), BN model in eval mode, BN-free model (empty stats).
    """

    def run(params):
        variables: dict[str, Any] = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            if train:
                logits, mutated = model.apply(
                    variables, x, train=True, mutable=["batch_stats"]
                )
                return logits, mutated["batch_stats"]
            logits = model.apply(variables, x, train=False)
            return logits, batch_stats
        logits = model.apply(variables, x, train=train)
        return logits, {}

    def loss_fn(params):
        logits, new_stats = run(params)
        return cross_entropy_loss(logits, labels), (logits, new_stats)

    return loss_fn
