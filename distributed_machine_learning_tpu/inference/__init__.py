from distributed_machine_learning_tpu.inference.generate import (
    generate,
    make_generate_fn,
)

__all__ = ["generate", "make_generate_fn"]
