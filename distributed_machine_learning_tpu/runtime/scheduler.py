"""Regime-aware dispatch scheduler for the serving tier (ISSUE 19).

docs/PERF.md pins two measured serving levers: speculative decoding
wins latency 2-5.7x when the batch is THIN (per-request wall time is
decode-step count; extra draft FLOPs are free at low occupancy), and
int8 weight-only ``quant_matmul`` wins throughput when the batch is
WIDE (decode is weight-bandwidth-bound; halving weight bytes ~halves
step time at large width).  The boundary between those regimes is a
function of *load*, not of the request — so the serving fleet needs a
policy object that watches load and flips the dispatch lever.

This module is that policy, deliberately tiny and jax-free:
:class:`RegimeScheduler` observes ``(queue_depth, in_flight_width)``
each engine step — both read through the telemetry registry's gauges
so dashboards see exactly what the policy saw — and returns which
lever the next step should use.  **Hysteresis** comes from two
mechanisms, both required to not thrash at the boundary:

* a **dead band**: pressure must reach ``wide_width`` to enter the
  throughput regime but fall to ``thin_width`` (< wide) to leave it —
  oscillation inside (thin, wide) never flips;
* a **dwell**: the out-of-regime pressure must persist for
  ``dwell_steps`` consecutive observations before the flip commits —
  a one-step spike (one bursty arrival, one long retire) is ignored.

The scheduler is consulted by the continuous-batching engine
(``inference/continuous.py``) per step, and by the router
(``runtime/serving.py``) at dispatch, which stamps the chosen lever
onto each request so every replica's engine follows one fleet-wide
regime instead of N drifting local views.
"""

from __future__ import annotations

import dataclasses
import threading

LATENCY = "latency"
THROUGHPUT = "throughput"


@dataclasses.dataclass(frozen=True)
class RegimeConfig:
    """Thresholds are in units of *pressure* = queued + in-flight
    requests at observation time.  Defaults suit a W=8-lane engine:
    <= 2 outstanding means requests mostly ride alone (latency
    regime); >= 6 means the batch runs wide (throughput regime)."""

    thin_width: int = 2
    wide_width: int = 6
    dwell_steps: int = 8

    def __post_init__(self):
        if self.thin_width < 0:
            raise ValueError(f"thin_width must be >= 0: {self.thin_width}")
        if self.wide_width <= self.thin_width:
            raise ValueError(
                f"need thin_width < wide_width for a dead band, got "
                f"{self.thin_width} >= {self.wide_width}"
            )
        if self.dwell_steps < 1:
            raise ValueError(f"dwell_steps must be >= 1: {self.dwell_steps}")


class RegimeScheduler:
    """Hysteretic two-regime lever policy.

    ``observe(queue_depth, width) -> "latency" | "throughput"``.
    Thread-safe: the router thread and an engine thread may both
    observe (the lock is a leaf — held for arithmetic only).
    """

    def __init__(self, cfg: RegimeConfig | None = None, registry=None):
        self.cfg = cfg or RegimeConfig()
        self._lock = threading.Lock()
        self.lever = LATENCY
        self.flips = 0
        self._streak = 0
        self._g_regime = self._g_pressure = self._c_flips = None
        if registry is not None:
            self._g_regime = registry.gauge("serving_regime")
            self._g_pressure = registry.gauge("serving_pressure")
            self._c_flips = registry.counter("serving_regime_flips")
            self._g_regime.set(0.0)

    def observe(self, queue_depth: int, width: int) -> str:
        """Feed one load sample; returns the lever for the next step."""
        pressure = int(queue_depth) + int(width)
        with self._lock:
            cfg = self.cfg
            if self.lever == LATENCY:
                wants_flip = pressure >= cfg.wide_width
            else:
                wants_flip = pressure <= cfg.thin_width
            if wants_flip:
                self._streak += 1
                if self._streak >= cfg.dwell_steps:
                    self.lever = (
                        THROUGHPUT if self.lever == LATENCY else LATENCY
                    )
                    self.flips += 1
                    self._streak = 0
                    if self._c_flips is not None:
                        self._c_flips.inc()
            else:
                self._streak = 0
            lever = self.lever
        if self._g_pressure is not None:
            self._g_pressure.set(float(pressure))
        if self._g_regime is not None:
            self._g_regime.set(1.0 if lever == THROUGHPUT else 0.0)
        return lever

    def snapshot(self) -> dict:
        with self._lock:
            return {"lever": self.lever, "flips": self.flips,
                    "streak": self._streak,
                    "thin_width": self.cfg.thin_width,
                    "wide_width": self.cfg.wide_width,
                    "dwell_steps": self.cfg.dwell_steps}
