# dmlcheck-virtual-path: distributed_machine_learning_tpu/train/fixture.py
"""DML005 firing case: bare except + swallowed CheckpointVerifyError."""


def restore_or_garbage(path, restore, CheckpointVerifyError):
    try:
        return restore(path)
    except CheckpointVerifyError:
        pass                       # detected corruption, waved through
    try:
        return restore(path + ".bak")
    except:                        # noqa: E722 — deliberate fixture
        return None
