"""Launch an elastic serving fleet over the gang control plane
(ISSUE 16).

Fleet mode (the default) builds the router and a pool of replica
workers over the chosen ``--gang-transport``, promotes ``--replicas``
of them live (the rest stay warm spares), fires ``--requests``
synthetic prompts at the admission queue, waits for the fleet to
drain, and prints the latency quantiles, the exactly-once audit, and
the resilience summary.  Exit status is the audit verdict: 0 only when
every admitted request completed exactly once.

    python -m distributed_machine_learning_tpu.cli.serve \
        --replicas 4 --spares 2 --requests 200 \
        --gang-transport inproc

    # same fleet coordinating through a directory / a tcp gang server:
    python -m distributed_machine_learning_tpu.cli.serve \
        --replicas 2 --spares 1 --requests 50 \
        --gang-transport file --gang-dir /tmp/serve
    python -m distributed_machine_learning_tpu.cli.serve \
        --replicas 4 --spares 2 --requests 200 --gang-transport tcp

Worker mode joins an EXISTING tcp fleet from another process — the
subprocess-replica shape the slow chaos campaign uses:

    python -m distributed_machine_learning_tpu.cli.serve \
        --role worker --rank 3 --address 127.0.0.1:4242 \
        [--tx-chaos partition@40]

``--drain-after N`` demos the graceful-drain protocol mid-load:
after N completions, replica 0 is drained, finishes its in-flight
requests, and demotes to spare with zero drops.

The decode step is synthetic by default (echo + checksum token, with
``--service-time`` of simulated work) so the fleet story is testable
without a model; ``inference/generate.py::make_serving_step`` is the
production step-callable this slot takes.  ``--engine`` (ISSUE 19)
swaps every replica onto the continuous-batching engine
(``inference/continuous.py``: paged KV cache, iteration-level
scheduling) over a tiny real model, and hands the router the
regime-aware scheduler whose lever/flips are reported at exit.

Observability (ISSUE 17): ``--telemetry-dir`` gives every serving
process its own instance-tagged stream (``registry.router.json`` with
the per-stage latency histograms, ``trace.router.json`` +
``trace.replica<r>.json`` request spans that ``tools/trace_merge.py``
fuses into one Perfetto timeline), and repeatable ``--slo`` objectives
(``p99<=250ms``, ``reject_ratio<=5%``) run a live burn-rate engine
whose end-of-run verdict fails the exit status:

    python -m distributed_machine_learning_tpu.cli.serve \
        --replicas 2 --spares 1 --requests 100 \
        --gang-dir /tmp/serve --telemetry-dir /tmp/serve/telemetry \
        --slo 'p99<=250ms' --slo 'reject_ratio<=0.05'

``tools/serve_status.py /tmp/serve`` then renders the per-stage
quantiles, per-replica compute skew, and SLO burn state — and
``--postmortem RID`` one request's full stage-event timeline.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time


def synthetic_step(service_time_s: float = 0.0):
    """A model-free decode step: echoes each prompt plus one checksum
    token, sleeping ``service_time_s`` per micro-batch to simulate
    decode work."""

    def step(prompts):
        if service_time_s > 0:
            time.sleep(service_time_s)
        return [list(p) + [(sum(p) + len(p)) % 97] for p in prompts]

    return step


def _make_engine(micro_batch: int):
    """A continuous-batching engine (ISSUE 19) over a tiny real model
    — one per worker, since an engine is owned by a single thread.
    Warmed before the worker starts heartbeating: XLA compilation
    inside the first live ``step()`` would starve the beat channel
    long enough to look like a dead replica."""
    from distributed_machine_learning_tpu.inference.continuous import (
        ContinuousEngine,
        EngineConfig,
    )
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
    )

    model = TransformerLM(vocab_size=32, d_model=16, n_layers=2,
                          n_heads=4, n_kv_heads=2)
    engine = ContinuousEngine(
        model, init_lm_state(model).params,
        EngineConfig(max_lanes=micro_batch, block_size=4,
                     num_blocks=32, max_len=16, max_new=8),
    )
    engine.warmup(prompt_lens=(1, 2, 3))
    return engine


def _parse_tx_chaos(spec: str):
    from distributed_machine_learning_tpu.runtime.faults import (
        TransportChaos,
    )

    kind, _, arg = spec.partition("@")
    if kind == "partition" and arg.isdigit():
        return TransportChaos(partition_after=int(arg))
    raise ValueError(
        f"bad --tx-chaos {spec!r} (expected partition@AFTER_OPS)")


def _instance_telemetry(args, instance: str):
    """One instance-tagged Telemetry over ``--telemetry-dir`` (or None
    when the flag is unset).  ``enabled=True`` bypasses the rank-0
    gate: every serving process owns its own stream — the collision
    safety comes from the instance tag, not from writing nothing."""
    if not args.telemetry_dir:
        return None
    from distributed_machine_learning_tpu.telemetry import Telemetry

    return Telemetry(args.telemetry_dir, instance=instance,
                     enabled=True)


def _run_worker(args) -> int:
    from distributed_machine_learning_tpu.runtime.serving_worker import (
        ServingWorkerConfig,
        run_serving_worker,
    )
    from distributed_machine_learning_tpu.runtime.transport import (
        make_transport,
    )

    chaos = _parse_tx_chaos(args.tx_chaos) if args.tx_chaos else None
    tx = make_transport("tcp", address=args.address, chaos=chaos)
    stop = threading.Event()
    tel = _instance_telemetry(args, f"replica{args.rank}")
    engine = _make_engine(args.micro_batch) if args.engine else None
    try:
        summary = run_serving_worker(
            tx, args.rank, synthetic_step(args.service_time), stop,
            ServingWorkerConfig(micro_batch=args.micro_batch),
            telemetry=tel, engine=engine)
    finally:
        if tel is not None:
            tel.close()
    print(f"worker rank {args.rank}: {summary}")
    return 0


def _run_fleet(args) -> int:
    from distributed_machine_learning_tpu.runtime.faults import FaultEvents
    from distributed_machine_learning_tpu.runtime.serving import (
        Overloaded,
        ServingConfig,
        ServingRouter,
    )
    from distributed_machine_learning_tpu.runtime.serving_worker import (
        ServingWorkerConfig,
        start_worker_thread,
    )
    from distributed_machine_learning_tpu.runtime.transport import (
        FileTransport,
        InProcHub,
        InProcTransport,
        TcpGangServer,
        TcpTransport,
    )
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    world = args.replicas + args.spares
    server = None
    if args.gang_transport == "inproc":
        hub = InProcHub(mirror_dir=args.gang_dir)
        make_tx = lambda: InProcTransport(hub)  # noqa: E731
    elif args.gang_transport == "file":
        if not args.gang_dir:
            print("--gang-transport file requires --gang-dir",
                  file=sys.stderr)
            return 2
        make_tx = lambda: FileTransport(args.gang_dir)  # noqa: E731
    else:  # tcp: host the gang server in-process, clients on the wire
        server = TcpGangServer(mirror_dir=args.gang_dir).start()
        address = server.address
        make_tx = lambda: TcpTransport(address,  # noqa: E731
                                       backoff_s=0.01)

    slo = None
    if args.slo:
        from distributed_machine_learning_tpu.telemetry.slo import (
            SLOEngine,
        )

        slo = SLOEngine(args.slo,
                        short_window_s=args.slo_short_window,
                        long_window_s=args.slo_long_window,
                        burn_threshold=args.slo_burn_threshold)
    router_tel = _instance_telemetry(args, "router")
    worker_tels = [_instance_telemetry(args, f"replica{rank}")
                   for rank in range(world)]

    scheduler = None
    if args.engine:
        from distributed_machine_learning_tpu.runtime.scheduler import (
            RegimeScheduler,
        )

        scheduler = RegimeScheduler()
    events = FaultEvents()
    router = ServingRouter(
        make_tx(),
        ServingConfig(replicas=args.replicas,
                      max_queue=args.max_queue,
                      micro_batch=args.micro_batch,
                      replica_timeout_s=args.replica_timeout),
        events=events, telemetry=router_tel, slo=slo,
        scheduler=scheduler)
    stop = threading.Event()
    wcfg = ServingWorkerConfig(micro_batch=args.micro_batch)
    workers = [start_worker_thread(
        make_tx(), rank, synthetic_step(args.service_time), stop, wcfg,
        telemetry=worker_tels[rank],
        engine=_make_engine(args.micro_batch) if args.engine else None)
        for rank in range(world)]
    router_thread = threading.Thread(target=router.run, args=(stop,),
                                     name="serve-router", daemon=True)
    router_thread.start()

    rng_state = 12345
    drained = args.drain_after <= 0
    try:
        for i in range(args.requests):
            rng_state = (1103515245 * rng_state + 12345) % (1 << 31)
            prompt = [1 + (rng_state >> s) % 13 for s in (3, 7, 11)][
                :1 + rng_state % 3]
            while True:
                try:
                    router.submit(prompt)
                    break
                except Overloaded:
                    time.sleep(0.005)  # explicit back-pressure: retry
            if not drained and router.completed >= args.drain_after:
                drained = True
                router.drain(0)
        if not drained:
            # Submission outpaced completion: wait for the threshold so
            # the drain demo still happens mid-completion.
            deadline = time.monotonic() + args.timeout
            while (router.completed < args.drain_after
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            drained = True
            router.drain(0)
        ok = router.wait_idle(args.timeout)
    finally:
        verdict = router.close()
        stop.set()
        for t, _ in workers:
            t.join(timeout=5)
        router_thread.join(timeout=5)
        for tel in (router_tel, *worker_tels):
            if tel is not None:
                tel.close()
        if server is not None:
            server.stop()

    lat = verdict["latency"]
    print(f"fleet: {args.replicas} replicas + {args.spares} spares "
          f"over {args.gang_transport}")
    print(f"requests: {verdict['completed']}/{verdict['admitted']} "
          f"completed, {verdict['rejected']} rejected at admission, "
          f"{verdict['duplicates_discarded']} duplicates discarded")
    print(f"fleet events: {verdict['promotions']} promotions, "
          f"{verdict['evictions']} evictions, "
          f"{verdict['drains']} drains")
    if lat.get("p50") is not None:
        print(f"latency: p50 {lat['p50'] * 1e3:.1f} ms  "
              f"p95 {lat['p95'] * 1e3:.1f} ms  "
              f"p99 {lat['p99'] * 1e3:.1f} ms")
    if scheduler is not None:
        print(f"regime: {scheduler.lever} after "
              f"{scheduler.flips} flip(s)")
    print(resilience_summary(events))
    rc = 0
    if slo is not None:
        from distributed_machine_learning_tpu.telemetry.slo import (
            format_verdict,
        )

        slo_verdict = slo.verdict()
        print(format_verdict(slo_verdict))
        if not slo_verdict["ok"]:
            print("FAILED: SLO objectives violated", file=sys.stderr)
            rc = 1
    if not ok or not verdict["exactly_once"]:
        print("FAILED: not every admitted request completed exactly "
              "once", file=sys.stderr)
        return 1
    print("exactly-once audit: PASS")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--role", choices=("fleet", "worker"),
                    default="fleet",
                    help="fleet: router + worker pool in this process; "
                         "worker: join an existing tcp fleet")
    ap.add_argument("--replicas", type=int, default=4,
                    help="target live replicas (fleet mode)")
    ap.add_argument("--spares", type=int, default=1,
                    help="warm spares kept ready for promotion")
    ap.add_argument("--requests", type=int, default=100,
                    help="synthetic requests to fire (fleet mode)")
    ap.add_argument("--max-queue", dest="max_queue", type=int,
                    default=64,
                    help="admission bound: open requests past this "
                         "raise Overloaded")
    ap.add_argument("--micro-batch", dest="micro_batch", type=int,
                    default=4, help="requests per dispatch")
    ap.add_argument("--engine", action="store_true",
                    help="replicas run the continuous-batching engine "
                         "(paged KV cache, per-sequence retirement, "
                         "ISSUE 19) over a tiny real model instead of "
                         "the synthetic batch step; the router gets "
                         "the regime-aware scheduler")
    ap.add_argument("--service-time", dest="service_time", type=float,
                    default=0.0,
                    help="simulated decode seconds per micro-batch")
    ap.add_argument("--replica-timeout", dest="replica_timeout",
                    type=float, default=2.0,
                    help="beat staleness that evicts a replica")
    ap.add_argument("--drain-after", dest="drain_after", type=int,
                    default=0,
                    help="gracefully drain replica 0 after this many "
                         "completions (0: never)")
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="fleet-idle deadline before declaring failure")
    ap.add_argument("--gang-transport", dest="gang_transport",
                    choices=("file", "inproc", "tcp"),
                    default="inproc", help="control-plane backend")
    ap.add_argument("--gang-dir", dest="gang_dir", default=None,
                    help="file backend directory / inproc+tcp ledger "
                         "mirror for post-mortem gang_status")
    ap.add_argument("--telemetry-dir", dest="telemetry_dir",
                    default=None,
                    help="per-instance telemetry artifacts (router + "
                         "one stream per replica): stage histograms "
                         "in registry.router.json, request spans in "
                         "trace.<instance>.json for trace_merge")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="SPEC",
                    help="declare an objective, e.g. p99<=250ms or "
                         "reject_ratio<=0.05 (repeatable); the run "
                         "fails when one is violated or its burn-rate "
                         "alert fires")
    ap.add_argument("--slo-short-window", dest="slo_short_window",
                    type=float, default=5.0,
                    help="burn-rate short window, seconds")
    ap.add_argument("--slo-long-window", dest="slo_long_window",
                    type=float, default=60.0,
                    help="burn-rate long window, seconds")
    ap.add_argument("--slo-burn-threshold", dest="slo_burn_threshold",
                    type=float, default=2.0,
                    help="alert when BOTH windows burn error budget "
                         "above this multiple of the sustainable rate")
    ap.add_argument("--address", default=None,
                    help="worker mode: host:port of the fleet's gang "
                         "server")
    ap.add_argument("--rank", type=int, default=0,
                    help="worker mode: this replica's rank")
    ap.add_argument("--tx-chaos", dest="tx_chaos", default=None,
                    help="worker mode: 'partition@AFTER_OPS' severs "
                         "this worker's channel after that many "
                         "transport ops")
    args = ap.parse_args(argv)

    if args.role == "worker":
        if not args.address:
            ap.error("--role worker requires --address")
        return _run_worker(args)
    if args.replicas < 1:
        ap.error(f"--replicas must be >= 1, got {args.replicas}")
    if args.spares < 0:
        ap.error(f"--spares must be >= 0, got {args.spares}")
    if args.tx_chaos:
        ap.error("--tx-chaos is a worker-mode flag (the fleet's own "
                 "channels must stay healthy)")
    return _run_fleet(args)


if __name__ == "__main__":
    sys.exit(main())
