"""Expert parallelism for the MoE transformer — GSPMD sharding rules.

Same design as ``parallel/tensor_parallel.py``: declare where params live,
jit the unmodified step with those shardings, and let XLA's partitioner
derive the comm.  Expert-owned params (leading ``[n_experts, ...]`` axis:
``w_in``/``b_in``/``w_out``/``b_out`` of every ``MoEMLP``) shard that axis
over the mesh's ``expert`` axis; the dispatch/combine einsums in
``models/moe.py`` then lower to the token all-to-all over ICI.  Everything
else (attention, norms, router, embeddings) stays replicated; the batch
shards over ``data_axis``, giving EP×DP on one mesh.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.moe import (
    SEQ_LOCAL_ATTN_IMPLS,
    SEQ_SHARDED_ATTN_IMPLS,
    MoETransformerLM,
)
from distributed_machine_learning_tpu.parallel.gspmd import (
    make_cached_sharded_step,
    shard_state,
    state_shardings,
)
from distributed_machine_learning_tpu.train.losses import lm_cross_entropy
from distributed_machine_learning_tpu.train.optimizers import update_fn_for_config
from distributed_machine_learning_tpu.train.state import TrainState

EXPERT_AXIS = "expert"
_EXPERT_PARAMS = {"w_in", "b_in", "w_out", "b_out"}


def ep_spec_for(path: tuple[str, ...], ndim: int, expert_axis: str = EXPERT_AXIS) -> P:
    """Expert-owned leaves shard their leading axis; the rest replicate."""
    if path and path[-1] in _EXPERT_PARAMS and "moe" in path:
        return P(expert_axis, *(None,) * (ndim - 1))
    return P(*(None,) * ndim)


def _spec_for(expert_axis: str):
    # gspmd.SpecFor passes the leaf shape; the EP rule only needs rank.
    return lambda path, shape: ep_spec_for(path, len(shape), expert_axis)


def ep_state_shardings(state: TrainState, mesh: Mesh, expert_axis: str = EXPERT_AXIS):
    return state_shardings(state, mesh, _spec_for(expert_axis))


def shard_ep_state(
    state: TrainState, mesh: Mesh, expert_axis: str = EXPERT_AXIS
) -> TrainState:
    return shard_state(state, mesh, _spec_for(expert_axis))


def _moe_step_impl(model: MoETransformerLM, state: TrainState, tokens, targets):
    def loss_fn(params):
        logits, mutated = model.apply(
            {"params": params}, tokens, train=True, mutable=["losses"]
        )
        ce = lm_cross_entropy(logits, targets)
        aux_leaves = jax.tree_util.tree_leaves(mutated.get("losses", {}))
        aux = sum(jax.numpy.sum(a) for a in aux_leaves) if aux_leaves else 0.0
        return ce + model.aux_loss_weight * aux, ce

    (loss, ce), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
    new_params, new_momentum = update_fn_for_config(state.config)(
        state.params, state.momentum, grads, state.config, step=state.step
    )
    new_state = state.replace(
        params=new_params, momentum=new_momentum, step=state.step + 1
    )
    return new_state, ce


def init_moe_state(model: MoETransformerLM, seed: int = 69143,
                   config=None) -> TrainState:
    """``config``: optional optimizer config (as in ``init_lm_state``);
    the EP step dispatches its update from the state's config type."""
    from distributed_machine_learning_tpu.train.lm_step import init_lm_state

    return init_lm_state(model, seed=seed, config=config)


def _is_expert_path(path: tuple[str, ...]) -> bool:
    return bool(path) and path[-1] in _EXPERT_PARAMS and "moe" in path


def state_pspecs(state: TrainState, mesh: Mesh, spec_for):
    """PartitionSpec pytree for a TrainState (shard_map in/out specs),
    derived from ``gspmd.state_shardings`` so the manual steps and the
    GSPMD steps can never disagree about the state layout."""
    from distributed_machine_learning_tpu.parallel.gspmd import (
        state_shardings,
    )

    return jax.tree_util.tree_map(
        lambda s: s.spec, state_shardings(state, mesh, spec_for)
    )


def make_ep_grouped_train_step(
    model: MoETransformerLM,
    mesh: Mesh,
    data_axis: str = "batch",
    expert_axis: str = EXPERT_AXIS,
    seq_axis: str | None = None,
    slots_per_owner: int | None = None,
):
    """Dropless grouped MoE under REAL expert parallelism — the manual
    shard_map twin of :func:`make_ep_train_step`.

    Differences from the GSPMD einsum step:

    - the batch shards over ``data_axis`` **and** ``expert_axis``
      jointly (the einsum step replicates activations over the expert
      axis, duplicating attention compute ep-fold; here every device
      computes attention on its own batch shard);
    - expert compute is ``ops/grouped.py::grouped_expert_mlp_ep``: an
      explicit ``lax.all_to_all`` of token rows to their expert's owner
      device, ``lax.ragged_dot`` over the received groups, and the
      inverse all_to_all home — **dropless** (send slots bound at
      N_local per owner, which cannot overflow), vs the einsum path's
      per-expert capacity + overflow drops;
    - gradient sync is per-leaf: every grad psums over ``data_axis``;
      non-expert leaves additionally psum over ``expert_axis`` (expert
      leaves are sharded there — averaging them would mix different
      experts' gradients).

    The state uses the SAME placement as the einsum step
    (``shard_ep_state``), so checkpoints/eval tooling carry over;
    inside the shard_map the model is cloned with
    ``expert_axis``/``token_axes`` so expert params are declared at
    their local shard shape and the aux loss uses global routing stats.

    Update-equivalence to einsum-EP at non-dropping capacity is
    property-tested (``tests/test_moe.py``).

    ``seq_axis``: MoE × context parallelism.  When set, the sequence
    shards over it too (batch over data×expert, sequence over seq — a
    3-D token layout), attention runs the sequence-sharded ring
    (``attn_impl="ring"``/``"ring_flash"``/``"ulysses"``), and the MoE
    dispatch composes unchanged: the router is per-token, so each
    device all_to_alls its (batch- AND sequence-)local rows to expert
    owners along the expert axis exactly as in the 2-D case.  This
    lifts round 3's MoE × sequence-parallel exclusion
    (``models/moe.py`` guard; VERDICT r03 item 3).

    ``slots_per_owner`` (ADVICE r4): bound the dispatch all-to-all at
    this many send slots per owner device instead of the dropless
    N_local default — wire bytes and ragged padding shrink ~ep-fold on
    a balanced router, at Switch-style per-owner overflow drops
    (``ops/grouped.py::grouped_expert_mlp_ep``).
    """
    from jax import lax

    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    if model.moe_impl != "grouped":
        raise ValueError(
            "make_ep_grouped_train_step requires moe_impl='grouped' "
            f"(got {model.moe_impl!r}); use make_ep_train_step for the "
            "einsum path"
        )
    seq_sharded_impls = SEQ_SHARDED_ATTN_IMPLS
    if seq_axis is None:
        if model.attn_impl not in SEQ_LOCAL_ATTN_IMPLS:
            raise ValueError(
                "sequence-sharded attention "
                f"({model.attn_impl!r}) requires seq_axis= (the MoE x "
                "context-parallel layout)"
            )
        mesh_axes = (data_axis, expert_axis)
    else:
        mesh_axes = (data_axis, expert_axis, seq_axis)
        if (
            model.attn_impl not in seq_sharded_impls
            and mesh.shape.get(seq_axis, 1) > 1
        ):
            # A sequence-local kernel would silently attend within local
            # chunks at offset-0 positions (same hazard lm_step guards).
            raise ValueError(
                f"attn_impl={model.attn_impl!r} cannot shard the "
                f"sequence: axis {seq_axis!r} has size "
                f"{mesh.shape.get(seq_axis)}; use ring/ring_flash/"
                "ulysses or a seq-axis size of 1"
            )
        if (
            model.attn_impl == "ulysses"
            and model.n_heads % mesh.shape.get(seq_axis, 1)
        ):
            raise ValueError(
                f"Ulysses needs n_heads divisible by the seq-axis size: "
                f"{model.n_heads} heads over {mesh.shape.get(seq_axis)}"
            )
    for a in mesh_axes:
        if a not in mesh.axis_names:
            raise ValueError(f"mesh is missing axis {a!r}: {mesh.axis_names}")
    ep = mesh.shape[expert_axis]
    if model.n_experts % ep:
        raise ValueError(
            f"n_experts={model.n_experts} must be divisible by the "
            f"expert-axis size {ep}"
        )
    axis_names = mesh_axes
    # Inside the manual region: local expert shards + global aux stats.
    local_model = model.clone(expert_axis=expert_axis, token_axes=axis_names,
                              ep_slots_per_owner=slots_per_owner)

    import numpy as _np

    n_total = int(_np.prod([mesh.shape[a] for a in axis_names]))

    def impl(state: TrainState, tokens, targets):
        def loss_fn(params):
            logits, mutated = local_model.apply(
                {"params": params}, tokens, train=True, mutable=["losses"]
            )
            ce = lm_cross_entropy(logits, targets)  # LOCAL token mean
            aux_leaves = jax.tree_util.tree_leaves(mutated.get("losses", {}))
            # Sown aux is computed from pmean'd global routing stats —
            # identical on every device; add it once.
            aux = sum(jax.numpy.sum(a) for a in aux_leaves) if aux_leaves else 0.0
            return ce + model.aux_loss_weight * aux, ce

        (loss, ce), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        # Every device seeds its local loss with cotangent 1, and the
        # in-trace collective transposes (all_to_all, the aux pmeans)
        # cross-route cotangents — so the per-device grads assemble to
        # ∂(Σ_d loss_d)/∂θ under a psum.  The true loss is the device
        # MEAN (1/n)Σ_d loss_d = global-mean ce + w·aux, hence the /n.
        # Expert leaves psum over the data axis only: they are sharded
        # over the expert axis, where a reduction would mix different
        # experts' gradients (the expert-axis cross terms already
        # arrived through the all_to_all transpose).
        non_expert_axes = tuple(a for a in axis_names if a != expert_axis)

        def sync(path, g):
            keys = tuple(k.key if hasattr(k, "key") else str(k) for k in path)
            axes = non_expert_axes if _is_expert_path(keys) else axis_names
            return lax.psum(g, axes) / n_total

        grads = jax.tree_util.tree_map_with_path(sync, grads)
        ce = lax.pmean(ce, axis_names)
        new_params, new_momentum = update_fn_for_config(state.config)(
            state.params, state.momentum, grads, state.config, step=state.step
        )
        new_state = state.replace(
            params=new_params, momentum=new_momentum, step=state.step + 1
        )
        return new_state, ce

    def build(state):
        sspecs = state_pspecs(state, mesh, _spec_for(expert_axis))
        batch_spec = P((data_axis, expert_axis), seq_axis)
        return jax.jit(
            shard_map_no_check(
                impl,
                mesh=mesh,
                in_specs=(sspecs, batch_spec, batch_spec),
                out_specs=(sspecs, P()),
            ),
            donate_argnums=(0,),
        )

    jitted: dict = {}

    def step(state: TrainState, tokens, targets):
        key = jax.tree_util.tree_structure(state)
        fn = jitted.get(key)
        if fn is None:
            fn = jitted[key] = build(state)
        return fn(state, tokens, targets)

    return step


def make_ep_train_step(
    model: MoETransformerLM,
    mesh: Mesh | None = None,
    data_axis: str = "batch",
    expert_axis: str = EXPERT_AXIS,
):
    """Build the EP(+DP) MoE train step: ``step(state, tokens, targets) →
    (state, ce_loss)``.  Without a mesh: plain jit (the single-device
    reference).  With a mesh: state placed via ``shard_ep_state``,
    tokens/targets sharded over ``data_axis`` (``shard_tp_batch`` works)."""
    if model.attn_impl not in SEQ_LOCAL_ATTN_IMPLS:
        raise ValueError(
            "expert-parallel step requires a sequence-LOCAL attention "
            "(dense/flash/auto): the sequence is not sharded here, so the "
            "ring/ulysses impls have no axis to run over"
        )
    if mesh is None:
        return jax.jit(partial(_moe_step_impl, model), donate_argnums=(0,))
    if model.moe_impl != "einsum":
        # ragged_dot has no GSPMD partitioning rule that would recover the
        # token all-to-all from an expert-sharded leading axis; only the
        # one-hot einsum form shards over the expert axis.  The grouped
        # path stays single-device / shard_map-DP (ops/grouped.py).
        raise ValueError(
            "the expert-sharded GSPMD step requires moe_impl='einsum' "
            f"(got {model.moe_impl!r}): the dispatch/combine einsums are "
            "what XLA partitions into the all-to-all; the grouped "
            "ragged_dot path does not shard over the expert axis"
        )
    if model.attn_impl in ("flash", "auto") and model.flash_mesh is None:
        # A bare Pallas (Mosaic) custom call inside this GSPMD-
        # partitioned jit has no sharding rules, so flash runs through
        # the model's fully-manual shard_map wrap (batch dim sharded)
        # instead (models/transformer.py::Attention.flash_mesh): the
        # kernel sees local per-device shapes and never meets the
        # partitioner — valid on CPU interpret AND real TPU meshes.
        model = model.clone(flash_mesh=mesh, flash_batch_axis=data_axis)
    impl = partial(_moe_step_impl, model)
    for a in (data_axis, expert_axis):
        if a not in mesh.axis_names:
            raise ValueError(f"mesh is missing axis {a!r}: {mesh.axis_names}")
    if model.n_experts % mesh.shape[expert_axis]:
        raise ValueError(
            f"n_experts={model.n_experts} must be divisible by the "
            f"expert-axis size {mesh.shape[expert_axis]}"
        )
    batch_sharding = NamedSharding(mesh, P(data_axis, None))
    return make_cached_sharded_step(impl, mesh, _spec_for(expert_axis), batch_sharding)
