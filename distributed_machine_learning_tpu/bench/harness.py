"""Shared scan-epoch timing harness for the benchmark entrypoints.

One copy of the measurement protocol (bench.py and bench/sweep.py both
use it): all timed iterations run as ONE jitted ``lax.scan`` over
pre-staged device-resident batches, and timing brackets a HOST VALUE
FETCH of the final loss.  Rationale — per-step Python dispatch would
dominate on a remote/tunneled device (~100 ms round-trip vs a ~4 ms
step), and an asynchronously-dispatched backend can return from
``block_until_ready`` before compute actually finishes, so only a value
fetch is trustworthy; the reference's excluded iteration 0
(``part1/main.py:53-58``) maps to the excluded compile run.
"""

from __future__ import annotations

import time

import jax
import numpy as np


def timed_scan_epoch(step, state, imgs, lbls, reps: int = 1):
    """Time ``len(imgs)`` train steps as one compiled scan.

    ``step``: un-jitted ``(state, x, y) -> (state, loss)`` (build with
    ``make_train_step(..., jit=False)``).  ``imgs``/``lbls``: stacked
    [T, ...] device arrays, one leading slice per iteration.  Runs once
    untimed (compile, the reference's iteration 0), then ``reps`` timed
    runs; returns ``(best_seconds, final_loss, state)``.

    Raises ``RuntimeError`` on a non-finite final loss — a benchmark
    number from a diverged run must never be reported.
    """

    @jax.jit
    def run(state, imgs, lbls):
        def body(st, xy):
            st, loss = step(st, *xy)
            return st, loss

        return jax.lax.scan(body, state, (imgs, lbls))

    state, losses = run(state, imgs, lbls)
    float(losses[-1])  # compile + completion

    best = float("inf")
    final_loss = float("nan")
    for _ in range(max(reps, 1)):
        start = time.perf_counter()
        state, losses = run(state, imgs, lbls)
        final_loss = float(losses[-1])  # forces real device completion
        best = min(best, time.perf_counter() - start)
    if not np.isfinite(final_loss):
        raise RuntimeError(
            f"benchmark run diverged (final loss {final_loss}); refusing to "
            "report a throughput number"
        )
    return best, final_loss, state
