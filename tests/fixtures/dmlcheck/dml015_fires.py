# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/serving_worker.py
"""DML015 firing cases: serving observability state opened without a
guaranteed close — a bare span object whose __exit__ any exception can
skip, and a worker-loop body that stamps an open stage (bound/computed)
with no terminal stamp (posted/completed/requeued/fenced/dropped)
anywhere in the same function."""
from distributed_machine_learning_tpu.runtime.transport import stamp_stage


def leaky_span(tracer, rid):
    span = tracer.span("request", rid=rid)   # never used as a `with`
    do_work(rid)
    span.__exit__(None, None, None)          # skipped on any exception


def bare_span_call(tel):
    tel.span("request", rid="r1")            # span object dropped


def half_journey(reqs, step_fn, rank):
    by = f"replica{rank}"
    for req in reqs:
        stamp_stage(req, "bound", by)
    outs = step_fn([r["prompt"] for r in reqs])
    for req in reqs:
        stamp_stage(req, "computed", by)
    return outs                              # no terminal stamp at all


def do_work(rid):
    return rid
