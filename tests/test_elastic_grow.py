"""Elastic GROW (ISSUE 10): rejoin-on-recovery, warm spares, backup-
worker straggler replacement, and the supervision plumbing behind them.

Fast half (stub processes, no jax in the workers): the coordinator's
join/announcement channel, the ``recover_rank`` fault kind and its
gang-wide ledger latch, ``checkpoint_extra`` round-trips, the
``_seed_checkpoint`` admission copy, ``gang_supervise`` grow/spare
validation, and stub-process supervision proofs — grow-on-announced-
join at a planned boundary, spare promotion filling the grown world,
failure shrinks NOT silently backfilled by spares, and readmission
after a shrink (the 3→2→3 trajectory with the lose_rank marker cleared
by recover_rank).

Slow half (``slow`` + ``faultinject``): the ROADMAP's named chaos
proofs — a 4-worker gang goes 4→3→5 in one supervised run (lose a
rank, recover it, promote a spare) with exactly-once consumption
across both transitions and a final checkpoint restoring onto worlds
1/3/4/5; the linear scaling rule keeps the loss curve continuous
across the world changes while the pinned control shifts the floor
(the rule is load-bearing); and ``--straggler-policy=replace`` turns a
``stall_rank`` fault into a demotion + spare promotion the status tool
can narrate.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_machine_learning_tpu.runtime.coordinator import (
    announce_join,
    clear_gang_state,
    consume_join,
    read_joins,
)
from distributed_machine_learning_tpu.runtime.faults import (
    FAULT_LEDGER_FILE,
    FaultEvents,
    FaultInjector,
    corrupt_checkpoint_data,
    ledger_entries,
    ledger_recovered_ranks,
    ledger_unrecovered_lost_ranks,
)
from distributed_machine_learning_tpu.runtime.supervisor import (
    _seed_checkpoint,
    gang_supervise,
)
from distributed_machine_learning_tpu.telemetry.aggregator import (
    read_health_events,
)
from distributed_machine_learning_tpu.train.checkpoint import (
    checkpoint_config,
    checkpoint_extra,
    latest_checkpoint,
    quarantine_checkpoint,
    reshard_restore,
    save_checkpoint,
    validate_checkpoint,
)
from distributed_machine_learning_tpu.train.state import TrainState

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


# ---------------------------------------------------------------------------
# Coordinator join/announcement channel
# ---------------------------------------------------------------------------


def test_join_channel_roundtrip(tmp_path):
    announce_join(tmp_path, 2, kind="recover", at_step=5)
    announce_join(tmp_path, 4, spare=True, prefetched_step=10)
    joins = read_joins(tmp_path)
    assert set(joins) == {2, 4}
    assert joins[2]["spare"] is False and joins[2]["at_step"] == 5
    assert joins[4]["spare"] is True and joins[4]["prefetched_step"] == 10
    # Re-announcing is an idempotent atomic overwrite (the spare's
    # heartbeat refreshes its prefetch progress this way).
    announce_join(tmp_path, 4, spare=True, prefetched_step=12)
    assert read_joins(tmp_path)[4]["prefetched_step"] == 12
    consume_join(tmp_path, 2)
    assert set(read_joins(tmp_path)) == {4}
    consume_join(tmp_path, 2)  # consuming twice is a no-op
    with pytest.raises(ValueError):
        announce_join(tmp_path, -1)
    # A torn payload is skipped, not fatal — the next poll sees it whole.
    (tmp_path / "join_rank7.json").write_text("{not json")
    assert set(read_joins(tmp_path)) == {4}


def test_clear_gang_state_join_survival(tmp_path):
    """A pending join must survive the very boundary that will admit it
    (between-attempt and shrink clears), dying only at fresh-run init —
    the same rule as the fault ledger."""
    announce_join(tmp_path, 3)
    clear_gang_state(tmp_path)  # between same-size attempts
    assert 3 in read_joins(tmp_path)
    clear_gang_state(tmp_path, restore_records=True, fault_ledger=False)
    assert 3 in read_joins(tmp_path)  # a shrink boundary keeps it too
    clear_gang_state(tmp_path, restore_records=True)  # fresh run
    assert read_joins(tmp_path) == {}


def test_ledger_loss_recovery_masking_is_order_aware(tmp_path):
    """A recover_rank clears only EARLIER lose_rank entries: a rank
    that dies again after recovering is lost again.  Plain set
    subtraction would mask the second loss forever."""
    ledger = tmp_path / FAULT_LEDGER_FILE

    def append(entry):
        with open(ledger, "a") as f:
            f.write(json.dumps(entry) + "\n")

    append({"kind": "lose_rank", "rank": 1, "at": 3})
    assert ledger_unrecovered_lost_ranks(ledger) == {1}
    append({"kind": "recover_rank", "rank": 0, "target": 1, "at": 6})
    assert ledger_unrecovered_lost_ranks(ledger) == set()
    append({"kind": "lose_rank", "rank": 1, "at": 9})
    assert ledger_unrecovered_lost_ranks(ledger) == {1}
    # ... while the all-time sets stay order-blind (the budget-reset
    # marker keeps using them).
    assert ledger_recovered_ranks(ledger) == {1}


# ---------------------------------------------------------------------------
# recover_rank fault kind
# ---------------------------------------------------------------------------


def test_recover_rank_grammar():
    inj = FaultInjector.parse("recover_rank@1:5", rank=0)
    assert inj.pending() == ["recover_rank@1:5"]
    with pytest.raises(ValueError):
        FaultInjector.parse("recover_rank@5")  # missing target rank
    with pytest.raises(ValueError):
        FaultInjector.parse("recover_rank@1:5:2.0")  # too many fields


def test_recover_rank_acts_via_current_rank0(tmp_path):
    ledger = tmp_path / FAULT_LEDGER_FILE
    ev = FaultEvents()
    # A process NOT currently holding rank 0 latches without acting:
    # no ledger entry, no join announcement.
    inj = FaultInjector.parse("recover_rank@1:5", rank=3)
    inj.current_rank = 2
    inj.attach_ledger(ledger)
    assert list(inj.wrap_batches(range(8), ev)) == list(range(8))
    assert ev.rank_recoveries == 0
    assert read_joins(tmp_path) == {}
    assert ledger_recovered_ranks(ledger) == set()
    # The current rank 0 (here: original rank 2 after a renumbering)
    # acts on the dead host's behalf: ledger entry with the TARGET rank
    # distinct from the acting rank, plus the join announcement.
    inj = FaultInjector.parse("recover_rank@1:5", rank=2)
    inj.current_rank = 0
    inj.attach_ledger(ledger)
    list(inj.wrap_batches(range(8), ev))
    assert ev.rank_recoveries == 1
    joins = read_joins(tmp_path)
    assert joins[1]["spare"] is False and joins[1]["at_step"] == 5
    assert ledger_recovered_ranks(ledger) == {1}
    entry = ledger_entries(ledger)[-1]
    assert entry["kind"] == "recover_rank"
    assert entry["target"] == 1 and entry["rank"] == 2
    # The latch is GANG-WIDE: any fresh process re-attaching (including
    # a different future holder of rank 0) sees it fired and never
    # re-fires the recovery.
    inj2 = FaultInjector.parse("recover_rank@1:5", rank=0)
    inj2.current_rank = 0
    inj2.attach_ledger(ledger)
    assert inj2.pending() == []


# ---------------------------------------------------------------------------
# checkpoint_extra + _seed_checkpoint (the admission copy)
# ---------------------------------------------------------------------------


def test_checkpoint_extra_roundtrip(tmp_path):
    state = TrainState.create(params={"w": jnp.zeros((4,), jnp.float32)})
    p = save_checkpoint(tmp_path, state,
                        extra_payload={"example_cursor": 96, "world": 4})
    assert checkpoint_extra(p) == {"example_cursor": 96, "world": 4}
    # The extra payload rides the config file without polluting the
    # config read-back.
    checkpoint_config(p)
    p2 = save_checkpoint(tmp_path, state.replace(step=state.step + 1))
    assert checkpoint_extra(p2) == {}  # absent: empty, not an error
    quarantine_checkpoint(p, "gang election verdict")
    assert checkpoint_extra(p) == {}  # known-bad data is never served


def test_seed_checkpoint_copies_and_validates(tmp_path):
    state = TrainState.create(
        params={"w": jnp.arange(4, dtype=jnp.float32)}
    )
    src = tmp_path / "src"
    save_checkpoint(src, state)  # step_0
    dst = tmp_path / "dst"
    os.makedirs(dst)
    assert _seed_checkpoint(dst, 0, [str(src)]) is True
    assert validate_checkpoint(os.path.join(dst, "step_0")) == []
    # Already holding a valid copy: True without touching any source.
    assert _seed_checkpoint(dst, 0, [str(tmp_path / "nowhere")]) is True
    assert _seed_checkpoint(dst, None, [str(src)]) is False
    # A corrupt source is skipped (the COPY is validated, so a torn
    # copy can never masquerade as a checkpoint); a later valid source
    # still lands.
    src_bad = tmp_path / "src_bad"
    corrupt_checkpoint_data(save_checkpoint(src_bad, state))
    dst2 = tmp_path / "dst2"
    assert _seed_checkpoint(dst2, 0, [str(src_bad)]) is False
    assert _seed_checkpoint(dst2, 0, [str(src_bad), str(src)]) is True
    assert validate_checkpoint(os.path.join(dst2, "step_0")) == []


# ---------------------------------------------------------------------------
# gang_supervise validation
# ---------------------------------------------------------------------------


def test_gang_supervise_grow_validation(tmp_path):
    def cmd4(rank, attempt, world, orig):
        return ["true"]

    def cmd3(rank, attempt, world):
        return ["true"]

    def spare(orig, attempt):
        return ["true"]

    g = str(tmp_path / "g")
    with pytest.raises(ValueError):  # max_world below the launch world
        gang_supervise(cmd4, 4, g, max_world=3)
    with pytest.raises(ValueError):
        gang_supervise(cmd4, 2, g, spares=-1)
    with pytest.raises(ValueError):  # spares need a spare_cmd
        gang_supervise(cmd4, 2, g, spares=1)
    with pytest.raises(ValueError):
        gang_supervise(cmd4, 2, g, straggler_policy="evict")
    with pytest.raises(ValueError):  # replace needs a spare to promote
        gang_supervise(cmd4, 2, g, straggler_policy="replace")
    with pytest.raises(ValueError):
        gang_supervise(cmd4, 2, g, spares=1, spare_cmd=spare,
                       straggler_policy="replace", replace_after=0)
    with pytest.raises(ValueError):  # growing needs the 4-arg signature
        gang_supervise(cmd3, 2, g, max_world=3)
    with pytest.raises(ValueError):  # per-rank dirs must cover spares
        gang_supervise(cmd4, 2, g, spares=1, spare_cmd=spare,
                       ckpt_dirs=[str(tmp_path / "a"), str(tmp_path / "b")])


# ---------------------------------------------------------------------------
# Stub-process supervision: grow, spare promotion, no silent backfill
# ---------------------------------------------------------------------------


def _stub_worker_cmd(tmp_path, body: str):
    """Worker argv factory: the subprocess runs ``body`` with {rank}/
    {attempt}/{world}/{orig}/{root} substitutions — cheap processes, no
    jax import.  Same idiom as tests/test_elastic.py."""

    def worker_cmd(rank, attempt, world, orig_rank):
        code = body.format(rank=rank, attempt=attempt, world=world,
                           orig=orig_rank, root=str(tmp_path))
        return [sys.executable, "-c", code]

    return worker_cmd


def _spare_stub_cmd(tmp_path, prefetched_step=0):
    """Spare argv factory: announce on the join channel, then stand by
    until the drain terminates us."""

    def spare_cmd(orig, attempt):
        code = (
            "import json, os, time\n"
            f"orig = {orig}\n"
            f"gang = os.path.join({str(tmp_path)!r}, 'gang')\n"
            "os.makedirs(gang, exist_ok=True)\n"
            "tmp = os.path.join(gang, '.spare%d' % orig)\n"
            "with open(tmp, 'w') as f:\n"
            "    json.dump(dict(rank=orig, spare=True, time=time.time(),\n"
            f"                   prefetched_step={prefetched_step}), f)\n"
            "os.replace(tmp, os.path.join(gang, 'join_rank%d.json' % orig))\n"
            "time.sleep(60)\n"
        )
        return [sys.executable, "-c", code]

    return spare_cmd


# Attempt-0 workers: rank 0 announces a (non-spare) join for JOINRANK,
# then everyone waits on the abort latch and takes the coordinated
# abort exit (43); attempt >= 1 workers record themselves and finish.
_GROW_BODY = (
    "import json, os, sys, time\n"
    "rank, attempt, world, orig = {rank}, {attempt}, {world}, {orig}\n"
    "root = {root!r}\n"
    "gang = os.path.join(root, 'gang')\n"
    "with open(os.path.join(root, 'seen.jsonl'), 'a') as f:\n"
    "    f.write(json.dumps(dict(rank=rank, attempt=attempt,\n"
    "                            world=world, orig=orig)) + '\\n')\n"
    "if attempt == 0:\n"
    "    if rank == 0:\n"
    "        tmp = os.path.join(gang, '.join_tmp')\n"
    "        with open(tmp, 'w') as f:\n"
    "            json.dump(dict(rank=JOINRANK, spare=False,\n"
    "                           time=time.time()), f)\n"
    "        os.replace(tmp, os.path.join(gang, 'join_rankJOINRANK.json'))\n"
    "    deadline = time.time() + 20\n"
    "    while time.time() < deadline:\n"
    "        if os.path.exists(os.path.join(gang, 'abort.json')):\n"
    "            os._exit(43)\n"
    "        time.sleep(0.05)\n"
    "sys.exit(0)\n"
)


def _seen(tmp_path):
    return [json.loads(line) for line in
            (tmp_path / "seen.jsonl").read_text().splitlines()]


def test_gang_supervise_grows_on_announced_join(tmp_path):
    """A pending (non-spare) join triggers a PLANNED boundary: the
    supervisor latches the abort itself, admits the joiner, renumbers
    2→3, charges nobody's budget and consumes no max_restarts — with
    the grow visible in events and the health ledger."""
    gang = tmp_path / "gang"
    events = FaultEvents()
    codes = gang_supervise(
        _stub_worker_cmd(tmp_path, _GROW_BODY.replace("JOINRANK", "2")),
        2, gang, max_world=3, events=events, poll_s=0.05,
        max_restarts=1, grace_s=5.0,
    )
    assert codes == [0, 0, 0]
    assert events.gang_grows == 1
    assert events.gang_restarts == 0  # planned boundaries are free
    assert events.gang_shrinks == 0
    final = [s for s in _seen(tmp_path) if s["attempt"] == 1]
    assert sorted((s["rank"], s["orig"]) for s in final) == [
        (0, 0), (1, 1), (2, 2)]
    assert all(s["world"] == 3 for s in final)
    # The admission consumed the announcement: it can't drive a second
    # grow.
    assert read_joins(gang) == {}
    kinds = [e.get("kind") for e in read_health_events(gang)]
    assert "boundary" in kinds and "grow" in kinds


def test_gang_supervise_promotes_spare_to_fill_grown_world(tmp_path):
    """With room left after the announced join (max_world 4, 2 workers,
    1 joiner), the live announced spare is promoted to fill the world —
    counted as a spare_promotion and narrated in the health ledger."""
    gang = tmp_path / "gang"
    events = FaultEvents()
    codes = gang_supervise(
        _stub_worker_cmd(tmp_path, _GROW_BODY.replace("JOINRANK", "3")),
        2, gang, max_world=4, spares=1,
        spare_cmd=_spare_stub_cmd(tmp_path, prefetched_step=7),
        events=events, poll_s=0.05, max_restarts=1, grace_s=5.0,
    )
    assert codes == [0, 0, 0, 0]
    assert events.gang_grows == 1
    assert events.spare_promotions == 1
    assert events.spare_demotions == 0
    final = [s for s in _seen(tmp_path) if s["attempt"] == 1]
    # Joined rank 3 AND promoted spare (orig 2) fill the world of 4,
    # renumbered in original order.
    assert sorted((s["rank"], s["orig"]) for s in final) == [
        (0, 0), (1, 1), (2, 2), (3, 3)]
    assert all(s["world"] == 4 for s in final)
    health = read_health_events(gang)
    promo = [e for e in health if e.get("kind") == "promote"]
    assert len(promo) == 1 and promo[0]["rank"] == 2
    grow = [e for e in health if e.get("kind") == "grow"]
    assert grow and grow[0]["joined"] == [3] and grow[0]["promoted"] == [2]


# Attempt-0: rank 1 writes a lose_rank ledger entry and dies hard;
# later attempts just finish.  Used to prove failure shrinks never
# silently backfill from the spare pool.
_LOSE_BODY = (
    "import json, os, sys\n"
    "rank, attempt, world, orig = {rank}, {attempt}, {world}, {orig}\n"
    "root = {root!r}\n"
    "with open(os.path.join(root, 'seen.jsonl'), 'a') as f:\n"
    "    f.write(json.dumps(dict(rank=rank, attempt=attempt,\n"
    "                            world=world, orig=orig)) + '\\n')\n"
    "if attempt == 0 and orig == 1:\n"
    "    with open(os.path.join(root, 'gang',\n"
    "                           'faults_fired.jsonl'), 'a') as f:\n"
    "        f.write(json.dumps(dict(index=0, kind='lose_rank', at=7,\n"
    "                                rank=1)) + '\\n')\n"
    "    os._exit(23)\n"
    "sys.exit(0)\n"
)


def test_failure_shrink_never_backfills_from_spares(tmp_path):
    """Spares promote ONLY at planned boundaries: a lose_rank failure
    shrink proceeds to the smaller world even with a live announced
    spare standing by — the reduced world stays observable."""
    gang = tmp_path / "gang"
    events = FaultEvents()
    codes = gang_supervise(
        _stub_worker_cmd(tmp_path, _LOSE_BODY), 3, gang,
        min_world=1, max_world=3, spares=1,
        spare_cmd=_spare_stub_cmd(tmp_path),
        events=events, poll_s=0.05, max_restarts=2, grace_s=5.0,
    )
    assert codes == [0, 0]
    assert events.gang_shrinks == 1
    assert events.gang_grows == 0 and events.spare_promotions == 0
    final = [s for s in _seen(tmp_path) if s["attempt"] == 1]
    assert sorted((s["rank"], s["orig"]) for s in final) == [(0, 0), (1, 2)]
    assert all(s["world"] == 2 for s in final)


# The readmission trajectory 3→2→3: attempt 0 loses rank 1 (shrink to
# 2); attempt 1's CURRENT rank 0 announces rank 1 recovered (the
# recover_rank acting rule) and the gang waits at the latch; attempt 2
# runs the re-grown world of 3.
_RECOVER_BODY = (
    "import json, os, sys, time\n"
    "rank, attempt, world, orig = {rank}, {attempt}, {world}, {orig}\n"
    "root = {root!r}\n"
    "gang = os.path.join(root, 'gang')\n"
    "with open(os.path.join(root, 'seen.jsonl'), 'a') as f:\n"
    "    f.write(json.dumps(dict(rank=rank, attempt=attempt,\n"
    "                            world=world, orig=orig)) + '\\n')\n"
    "if attempt == 0 and orig == 1:\n"
    "    with open(os.path.join(gang, 'faults_fired.jsonl'), 'a') as f:\n"
    "        f.write(json.dumps(dict(index=0, kind='lose_rank', at=7,\n"
    "                                rank=1)) + '\\n')\n"
    "    os._exit(23)\n"
    "if attempt == 1:\n"
    "    if rank == 0:\n"
    "        with open(os.path.join(gang, 'faults_fired.jsonl'), 'a') as f:\n"
    "            f.write(json.dumps(dict(index=1, kind='recover_rank',\n"
    "                                    at=9, rank=orig,\n"
    "                                    target=1)) + '\\n')\n"
    "        tmp = os.path.join(gang, '.join_tmp')\n"
    "        with open(tmp, 'w') as f:\n"
    "            json.dump(dict(rank=1, spare=False, kind='recover',\n"
    "                           time=time.time()), f)\n"
    "        os.replace(tmp, os.path.join(gang, 'join_rank1.json'))\n"
    "    deadline = time.time() + 20\n"
    "    while time.time() < deadline:\n"
    "        if os.path.exists(os.path.join(gang, 'abort.json')):\n"
    "            os._exit(43)\n"
    "        time.sleep(0.05)\n"
    "sys.exit(0)\n"
)


def test_recovered_rank_rejoins_after_shrink(tmp_path):
    """The full rejoin-on-recovery trajectory with stubs: 3→2 on
    lose_rank, then the recover_rank ledger entry clears the lost
    marker and the announced join re-admits original rank 1 → 2→3,
    with its failure budget reset."""
    gang = tmp_path / "gang"
    events = FaultEvents()
    codes = gang_supervise(
        _stub_worker_cmd(tmp_path, _RECOVER_BODY), 3, gang,
        min_world=1, max_world=3, events=events, poll_s=0.05,
        max_restarts=2, grace_s=5.0,
    )
    assert codes == [0, 0, 0]
    assert events.gang_shrinks == 1 and events.gang_grows == 1
    assert events.gang_restarts == 1  # only the failure charged
    by_attempt: dict[int, list] = {}
    for s in _seen(tmp_path):
        by_attempt.setdefault(s["attempt"], []).append(s)
    assert sorted(s["orig"] for s in by_attempt[1]) == [0, 2]
    assert all(s["world"] == 2 for s in by_attempt[1])
    assert sorted(s["orig"] for s in by_attempt[2]) == [0, 1, 2]
    assert all(s["world"] == 3 for s in by_attempt[2])
    # The world trajectory reads 3 -> 2 -> 3 in the status tool's
    # derivation of the health ledger.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gang_status", os.path.join(REPO, "tools", "gang_status.py")
    )
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    status = tool.collect(str(gang), str(tmp_path / "no-telemetry"))
    assert status["world_trajectory"] == [3, 2, 3]


# ---------------------------------------------------------------------------
# Chaos proofs (slow + faultinject): 4→3→5, scaling-rule continuity,
# straggler replacement
# ---------------------------------------------------------------------------


def _run_gang(root, *, faults=None, workers=4, steps=30, save_every=5,
              timeout=280, extra=()):
    from distributed_machine_learning_tpu.cli.gang import (
        scrubbed_worker_env,
    )

    cmd = [
        sys.executable, "-m", "distributed_machine_learning_tpu.cli.gang",
        "--workers", str(workers), "--steps", str(steps),
        "--save-every", str(save_every),
        "--ckpt-dir", os.path.join(root, "ckpt"),
        "--gang-dir", os.path.join(root, "gang"),
        "--telemetry-dir", os.path.join(root, "telemetry"),
        *extra,
    ]
    if faults:
        cmd += ["--faults", faults]
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        env=scrubbed_worker_env(REPO), cwd=REPO,
    )


def _consumed_records(root):
    gang = os.path.join(root, "gang")
    recs = []
    for name in os.listdir(gang):
        if name.startswith("consumed_rank"):
            with open(os.path.join(gang, name)) as f:
                for line in f:
                    recs.append(json.loads(line))
    return recs


def _assert_exactly_once_chained(root, n_steps) -> dict[int, int]:
    """Judged in the attempt that finally completed each step, the
    consumed example ids chain CONTIGUOUSLY across the whole run — any
    world/batch history partitions the example stream into
    non-overlapping global batches (the elastic exactly-once
    invariant).  Returns step -> world."""
    by_step: dict[int, list] = {}
    for r in _consumed_records(root):
        by_step.setdefault(r["step"], []).append(r)
    assert sorted(by_step) == list(range(n_steps))
    cursor = 0
    worlds: dict[int, int] = {}
    for step in range(n_steps):
        rows = by_step[step]
        final_attempt = max(r["attempt"] for r in rows)
        final = [r for r in rows if r["attempt"] == final_attempt]
        ids = sorted(i for r in final for i in r["ids"])
        assert ids == list(range(cursor, cursor + len(ids))), (
            f"step {step}: consumed ids {ids[:3]}..{ids[-3:]} do not "
            f"chain at cursor {cursor} — examples lost or duplicated"
        )
        ws = {r["world"] for r in final}
        assert len(ws) == 1, f"step {step} consumed at mixed worlds {ws}"
        worlds[step] = ws.pop()
        assert len(final) == worlds[step]  # every rank logged its shard
        cursor += len(ids)
    return worlds


def _step_losses(root) -> dict[int, float]:
    """step -> quadratic loss from current-rank-0's per-attempt logs,
    later attempts overriding replayed steps (original rank 0 survives
    every transition in these scenarios, so it holds current rank 0
    throughout)."""
    logs = os.path.join(root, "gang", "logs")
    by_attempt = sorted(
        (name for name in os.listdir(logs)
         if name.startswith("rank0.attempt")),
        key=lambda n: int(n.split("attempt")[1].split(".")[0]),
    )
    losses: dict[int, float] = {}
    for name in by_attempt:
        with open(os.path.join(logs, name)) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 4 and parts[0] == "step" \
                        and parts[2] == "loss":
                    losses[int(parts[1])] = float(parts[3])
    return losses


def _registry_counters(root):
    with open(os.path.join(root, "telemetry", "registry.json")) as f:
        snap = json.load(f)
    counters = {c["name"]: c["value"] for c in snap["counters"]
                if not c.get("labels")}
    gauges = {g["name"]: g["value"] for g in snap.get("gauges", [])}
    return counters, gauges, snap


# The 4→3→5 schedule: lose rank 1 at step 7 (shrink to 3), recover it
# at step 14 (planned grow boundary; the warm spare rides along to 5).
_CHAOS_FAULTS = "lose_rank@1:7,recover_rank@1:14"
_CHAOS_EXTRA = ("--max-world", "5", "--spares", "1",
                "--feature-dim", "64", "--min-world", "1")


@pytest.mark.slow
@pytest.mark.faultinject
def test_chaos_world_4_3_5_with_linear_rule(tmp_path):
    """The ROADMAP's named chaos proof: one supervised run goes 4→3→5 —
    lose_rank@1:7 shrinks to the 3 survivors, recover_rank@1:14
    triggers a planned grow boundary readmitting rank 1 AND promoting
    the warm spare to reach 5 — finishing with a verified checkpoint
    that restores onto worlds 1/3/4/5, exactly-once consumption
    chained across both transitions, and (under the linear scaling
    rule) a loss curve continuous across both world changes."""
    root = str(tmp_path / "chaos")
    res = _run_gang(root, faults=_CHAOS_FAULTS,
                    extra=(*_CHAOS_EXTRA, "--scaling-rule", "linear"))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "shrinking to 3 survivor(s)" in res.stdout
    assert "world 3 -> 5" in res.stdout
    assert "world size 5" in res.stdout

    counters, gauges, _ = _registry_counters(root)
    assert counters["gang_shrinks"] == 1
    assert counters["gang_grows"] == 1
    assert counters["spare_promotions"] == 1
    assert counters["gang_restarts"] == 1  # only the failure charged
    assert gauges.get("gang_world_size") == 5

    # Both transitions are trace instants (tools/trace_merge.py renders
    # them on the merged timeline).
    with open(os.path.join(root, "telemetry", "trace.json")) as f:
        trace = f.read()
    assert '"gang_shrink"' in trace and '"gang_grow"' in trace

    # Exactly-once consumption, chained across 4→3→5 (batch 24→18→30
    # under the linear rule).
    worlds = _assert_exactly_once_chained(root, 30)
    assert set(worlds.values()) == {3, 4, 5}
    assert worlds[0] == 4 and worlds[29] == 5

    # The health ledger narrates the story and the status tool derives
    # the 4→3→5 trajectory from it.
    res_status = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gang_status.py"),
         os.path.join(root, "gang"), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert res_status.returncode == 0, res_status.stderr
    status = json.loads(res_status.stdout)
    assert status["world_trajectory"] == [4, 3, 5]
    kinds = [e.get("kind") for e in status["health"]]
    assert "shrink" in kinds and "grow" in kinds and "promote" in kinds
    grow = next(e for e in status["health"] if e.get("kind") == "grow")
    assert grow["joined"] == [1] and grow["promoted"] == [4]

    # The final checkpoint restores onto worlds 1/3/4/5 bit-identically
    # from every member's directory, and the whole chain verifies.
    digests = {}
    for orig_rank in (0, 2, 3, 4):
        latest = latest_checkpoint(
            os.path.join(root, "ckpt", f"rank{orig_rank}")
        )
        assert latest is not None and latest.endswith("step_30")
        for w in (1, 3, 4, 5):
            state, spec = reshard_restore(latest, world=w)
            assert spec.world == w
            digests[(orig_rank, w)] = hashlib.sha256(
                np.ascontiguousarray(
                    np.asarray(state.params["w"])
                ).tobytes()
            ).hexdigest()
    assert len(set(digests.values())) == 1, digests
    res_verify = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_verify.py"),
         os.path.join(root, "ckpt"), "--json"],
        capture_output=True, text=True, timeout=120,
    )
    assert res_verify.returncode == 0, res_verify.stdout + res_verify.stderr
    assert json.loads(res_verify.stdout)["invalid"] == 0

    # Loss-curve continuity (the scaling-rule proof, linear half):
    # no step-discontinuity beyond the fixed tolerance at either
    # transition, and the stationary floor is world-invariant within
    # band — the quadratic loss is chi-square-noisy (dim 64: ~18%/step),
    # so windows average a few steps and the tolerances are generous
    # multiples of the expected shifts.
    losses = _step_losses(root)
    assert sorted(losses) == list(range(30))
    for boundary in (7, 14):
        pre = np.mean([losses[s] for s in range(boundary - 3, boundary)])
        post = np.mean([losses[s] for s in range(boundary, boundary + 3)])
        assert 1 / 3 < post / pre < 3, (
            f"loss discontinuity at the world change near step "
            f"{boundary}: {pre:.4f} -> {post:.4f}"
        )
    floor3 = np.mean([losses[s] for s in range(9, 14)])
    floor5 = np.mean([losses[s] for s in range(25, 30)])
    assert 0.6 < floor5 / floor3 < 2.0, (
        f"linear rule failed to hold the stationary floor: world-3 "
        f"window {floor3:.4f} vs world-5 window {floor5:.4f}"
    )


@pytest.mark.slow
@pytest.mark.faultinject
def test_chaos_control_unscaled_rule_breaks_the_floor(tmp_path):
    """The load-bearing control: the same 4→3→5 run under ``unscaled``
    (batch tracks the world, LR never compensates) shifts the
    stationary loss floor with 1/world — the discontinuity the linear
    rule exists to prevent (expected ratio ≈ 0.6 here, well outside
    the linear run's band)."""
    root = str(tmp_path / "control")
    res = _run_gang(root, faults=_CHAOS_FAULTS,
                    extra=(*_CHAOS_EXTRA, "--scaling-rule", "unscaled"))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "world size 5" in res.stdout
    worlds = _assert_exactly_once_chained(root, 30)
    assert worlds[29] == 5  # same trajectory, same exactly-once story
    losses = _step_losses(root)
    floor3 = np.mean([losses[s] for s in range(9, 14)])
    floor5 = np.mean([losses[s] for s in range(25, 30)])
    # lr/(B(2-lr)) per coordinate: unchanged lr over a 18→30 batch
    # change moves the floor by ~0.6x — the control demonstrates the
    # compensation is load-bearing, not decorative.
    assert floor5 / floor3 < 0.75, (
        f"expected the unscaled control to shift the floor: "
        f"{floor3:.4f} -> {floor5:.4f}"
    )


@pytest.mark.slow
@pytest.mark.faultinject
def test_chaos_straggler_replacement_policy(tmp_path):
    """stall_rank@1:6:30 under ``--straggler-policy=replace``: the
    stalled rank is demoted to the spare pool at a planned replacement
    boundary and the warm spare is promoted in its place — world size
    unchanged, nobody's restart budget charged, and the counters +
    health ledger tell the story through ``gang_status``."""
    root = str(tmp_path / "straggle")
    res = _run_gang(
        root, faults="stall_rank@1:6:30", steps=16,
        extra=("--spares", "1", "--straggler-policy", "replace",
               "--replace-after", "2", "--peer-timeout", "60",
               "--max-world", "4"),
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "straggler policy: demoting rank 1" in res.stdout
    assert "world size 4" in res.stdout

    counters, gauges, snap = _registry_counters(root)
    assert counters["spare_promotions"] == 1
    assert counters["spare_demotions"] == 1
    assert counters.get("gang_restarts", 0) == 0  # planned, not charged
    assert counters.get("gang_shrinks", 0) == 0
    assert gauges.get("gang_world_size") == 4
    straggler = [c for c in snap["counters"]
                 if c["name"] == "gang_straggler"
                 and c.get("labels", {}).get("rank") == "1"]
    assert straggler and straggler[0]["value"] >= 1

    worlds = _assert_exactly_once_chained(root, 16)
    assert set(worlds.values()) == {4}  # replacement kept the world

    res_status = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gang_status.py"),
         os.path.join(root, "gang"), "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert res_status.returncode == 0, res_status.stderr
    status = json.loads(res_status.stdout)
    demotes = [e for e in status["health"] if e.get("kind") == "demote"]
    promotes = [e for e in status["health"] if e.get("kind") == "promote"]
    assert len(demotes) == 1 and demotes[0]["rank"] == 1
    assert len(promotes) == 1 and promotes[0]["rank"] == 4
    # The demoted rank stands by as a spare in the final attempt.
    spare_ranks = {r["rank"] for r in status.get("spares", ())}
    assert 1 in spare_ranks
    # And the human rendering narrates the same story.
    res_render = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gang_status.py"),
         os.path.join(root, "gang")],
        capture_output=True, text=True, timeout=60,
    )
    assert "demote" in res_render.stdout
    assert "promote" in res_render.stdout
