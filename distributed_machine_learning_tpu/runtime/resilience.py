"""Failure detection + preemption-safe shutdown.

The reference has neither (SURVEY.md §5): a crashed rank leaves the
other three blocked inside a synchronous gloo collective forever, and
the only cleanup is ``dist.destroy_process_group()`` on the happy path
(``part2/2a/main.py:207``).  On TPU pods the equivalent failure modes
are a hung ICI/DCN collective (peer died) and *preemption* — the
scheduler SIGTERMs the job and reclaims the slice.  This module is the
framework's answer to both:

- :class:`Watchdog` — a daemon thread fed one ``beat()`` per completed
  step.  If no step lands within ``timeout_s`` it declares a stall,
  dumps every Python thread's stack (so the operator sees *which*
  collective is stuck), and invokes ``on_stall`` — by default a loud
  report; pass ``exit_code`` to make it terminate the process instead,
  the "fail fast so the supervisor restarts from the latest checkpoint"
  policy every production trainer settles on.
- :class:`PreemptionHandler` — installs signal handlers (SIGTERM, and
  the platform's advance-warning signal if any) that set a flag the
  training loop polls at step boundaries (``train_epoch(stop=...)``);
  the runner then writes a final checkpoint and exits cleanly, so a
  preempted run resumes exactly where it stopped (``--resume``).

Both are host-side Python: they watch the XLA program from outside and
never touch the compiled step, so they cost nothing on the device.
"""

from __future__ import annotations

import contextlib
import faulthandler
import os
import signal
import sys
import threading
import time
from typing import Callable

import jax


class Watchdog:
    """Detects a stalled training step (hung collective, dead peer).

    Usage::

        wd = Watchdog(timeout_s=300)
        wd.start()
        ...
        wd.beat()   # once per completed step
        ...
        wd.stop()

    or as a context manager.  ``on_stall(elapsed_s)`` runs in the
    watchdog thread on the first stall; the default prints a report and
    dumps all thread stacks.  ``exit_code``: if not None, the process
    exits with this code after ``on_stall`` — turning a silent hang
    into a fast, restartable failure.
    """

    def __init__(
        self,
        timeout_s: float,
        on_stall: Callable[[float], None] | None = None,
        exit_code: int | None = None,
        poll_s: float | None = None,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self.on_stall = on_stall
        self.exit_code = exit_code
        self.poll_s = poll_s if poll_s is not None else min(timeout_s / 4, 1.0)
        self.stalled = False
        self._suspended = 0
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self) -> None:
        """Record liveness — call once per completed step."""
        self._last_beat = time.monotonic()

    @contextlib.contextmanager
    def suspend(self):
        """Pause stall detection across an expected-long non-step phase
        (checkpoint save, eval, trace dump).

        Beating on the way in and out is not enough once the phase can
        outlast ``timeout_s``: the stall would be declared *during* the
        phase and — under a supervisor that escalates stalls to restarts
        — a perfectly healthy run would burn a restart per checkpoint.
        Suspension stops the clock instead; step time is the only time
        the watchdog judges.  Re-entrant, and beats on exit so the next
        step starts with a full window.
        """
        self._suspended += 1
        try:
            yield
        finally:
            # Beat BEFORE lifting suspension: the poll thread must never
            # observe un-suspended state with the save still on the clock.
            try:
                self.beat()
            finally:
                self._suspended -= 1

    def _run(self) -> None:
        reported = False
        while not self._stop.wait(self.poll_s):
            if self._suspended:
                continue  # inside save/eval — the clock is stopped
            elapsed = time.monotonic() - self._last_beat
            if elapsed >= self.timeout_s:
                if reported:
                    continue  # one report per stall episode
                reported = True
                self.stalled = True
                if self.on_stall is not None:
                    self.on_stall(elapsed)
                else:
                    print(
                        f"[watchdog] no step completed in {elapsed:.1f}s "
                        f"(timeout {self.timeout_s}s) — likely a hung "
                        "collective (dead peer?) or a stuck input "
                        "pipeline; dumping thread stacks:",
                        file=sys.stderr,
                        flush=True,
                    )
                    faulthandler.dump_traceback(file=sys.stderr)
                if self.exit_code is not None:
                    os._exit(self.exit_code)
            else:
                # A beat landed after a stall: the step recovered (e.g. a
                # slow eval or checkpoint in between) — keep monitoring
                # and allow the next episode to be reported too.
                reported = False

    def start(self) -> "Watchdog":
        if self._thread is not None:
            raise RuntimeError("watchdog already started")
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class PreemptionHandler:
    """Turns termination signals into a cooperative stop flag.

    ``signals``: defaults to SIGTERM (what TPU/Borg/k8s preemption
    sends).  The previous handlers are preserved and restored by
    ``uninstall()`` (or context-manager exit); the framework's handler
    only sets the flag — shutdown work (final checkpoint) belongs to
    the training loop, at a step boundary, where state is consistent.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._prev: dict[int, object] = {}
        self._installed = False
        self.requested = False

    def _handle(self, signum, frame):
        del frame
        self.requested = True
        print(
            f"[preemption] caught signal {signum}; will checkpoint and "
            "stop at the next step boundary",
            file=sys.stderr,
            flush=True,
        )

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "signal handlers can only be installed from the main thread"
            )
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def __call__(self) -> bool:
        """The stop predicate ``train_epoch(stop=...)`` polls."""
        return self.requested


def periodic_agree_stop(local_fn: Callable[[], bool], every: int = 10):
    """A stop predicate for ``train_epoch`` that reaches cross-host
    agreement only every ``every``-th poll.

    On multi-host runs ``agree_stop`` is a blocking allgather; paying it
    before *every* step taxes the whole run for an event that happens at
    most once.  Polling the agreement every N steps keeps the
    hang-free guarantee (all hosts skip and poll on the same iterations,
    since they count polls in lockstep) at 1/N the cost — preemption
    grace periods are tens of seconds, so a few extra steps of latency
    are immaterial.  Single-process: ``agree_stop`` is local and free,
    and ``every`` is forced to 1 so the signal is honored immediately.
    Once stopped, stays stopped.
    """
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    if jax.process_count() == 1:
        every = 1
    state = {"polls": 0, "stopped": False}

    def stop() -> bool:
        if state["stopped"]:
            return True
        i = state["polls"]
        state["polls"] += 1
        if i % every:
            return False  # off-cycle: no collective, no decision
        state["stopped"] = agree_stop(local_fn())
        return state["stopped"]

    return stop


def agree_stop(local: bool) -> bool:
    """Cross-host agreement on a stop decision.

    A per-host flag is not enough on multi-host runs: a signal lands on
    different hosts at different times, and a host that exits its step
    loop one iteration early leaves the others blocked forever inside a
    collective — the exact hang this module exists to prevent.  This
    max-reduces the flag over all processes (any host requesting stop
    stops everyone) at a common point in the loop, so every host leaves
    at the same step boundary.  Single-process: returns ``local`` with
    no collective.
    """
    if jax.process_count() == 1:
        return bool(local)
    from jax.experimental import multihost_utils

    import numpy as np

    return bool(
        multihost_utils.process_allgather(np.int32(bool(local))).max()
    )
