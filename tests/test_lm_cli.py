"""LM CLI entrypoint: each --parallel mode runs end-to-end (tiny configs,
8-device CPU mesh) and dp/ring agree on the loss trajectory."""

import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.lm import main, make_parser

TINY = [
    "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
    "--seq-len", "16", "--batch-size", "8", "--vocab", "64",
    "--max-iters", "3",
]


@pytest.mark.parametrize(
    "extra",
    [
        ["--parallel", "dp"],
        ["--parallel", "ring"],
        pytest.param(["--parallel", "ulysses", "--n-heads", "8"],
                     marks=pytest.mark.slow),
        pytest.param(["--parallel", "tp", "--n-heads", "8"],
                     marks=pytest.mark.slow),
        pytest.param(["--parallel", "pp", "--n-layers", "8"],
                     marks=pytest.mark.slow),
        pytest.param(["--parallel", "3d", "--n-heads", "8", "--pp", "2",
                      "--tp", "2"], marks=pytest.mark.slow),
        pytest.param(["--parallel", "ep", "--n-experts", "4", "--ep", "4",
                      "--batch-size", "4"], marks=pytest.mark.slow),
        pytest.param(["--parallel", "fsdp_pl"], marks=pytest.mark.slow),
    ],
    ids=["dp", "ring", "ulysses", "tp", "pp", "3d", "ep", "fsdp_pl"],
)
def test_lm_cli_runs(extra, capsys):
    main(TINY + extra)
    out = capsys.readouterr().out
    assert "Total execution time" in out


def test_lm_cli_dp_ring_same_loss(capsys):
    """dp and ring consume the same synthetic stream and replicate the
    same model — their printed losses must match."""
    main(TINY + ["--max-iters", "20", "--parallel", "dp"])
    dp_out = capsys.readouterr().out
    main(TINY + ["--max-iters", "20", "--parallel", "ring"])
    ring_out = capsys.readouterr().out

    def loss_of(out):
        for line in out.splitlines():
            if line.startswith("Loss at"):
                return float(line.rsplit(" ", 1)[-1])
        raise AssertionError(f"no loss line in {out!r}")

    np.testing.assert_allclose(loss_of(dp_out), loss_of(ring_out), rtol=1e-5)


def test_lm_cli_bad_config_fails_fast():
    with pytest.raises(ValueError, match="pipeline stages"):
        main(TINY + ["--parallel", "pp", "--n-layers", "3"])
    # a 3-D mesh that would idle devices is refused, not silently shrunk
    with pytest.raises(ValueError, match="device count"):
        main(TINY + ["--parallel", "3d", "--n-heads", "8", "--pp", "3",
                     "--tp", "2"])
    with pytest.raises(ValueError, match="--dp"):
        main(TINY + ["--parallel", "3d", "--dp", "0", "--pp", "2",
                     "--tp", "2"])
    with pytest.raises(ValueError, match="--pp and --tp"):
        main(TINY + ["--parallel", "3d", "--pp", "0", "--tp", "2"])
    with pytest.raises(ValueError, match="divisible"):
        main(TINY + ["--parallel", "dp", "--batch-size", "12"])
    # MoE x CP needs the grouped (manual shard_map) path, not einsum
    with pytest.raises(ValueError, match="grouped"):
        main(TINY + ["--parallel", "ep", "--n-experts", "4",
                     "--moe-impl", "einsum", "--ep-seq", "2"])
    # ep x ep_seq must divide the device count
    with pytest.raises(ValueError, match="divide"):
        main(TINY + ["--parallel", "ep", "--n-experts", "4", "--ep", "4",
                     "--moe-impl", "grouped", "--ep-seq", "3"])
    with pytest.raises(ValueError, match="sequence axis"):
        main(TINY + ["--parallel", "ring", "--seq-len", "100"])
    with pytest.raises(ValueError, match="data axis"):
        main(TINY + ["--parallel", "3d", "--n-heads", "8", "--pp", "2",
                     "--tp", "2", "--batch-size", "6"])


def test_ep_flag_guards():
    with pytest.raises(ValueError, match="positive divisor"):
        main(TINY + ["--parallel", "ep", "--n-experts", "4",
                     "--n-kv-heads", "0"])
    with pytest.raises(ValueError, match="mlp only"):
        main(TINY + ["--parallel", "ep", "--n-experts", "4", "--remat",
                     "--remat-policy", "block"])


def test_lm_cli_ep_slots_flag_discipline():
    with pytest.raises(ValueError, match="ep-slots"):
        main(TINY + ["--parallel", "dp", "--ep-slots", "4"])
    with pytest.raises(ValueError, match="ep-slots"):
        main(TINY + ["--parallel", "ep", "--moe-impl", "einsum",
                     "--ep-slots", "4"])


def test_lm_cli_ep_grouped_bounded_slots_runs(capsys):
    main(TINY + ["--parallel", "ep", "--moe-impl", "grouped",
                 "--n-experts", "4", "--ep", "4", "--ep-slots", "8",
                 "--batch-size", "8"])
    out = capsys.readouterr().out
    assert "Total execution time" in out


def test_lm_cli_dynamic_loss_scale_runs(capsys):
    main(TINY + ["--parallel", "dp", "--loss-scale", "dynamic"])
    out = capsys.readouterr().out
    assert "Total execution time" in out


def test_lm_cli_guard_nonfinite_runs(capsys):
    main(TINY + ["--parallel", "dp", "--guard-nonfinite"])
    out = capsys.readouterr().out
    assert "Total execution time" in out


def test_lm_cli_robustness_flags_fail_fast_on_unsupported_scheme():
    # fsdp_pl's step doesn't implement the guard: silently training
    # unguarded would be worse than refusing.
    with pytest.raises(ValueError, match="guard-nonfinite"):
        main(TINY + ["--parallel", "fsdp_pl", "--loss-scale", "dynamic"])


def test_lm_cli_resume_auto_restores_checkpoint(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    main(TINY + ["--parallel", "dp", "--ckpt-dir", ck])
    capsys.readouterr()
    main(TINY + ["--parallel", "dp", "--ckpt-dir", ck, "--resume", "auto"])
    out = capsys.readouterr().out
    assert "Resumed from" in out
    assert "Total execution time" in out
