"""dmlcheck layer 3 — deterministic interleaving exploration for the
gang control plane.

Layers 1 and 2 look at *programs* (AST idioms, jaxpr/HLO structure);
the properties PR 12's transport actually promises — exactly-once
ledger appends, first-writer-wins abort, admit-once joins, epoch
fencing — are *interleaving* properties, invisible to both.  This
module makes them testable deterministically:

- :class:`Scheduler` — a cooperative scheduler driven through the
  ``_sched_point`` / ``_sched_block`` seam in ``runtime/coordinator.py``
  (aliased by ``runtime/transport.py``).  Exactly one scenario thread
  runs between schedule points; every context switch is an explicit
  *choice*, so a run is fully described by its choice list.
- :func:`explore` — stateless DFS over choice prefixes: exhaustive for
  the quick configs (≤3 threads / ≤8 ops), with label-based
  partial-order pruning and a bounded-preemption filter for the larger
  ``full`` configs.
- :data:`SCENARIOS` — nine bounded gang protocols (abort race, join
  duplicate delivery, ledger append storm, dedup-cache hit racing a
  slow in-flight apply, beat publish vs batched reads, epoch fence vs
  zombie thread, serving drain/promote handoff vs a retiring
  replica's late result, weight hot-swap commit vs an old-version
  compute's late post, paged-KV admission racing decode appends and
  retirement frees), each with invariants checked after every
  terminal schedule.
- :data:`MUTATIONS` — the known-bug seeds (the pre-fix dedup eviction,
  the pre-fix epoch check outside the lock, the pre-fix serving
  result fence, the pre-fix weight-swap version fence, the pre-fix
  block-allocator capacity check outside the lock).  The
  mutation-test gate: with a seed applied, the explorer must
  rediscover the bug deterministically; on the fixed tree it must
  exit clean.
- Reproducers — a failing schedule serializes to JSON
  (:func:`save_reproducer`); ``dmlcheck --replay FILE`` re-runs that
  exact interleaving (:func:`replay_file`), so a CI failure is a
  deterministic test case, not a flake.

Determinism contract: no randomness, and no wall-clock reads in
control flow (``perf_counter`` is used only for reported durations and
the full-mode deadline; quick mode is capped by schedule COUNT only,
so two quick runs explore the identical schedule set).

Stdlib-only by construction, like the rest of layer 1's import chain.
"""

from __future__ import annotations

import contextlib
import importlib.util
import json
import os
import shutil
import sys
import tempfile
import threading
import time

from ..runtime import coordinator as _coord
from ..runtime import transport as _transport
from ..runtime.transport import (
    InProcHub,
    InProcTransport,
    TcpGangServer,
    TransportError,
    _InFlight,
    _read_jsonl_dicts,
)
from .findings import Finding


def _load_kv_blocks():
    """The block allocator under test WITHOUT importing the
    ``inference`` package: its ``__init__`` pulls in jax, and this
    module must stay importable under ``python -S`` (the dmlcheck
    CLI).  ``kv_blocks.py`` itself is stdlib-only by construction, so
    when the canonical module is already loaded (pytest runs) the
    scenario — and the ``admit-unlocked`` seed — target the REAL
    class; otherwise the file is loaded directly, bypassing the
    package ``__init__``."""
    mod = sys.modules.get(
        "distributed_machine_learning_tpu.inference.kv_blocks")
    if mod is not None:
        return mod
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "inference", "kv_blocks.py")
    spec = importlib.util.spec_from_file_location(
        "dml_layer3_kv_blocks", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_kvb = _load_kv_blocks()

LAYER3_RULES = {"DML301", "DML302"}

_WATCHDOG_S = 20.0


class ScheduleAbort(BaseException):
    """Raised inside a scenario thread during teardown so it unwinds
    instead of running free once exploration is done with this
    schedule.  Deliberately a BaseException: scenario code that
    catches ``Exception`` (e.g. retry loops) must not swallow it."""


class DeadlockError(RuntimeError):
    """No runnable thread, at least one blocked thread: the schedule
    wedged.  Reported as DML302."""

    def __init__(self, message: str, trace):
        super().__init__(message)
        self.trace = list(trace)


class SchedulerStuckError(RuntimeError):
    """A scheduled thread failed to reach its next schedule point
    within the watchdog — a real (seam-invisible) lock cycle or an
    unbounded wait inside the scenario."""


class _ThreadState:
    __slots__ = ("name", "thread", "gate", "state", "label",
                 "predicate", "error")

    def __init__(self, name: str):
        self.name = name
        self.thread: threading.Thread | None = None
        self.gate = threading.Semaphore(0)
        self.state = "runnable"     # runnable | blocked | running | done
        self.label = "spawn"
        self.predicate = None
        self.error: BaseException | None = None


class Scheduler:
    """Cooperative scheduler: scenario threads hand control back at
    every ``_sched_point``/``_sched_block`` via a semaphore handshake;
    the scheduler picks the next thread to run by asking its chooser.

    Threads not registered via :meth:`spawn` (e.g. leftover daemon
    monitors from other tests — the seam is a process-global) pass
    through every point as a no-op and fall back to real waits in
    ``block``, so installing a scheduler never perturbs bystanders.
    """

    def __init__(self, chooser, watchdog_s: float = _WATCHDOG_S):
        self._chooser = chooser
        self._threads: list[_ThreadState] = []
        self._by_ident: dict[int, _ThreadState] = {}
        self._control = threading.Semaphore(0)
        self._ready = threading.Semaphore(0)
        self._abort = False
        self.watchdog_s = watchdog_s
        self.trace: list[tuple[str, str]] = []

    # -- called from scenario threads (via the runtime seam) -------------
    def point(self, label: str) -> None:
        ts = self._by_ident.get(threading.get_ident())
        if ts is None:
            return
        if self._abort:
            raise ScheduleAbort()
        ts.label = label
        ts.state = "runnable"
        self._control.release()
        ts.gate.acquire()
        if self._abort:
            raise ScheduleAbort()

    def block(self, label: str, predicate) -> bool:
        """Deschedule the calling thread until ``predicate()`` is true
        (evaluated by the scheduler between steps).  Returns False for
        unregistered threads — the caller then falls back to its real
        blocking wait."""
        ts = self._by_ident.get(threading.get_ident())
        if ts is None:
            return False
        if self._abort:
            raise ScheduleAbort()
        ts.label = label
        ts.predicate = predicate
        ts.state = "blocked"
        self._control.release()
        ts.gate.acquire()
        ts.predicate = None
        if self._abort:
            raise ScheduleAbort()
        return True

    # -- driver ----------------------------------------------------------
    def spawn(self, name: str, fn) -> None:
        ts = _ThreadState(name)
        self._threads.append(ts)

        def body():
            self._by_ident[threading.get_ident()] = ts
            self._ready.release()
            ts.gate.acquire()
            try:
                if not self._abort:
                    fn()
            except ScheduleAbort:
                pass
            except BaseException as exc:
                ts.error = exc
            ts.state = "done"
            self._control.release()

        ts.thread = threading.Thread(
            target=body, name=f"l3-{name}", daemon=True)
        ts.thread.start()
        if not self._ready.acquire(timeout=self.watchdog_s):
            raise SchedulerStuckError(
                f"thread {name} never registered")

    def run(self) -> None:
        while True:
            for ts in self._threads:
                if (ts.state == "blocked" and ts.predicate is not None
                        and ts.predicate()):
                    ts.state = "runnable"
            runnable = [t for t in self._threads
                        if t.state == "runnable"]
            if not runnable:
                blocked = [t for t in self._threads
                           if t.state == "blocked"]
                if blocked:
                    raise DeadlockError(
                        "deadlock: no runnable thread; blocked: "
                        + ", ".join(f"{t.name}@{t.label}"
                                    for t in blocked),
                        self.trace)
                return
            options = [(t.name, t.label) for t in runnable]
            idx = self._chooser.choose(options)
            ts = runnable[idx]
            self.trace.append((ts.name, ts.label))
            ts.state = "running"
            ts.gate.release()
            if not self._control.acquire(timeout=self.watchdog_s):
                self._abort = True
                raise SchedulerStuckError(
                    f"watchdog: thread {ts.name} did not reach its "
                    f"next schedule point within {self.watchdog_s}s")

    def teardown(self) -> None:
        self._abort = True
        for ts in self._threads:
            if ts.state != "done":
                ts.gate.release()
        for ts in self._threads:
            if ts.thread is not None:
                ts.thread.join(timeout=5.0)


class _Chooser:
    """Replays a choice prefix, then always picks index 0 (the first
    runnable in registration order).  Records every decision and the
    options it saw, so the explorer can branch on the alternatives."""

    def __init__(self, prefix=()):
        self.prefix = list(prefix)
        self.choices: list[int] = []
        self.options: list[list[tuple[str, str]]] = []

    def choose(self, options) -> int:
        i = len(self.choices)
        pick = self.prefix[i] if i < len(self.prefix) else 0
        if pick >= len(options):
            # A stale prefix (e.g. a reproducer replayed against an
            # edited scenario) must not crash the scheduler: fall back
            # to the default and let the invariants speak.
            pick = 0
        self.choices.append(pick)
        self.options.append(list(options))
        return pick


class _ScheduleResult:
    __slots__ = ("choices", "options", "trace", "violations", "deadlock")

    def __init__(self, choices, options, trace, violations, deadlock):
        self.choices = list(choices)
        self.options = list(options)
        self.trace = list(trace)
        self.violations = list(violations)
        self.deadlock = deadlock


class _Scenario:
    """One bounded protocol instance: named thread bodies, an
    invariant check over the terminal state, and a cleanup hook."""

    def __init__(self, threads, check, cleanup=None):
        self.threads = list(threads)   # [(name, fn), ...]
        self._check = check
        self._cleanup = cleanup

    def check(self) -> list[str]:
        return list(self._check())

    def cleanup(self) -> None:
        if self._cleanup is not None:
            self._cleanup()


def _run_schedule(build, prefix=(),
                  watchdog_s: float = _WATCHDOG_S) -> _ScheduleResult:
    """Run ONE schedule of ``build()`` under the controllable
    scheduler, replaying ``prefix`` then defaulting.  Always uninstalls
    the scheduler and tears the threads down, even on invariant
    failure."""
    inst = build()
    chooser = _Chooser(prefix)
    sched = Scheduler(chooser, watchdog_s)
    violations: list[str] = []
    deadlock = False
    _coord.install_scheduler(sched)
    try:
        try:
            for name, fn in inst.threads:
                sched.spawn(name, fn)
            sched.run()
        except DeadlockError as e:
            deadlock = True
            violations.append(str(e))
        except SchedulerStuckError as e:
            violations.append(f"scheduler stuck: {e}")
        for ts in sched._threads:
            if ts.error is not None:
                violations.append(
                    f"thread {ts.name} raised "
                    f"{type(ts.error).__name__}: {ts.error}")
        if not violations:
            violations.extend(inst.check())
    finally:
        try:
            sched.teardown()
        finally:
            _coord.uninstall_scheduler()
            inst.cleanup()
    return _ScheduleResult(chooser.choices, chooser.options,
                           sched.trace, violations, deadlock)


# ---------------------------------------------------------------------------
# Exploration — stateless DFS over choice prefixes
# ---------------------------------------------------------------------------


def _independent(label_a: str, label_b: str) -> bool:
    """Label-level independence for the POR pruning (full mode only;
    quick mode is exhaustive and never consults this).  Labels are
    structured ``family:channel:mode`` — different channels commute,
    two reads commute, everything touching ``clear`` (the epoch fence)
    or with an unstructured/blocking mode conflicts conservatively."""
    pa, pb = label_a.split(":"), label_b.split(":")
    if len(pa) < 3 or len(pb) < 3:
        return False
    if "clear" in (pa[1], pb[1]):
        return False
    if pa[2] not in ("r", "w") or pb[2] not in ("r", "w"):
        return False
    if pa[0] != pb[0] or pa[1] != pb[1]:
        return True
    return pa[2] == "r" and pb[2] == "r"


def _count_preemptions(options, choices) -> int:
    """A preemption = switching away from a thread that could have
    kept running (its name still among the options)."""
    count = 0
    prev = None
    for opts, ch in zip(options, choices):
        name = opts[ch][0]
        if (prev is not None and name != prev
                and any(n == prev for n, _ in opts)):
            count += 1
        prev = name
    return count


class ExploreStats:
    __slots__ = ("schedules", "capped", "violation", "seconds")

    def __init__(self):
        self.schedules = 0
        self.capped = False
        self.violation: _ScheduleResult | None = None
        self.seconds = 0.0


def explore(build, max_schedules: int = 2000,
            stop_on_violation: bool = True,
            preemption_bound: int | None = None,
            por: bool = False,
            deadline_s: float | None = None) -> ExploreStats:
    """Systematically explore the schedule space of ``build()``.

    Stateless DFS: each stack entry is a choice prefix; running it
    replays the prefix then takes defaults, and every not-taken
    alternative at a position past the prefix becomes a new entry.
    With no ``preemption_bound``/``por``/``deadline_s`` (quick mode)
    the search is EXHAUSTIVE up to ``max_schedules`` and fully
    deterministic — same build, same schedule sequence, every run.
    """
    stats = ExploreStats()
    t0 = time.perf_counter()
    stack: list[tuple[int, ...]] = [()]
    while stack:
        if stats.schedules >= max_schedules:
            stats.capped = True
            break
        if (deadline_s is not None
                and time.perf_counter() - t0 > deadline_s):
            stats.capped = True
            break
        prefix = stack.pop()
        res = _run_schedule(build, prefix)
        stats.schedules += 1
        if res.violations:
            stats.violation = res
            if stop_on_violation:
                break
        for i in range(len(prefix), len(res.choices)):
            opts = res.options[i]
            for alt in range(1, len(opts)):
                if por and _independent(opts[0][1], opts[alt][1]):
                    continue
                cand = tuple(res.choices[:i]) + (alt,)
                if (preemption_bound is not None
                        and _count_preemptions(
                            res.options[:i + 1], list(cand))
                        > preemption_bound):
                    continue
                stack.append(cand)
    stats.seconds = time.perf_counter() - t0
    return stats


# ---------------------------------------------------------------------------
# Eviction spy — separates the BUG from capped-dedup physics
# ---------------------------------------------------------------------------


def _spy_evictions(srv: TcpGangServer) -> dict:
    """Wrap ``srv._evict_seen_locked`` (instance attribute shadowing
    the class method — so a MUTATIONS patch of the class still takes
    effect underneath) and record which op_ids each eviction dropped,
    split by whether the entry was still ``_InFlight``.

    This is what keeps the invariants honest at tiny ``_DEDUP_CAP``:
    evicting a SETTLED result early is legitimate capped-dedup
    behavior (the retry then re-applies — with the production cap of
    65536 that window is unreachable), while evicting an IN-FLIGHT
    reservation is exactly the PR-12 bug.  Scenarios assert
    ``spy['inflight'] == []`` unconditionally and excuse
    exactly-once row counts only for ops in ``spy['settled']``."""
    log = {"inflight": [], "settled": []}

    def spy():
        before = dict(srv._seen)
        type(srv)._evict_seen_locked(srv)
        for op_id, entry in before.items():
            if op_id not in srv._seen:
                kind = ("inflight" if isinstance(entry, _InFlight)
                        else "settled")
                log[kind].append(op_id)

    srv._evict_seen_locked = spy
    return log


# ---------------------------------------------------------------------------
# Scenarios
# ---------------------------------------------------------------------------


def _server(cap: int) -> TcpGangServer:
    srv = TcpGangServer(listen=False)
    srv._DEDUP_CAP = cap   # instance attr shadows the class's 65536
    return srv


def _build_abort_race() -> _Scenario:
    """Two ranks declare abort concurrently, each delivery duplicated
    (retry with the same op_id).  Invariants: every declarer sees ONE
    stable verdict across its deliveries, exactly one wins, and the
    latched abort matches the winner."""
    srv = _server(cap=8)
    results: dict[int, list] = {}

    def declarer(i: int):
        def run():
            req = {"op": "declare_abort", "op_id": f"ab{i}",
                   "reason": f"r{i}", "by_rank": i}
            out = []
            for _ in range(2):
                out.append(srv.dispatch(dict(req)))
            results[i] = out
        return run

    def check():
        v = []
        winners = []
        for i in sorted(results):
            out = results[i]
            if len({bool(x) for x in out}) > 1:
                v.append(f"declarer {i} saw an unstable verdict "
                         f"across duplicate deliveries: {out}")
            if out and out[0]:
                winners.append(i)
        if len(winners) != 1:
            v.append(f"abort latched by {winners or 'nobody'} "
                     "(want exactly one winner)")
        ab = srv.hub.abort
        if ab is None:
            v.append("no abort recorded after two declares")
        elif len(winners) == 1 and ab.get("by_rank") != winners[0]:
            v.append(f"latched abort credits rank {ab.get('by_rank')} "
                     f"but the stable winner is {winners[0]}")
        return v

    return _Scenario([("declare0", declarer(0)),
                      ("declare1", declarer(1))], check)


def _build_join_dup() -> _Scenario:
    """A join announce races its admit (consume+consumed-append),
    with the admit delivered twice — at ``_DEDUP_CAP=1`` so the store
    churns.  Invariant: the admit is applied exactly once (one
    consumed row) unless its settled result was legitimately evicted;
    an in-flight reservation is NEVER evicted."""
    srv = _server(cap=1)
    spy = _spy_evictions(srv)

    def announcer():
        srv.dispatch({"op": "announce_join", "op_id": "an1",
                      "rank": 7, "payload": {"host": "h7"}})

    def admit():
        srv.dispatch({"op": "append_consumed", "op_id": "ac1",
                      "rank": 7, "payload": {"admit": 1}})

    def check():
        v = []
        if spy["inflight"]:
            v.append("dedup eviction dropped in-flight reservation(s) "
                     f"{spy['inflight']} — their retries will "
                     "re-apply")
        rows = len(srv.hub.consumed.get(7, ()))
        if rows != 1 and "ac1" not in spy["settled"]:
            v.append(f"join admitted {rows} times (want exactly once; "
                     "no settled-result eviction to excuse it)")
        if srv.hub.joins.get(7) is None:
            v.append("join announcement lost")
        return v

    return _Scenario([("announce", announcer), ("admit", admit),
                      ("admit-dup", admit)], check)


def _build_ledger_storm(appends_per_writer: int = 2) -> _Scenario:
    """Two writers appending to the health ledger (mirrored to disk),
    the first append of writer 0 duplicated.  ``_DEDUP_CAP=8`` exceeds
    the distinct op count, so NO eviction can occur and the strict
    checks are sound: every append applied exactly once, per-writer
    order preserved, and the on-disk mirror byte-for-byte
    order-consistent with the hub ledger."""
    tmp = tempfile.mkdtemp(prefix="l3-ledger-")
    srv = TcpGangServer(listen=False, mirror_dir=tmp)
    srv._DEDUP_CAP = 8

    def writer(i: int):
        def run():
            for j in range(appends_per_writer):
                req = {"op": "append_health",
                       "op_id": f"w{i}n{j}",
                       "payload": {"w": i, "n": j}}
                srv.dispatch(dict(req))
                if i == 0 and j == 0:
                    srv.dispatch(dict(req))   # duplicated delivery
        return run

    def check():
        v = []
        rows = [(e["w"], e["n"]) for e in srv.hub.health]
        want = {(i, j) for i in range(2)
                for j in range(appends_per_writer)}
        for key in sorted(want):
            n = rows.count(key)
            if n != 1:
                v.append(f"append {key} applied {n} times "
                         "(want exactly once)")
        for i in range(2):
            mine = [n for (w, n) in rows if w == i]
            if mine != sorted(mine):
                v.append(f"writer {i}'s appends reordered: {mine}")
        mirror = [(e["w"], e["n"]) for e in _read_jsonl_dicts(
            os.path.join(tmp, _coord.GANG_HEALTH_FILE))]
        if mirror != rows:
            v.append(f"mirror order diverged from hub ledger: "
                     f"mirror={mirror} hub={rows}")
        return v

    return _Scenario(
        [("writer0", writer(0)), ("writer1", writer(1))], check,
        cleanup=lambda: shutil.rmtree(tmp, ignore_errors=True))


def _build_dedup_inflight() -> _Scenario:
    """THE dedup-eviction gate: an append's retry races the original's
    slow apply while a third op churns the dedup store at
    ``_DEDUP_CAP=1``.  Fixed tree: eviction skips the in-flight
    reservation, the retry waits on it, exactly-once holds (modulo a
    legitimately evicted SETTLED result, which the spy excuses).
    With ``MUTATIONS['dedup-evict']`` the naive popitem loop evicts
    the reservation and the retry re-applies."""
    srv = _server(cap=1)
    spy = _spy_evictions(srv)
    append_v1 = {"op": "append_health", "op_id": "v1",
                 "payload": {"k": "v1"}}

    def orig():
        srv.dispatch(dict(append_v1))

    def retry():
        srv.dispatch(dict(append_v1))

    def evictor():
        srv.dispatch({"op": "append_health", "op_id": "e1",
                      "payload": {"k": "e1"}})

    def check():
        v = []
        if spy["inflight"]:
            v.append("dedup eviction dropped in-flight reservation(s) "
                     f"{spy['inflight']} — exactly-once broken for "
                     "their retries")
        rows = [e["k"] for e in srv.hub.health].count("v1")
        if rows != 1 and "v1" not in spy["settled"]:
            v.append(f"append v1 applied {rows} times (want exactly "
                     "once; no settled-result eviction to excuse it)")
        return v

    return _Scenario([("orig", orig), ("retry", retry),
                      ("evictor", evictor)], check)


def _build_beat_read_race() -> _Scenario:
    """Beat publishes and health appends race a batched reader.
    Invariants: the reader's snapshot health is a prefix of the final
    ledger (prefix-closed reads), beat versions it observes never
    regress, and the terminal beat is the last publish."""
    hub = InProcHub()
    pub_t = InProcTransport(hub)
    app_t = InProcTransport(hub)
    read_t = InProcTransport(hub)
    seen: dict = {}

    def publisher():
        for k in (1, 2):
            pub_t.publish_beat(0, {"step": k})

    def appender():
        for j in (1, 2):
            app_t.append_health_event("mark", n=j)

    def reader():
        first = read_t.read_beats()
        snap = read_t.snapshot()
        second = read_t.read_beats()
        seen["first"] = first
        seen["snap"] = snap
        seen["second"] = second

    def check():
        v = []
        final_health = [e.get("n") for e in hub.health]
        snap_health = [e.get("n")
                       for e in seen["snap"]["health"]]
        if final_health[:len(snap_health)] != snap_health:
            v.append(f"snapshot health {snap_health} is not a prefix "
                     f"of the final ledger {final_health}")
        v0 = seen["first"].get(0, (0, None))[0]
        v1 = seen["second"].get(0, (0, None))[0]
        if v1 < v0:
            v.append(f"beat version regressed across reads: "
                     f"{v0} -> {v1}")
        final = hub.beats.get(0)
        if final is None or final[1] != {"step": 2}:
            v.append(f"terminal beat is not the last publish: {final}")
        return v

    return _Scenario([("publisher", publisher),
                      ("appender", appender),
                      ("reader", reader)], check)


def _build_epoch_fence() -> _Scenario:
    """A zombie thread from a drained attempt (epoch-bound transport)
    races the supervisor's clear + first write of the next attempt.
    Invariant: the zombie NEVER lands a row in the post-clear ledger —
    it either wrote before the clear (wiped) or got the
    TransportError fence.  ``MUTATIONS['epoch-unlocked']`` reopens
    the check-then-act window layer 3 must catch."""
    hub = InProcHub()
    zombie_t = InProcTransport(hub, bind_epoch=True)
    super_t = InProcTransport(hub)
    outcome: dict = {}

    def zombie():
        try:
            zombie_t.append_health_event("beat", zombie=True)
            outcome["zombie"] = "wrote"
        except TransportError:
            outcome["zombie"] = "fenced"

    def supervisor():
        hub.clear(restore_records=True, fault_ledger=True)
        super_t.append_health_event("init", post=True)

    def check():
        v = []
        # Strip the wall timestamps the coordinator stamps into health
        # rows: violation MESSAGES must be replay-stable byte for byte.
        rows = [{k: x for k, x in e.items() if k != "time"}
                for e in hub.health]
        if any(e.get("zombie") for e in rows):
            v.append("drained epoch's thread mutated hub state after "
                     f"the clear: post-clear ledger {rows}")
        if not any(e.get("post") for e in rows):
            v.append(f"next attempt's init write lost: {rows}")
        return v

    return _Scenario([("zombie", zombie),
                      ("supervisor", supervisor)], check)


def _build_drain_promote() -> _Scenario:
    """The serving drain/promote handoff (ISSUE 16): replica 7 holds
    request "x" in flight while the router retires it (the epoch-fence
    bump) and promotes spare 9 in its place, re-dispatching "x" to the
    survivor if 7's result never arrived.  Invariants: "x" is
    delivered exactly once through the router's first-result-wins
    collection, and a post from the RETIRED epoch never lands in the
    results channel after the handoff — the atomic check-and-append
    that ``MUTATIONS['result-unfenced']`` breaks open.
    """
    hub = InProcHub()
    router_t = InProcTransport(hub)
    zombie_t = InProcTransport(hub)
    spare_t = InProcTransport(hub)
    # Pre-schedule setup: 7 is live, "x" dispatched and taken (in
    # flight on the soon-to-be-drained replica).
    router_t.set_serving_role(7, "live")
    e0 = router_t.read_serving(7)["epoch"]
    router_t.push_request(7, {"rid": "x", "epoch": e0})
    assert zombie_t.take_requests(7, 1), "setup: take must claim x"
    delivered: list = []
    seen_rids: set = set()
    outcome: dict = {}

    def collect():
        for res in router_t.take_results(8):
            if res.get("rid") in seen_rids:
                outcome["duplicates"] = outcome.get("duplicates", 0) + 1
                continue
            seen_rids.add(res.get("rid"))
            delivered.append(res)

    def zombie():
        # The draining replica's late post, racing its own demotion.
        ok = zombie_t.post_result(7, e0, {"rid": "x", "who": "zombie"})
        outcome["zombie"] = "delivered" if ok else "fenced"

    def router():
        collect()
        router_t.retire_replica(7)     # the epoch-fenced handoff
        router_t.set_serving_role(9, "live")
        if not any(r.get("rid") == "x" for r in delivered):
            # 7 never answered: re-dispatch to the promoted spare.
            e9 = router_t.read_serving(9)["epoch"]
            router_t.push_request(9, {"rid": "x", "epoch": e9})
            for req in spare_t.take_requests(9, 1):
                spare_t.post_result(9, e9, {"rid": req.get("rid"),
                                            "who": "spare"})
        collect()

    def check():
        v = []
        xs = [r.get("who") for r in delivered if r.get("rid") == "x"]
        if len(xs) != 1:
            v.append(f"request x delivered {len(xs)} time(s) by {xs} "
                     "(want exactly once)")
        leftover = [{k: x for k, x in r.items() if k != "time"}
                    for r in hub.serving_results
                    if r.get("rid") == "x"]
        if leftover:
            v.append("retired replica's late result landed in the "
                     "results channel AFTER the drain/promote handoff "
                     f"(epoch fence broken): {leftover}")
        return v

    return _Scenario([("zombie", zombie), ("router", router)], check)


def _build_weight_swap() -> _Scenario:
    """The continuous-deployment hot-swap (ISSUE 18): replica 7 serves
    weights v1 with request "x" in flight while the deploy controller
    stages v2 and the swap commits (the worker's drain-then-commit
    edge).  Invariants: "x" completes exactly once — either the
    old-version compute's post landed BEFORE the commit (the graceful
    drain) or it is fenced and the post-swap compute answers — and a
    post from the OLD weights version never lands in the results
    channel after the swap committed.  The atomic
    version-check-and-append that ``MUTATIONS['swap-unfenced']``
    breaks open.
    """
    hub = InProcHub()
    deploy_t = InProcTransport(hub)
    zombie_t = InProcTransport(hub)
    fresh_t = InProcTransport(hub)
    # Pre-schedule setup: 7 is live on committed weights v1, "x"
    # dispatched and taken (in flight on the old-version compute).
    deploy_t.set_serving_role(7, "live")
    deploy_t.set_weights(7, 1, {"step": 100})
    deploy_t.commit_weights(7, 1)
    e0 = deploy_t.read_serving(7)["epoch"]
    deploy_t.push_request(7, {"rid": "x", "epoch": e0})
    assert zombie_t.take_requests(7, 1), "setup: take must claim x"
    delivered: list = []
    seen_rids: set = set()
    outcome: dict = {}

    def collect():
        for res in deploy_t.take_results(8):
            if res.get("rid") in seen_rids:
                outcome["duplicates"] = outcome.get("duplicates", 0) + 1
                continue
            seen_rids.add(res.get("rid"))
            delivered.append(res)

    def zombie():
        # The old-version compute's post, racing the swap commit.
        ok = zombie_t.post_result(7, e0, {"rid": "x", "who": "v1"},
                                  version=1)
        outcome["zombie"] = "delivered" if ok else "fenced"

    def deployer():
        # Stage v2, commit the swap, then redispatch "x" to the
        # post-swap compute if the old-version result never arrived —
        # the controller's zero-dropped-requests obligation.
        deploy_t.set_weights(7, 2, {"step": 200})
        deploy_t.commit_weights(7, 2)
        collect()
        if not any(r.get("rid") == "x" for r in delivered):
            deploy_t.push_request(7, {"rid": "x", "epoch": e0})
            for req in fresh_t.take_requests(7, 1):
                fresh_t.post_result(7, e0, {"rid": req.get("rid"),
                                            "who": "v2"}, version=2)
        collect()

    def check():
        v = []
        leftover = [{k: x for k, x in r.items() if k != "time"}
                    for r in hub.serving_results
                    if r.get("rid") == "x"]
        whos = [r.get("who") for r in delivered if r.get("rid") == "x"]
        n = len(whos) + len(leftover) + outcome.get("duplicates", 0)
        if n != 1:
            v.append(
                f"request x completed {n} time(s) (delivered by "
                f"{whos}, {outcome.get('duplicates', 0)} duplicate(s),"
                f" leftover {leftover}) — an old-version post landed "
                "after the swap committed (want exactly once)")
        return v

    return _Scenario([("zombie", zombie), ("deployer", deployer)],
                     check)


def _build_continuous_batching() -> _Scenario:
    """The paged-KV admission race (ISSUE 19): the router thread
    admits sequences into the block pool while the engine thread
    appends decode tokens and retires finished lanes.  Pool of 3
    blocks (block_size 2); lane "c" is live holding one block;
    admitters "a" and "b" each pledge 2 blocks — either alone fits
    the 2-block headroom, both together overcommit it.  Invariants:
    the allocator's accounting identities hold at every admit edge
    and terminally (pledged never exceeds free — the reserve-on-admit
    guarantee), every admitted sequence decodes its full budget at
    contiguous slots, and every block returns to the pool.
    ``MUTATIONS['admit-unlocked']`` hoists the capacity check out of
    the critical section: two admitters park in the TOCTOU window,
    both pass against the same headroom, and the pool overcommits.
    """
    alloc = _kvb.BlockAllocator(num_blocks=3, block_size=2)
    alloc.admit("c", prompt_len=2, max_new=0)   # a live decode lane
    outcome: dict = {}

    def admitter(seq):
        def run():
            try:
                alloc.admit(seq, prompt_len=2, max_new=2)
            except _kvb.CacheExhausted:
                outcome[seq] = "exhausted"
                return
            alloc.check_invariants()   # the admit edge must be sane
            slots = [alloc.append(seq) for _ in range(2)]
            alloc.free(seq)
            outcome[seq] = slots
        return run

    def retire_c():
        # Free-on-finish returning "c"'s block while admissions race.
        alloc.free("c")

    def check():
        v = []
        try:
            alloc.check_invariants()
        except AssertionError as e:
            v.append(f"allocator invariant broken: {e}")
        st = alloc.stats()
        if st["sequences"] or st["free"] != alloc.num_blocks:
            v.append("blocks leaked past retirement: "
                     f"{st['free']}/{alloc.num_blocks} free, "
                     f"{st['sequences']} live sequence(s)")
        admitted = [s for s in ("a", "b")
                    if isinstance(outcome.get(s), list)]
        if not admitted:
            v.append("admission control starved both admitters of a "
                     f"2-block headroom: {outcome}")
        for s in admitted:
            if outcome[s] != [2, 3]:
                v.append(f"sequence {s} decoded slots {outcome[s]} "
                         "(want contiguous [2, 3] — the "
                         "reserve-on-admit guarantee)")
        return v

    return _Scenario([("admit-a", admitter("a")),
                      ("admit-b", admitter("b")),
                      ("retire-c", retire_c)], check)


# name -> {"quick": build, "full": build, "quick_max": int,
#          "full_max": int, "invariant": str}
SCENARIOS = {
    "abort_race": {
        "quick": _build_abort_race,
        "full": _build_abort_race,
        "quick_max": 2000, "full_max": 20000,
        "invariant": "abort latched exactly once with a stable "
                     "verdict under duplicate delivery",
    },
    "join_dup": {
        "quick": _build_join_dup,
        "full": _build_join_dup,
        "quick_max": 12000, "full_max": 60000,
        "invariant": "a join is never admitted twice (duplicate "
                     "admit delivery, dedup store at cap)",
    },
    "ledger_storm": {
        "quick": _build_ledger_storm,
        "full": lambda: _build_ledger_storm(appends_per_writer=3),
        "quick_max": 400, "full_max": 20000,
        "invariant": "every ledger append applied exactly once and "
                     "order-consistent with the on-disk mirror",
    },
    "dedup_inflight": {
        "quick": _build_dedup_inflight,
        "full": _build_dedup_inflight,
        "quick_max": 12000, "full_max": 60000,
        "invariant": "dedup eviction never drops an in-flight "
                     "reservation (retry must wait, not re-apply)",
    },
    "beat_read_race": {
        "quick": _build_beat_read_race,
        "full": _build_beat_read_race,
        "quick_max": 6000, "full_max": 30000,
        "invariant": "snapshot() sees a prefix-closed ledger and "
                     "non-regressing beat versions",
    },
    "epoch_fence": {
        "quick": _build_epoch_fence,
        "full": _build_epoch_fence,
        "quick_max": 500, "full_max": 5000,
        "invariant": "a drained epoch's thread never mutates hub "
                     "state past the clear",
    },
    "drain_promote": {
        "quick": _build_drain_promote,
        "full": _build_drain_promote,
        "quick_max": 3000, "full_max": 20000,
        "invariant": "a retired replica's late result is fenced and "
                     "every request delivers exactly once across the "
                     "drain/promote handoff",
    },
    "weight_swap": {
        "quick": _build_weight_swap,
        "full": _build_weight_swap,
        "quick_max": 4000, "full_max": 20000,
        "invariant": "an old-version compute's late post is fenced "
                     "at the swap commit and every request delivers "
                     "exactly once across the weight hot-swap",
    },
    "continuous_batching": {
        "quick": _build_continuous_batching,
        "full": _build_continuous_batching,
        "quick_max": 6000, "full_max": 30000,
        "invariant": "paged-KV admission check-and-bind is one "
                     "critical section: the pool never overcommits "
                     "and every admitted sequence decodes within its "
                     "reservation",
    },
}


# ---------------------------------------------------------------------------
# Mutation seeds — the known bugs the explorer must rediscover
# ---------------------------------------------------------------------------


def _evict_seen_naive(self) -> None:
    # The pre-fix TcpGangServer eviction: blind to _InFlight.
    while len(self._seen) > self._DEDUP_CAP:
        self._seen.popitem(last=False)


@contextlib.contextmanager
def _locked_epoch_unlocked(self, label: str):
    # The pre-fix InProcTransport fence: epoch checked BEFORE the
    # lock, with an explicit schedule point in the TOCTOU window so
    # the explorer can park the zombie inside it.
    _transport._sched_point(label)
    hub = self.hub
    if self._epoch is not None and self._epoch != hub.epoch:
        raise TransportError(
            f"stale transport handle (epoch {self._epoch}, hub at "
            f"{hub.epoch})")
    _transport._sched_point("hub:epoch:gap")
    with hub.lock:
        yield hub


def _post_result_unfenced(self, replica, epoch, payload, version=None):
    # The pre-fix serving fence: the poster's epoch checked BEFORE
    # the lock that appends the result, with an explicit schedule
    # point in the TOCTOU window — a retiring replica can pass the
    # stale check, park in the gap through retire_replica's epoch
    # bump, and land its zombie result after the handoff.  (The
    # weights-version fence stays correct — inside the lock — so this
    # seed breaks exactly the epoch invariant, nothing else.)
    _transport._sched_point("hub:sresults:w")
    hub = self.hub
    if int(epoch) != hub.serving_epoch.get(int(replica), 0):
        return False
    _transport._sched_point("hub:sepoch:gap")
    with hub.lock:
        if version is not None:
            wrec = hub.serving_weights.get(int(replica)) or {}
            if int(version) != int(wrec.get("version", 0)):
                return False
            payload = dict(payload, version=int(version))
        hub.serving_results.append(
            dict(payload, replica=int(replica), epoch=int(epoch)))
    return True


def _post_result_swap_unfenced(self, replica, epoch, payload,
                               version=None):
    # The pre-fix weight-swap fence: the poster's weights VERSION
    # checked BEFORE the lock that appends the result, with an
    # explicit schedule point in the TOCTOU window — an old-version
    # compute can pass the stale check, park in the gap through
    # commit_weights' version flip, and land its result after the
    # swap committed.  (The epoch fence stays correct — inside the
    # lock — so this seed breaks exactly the swap invariant.)
    _transport._sched_point("hub:sresults:w")
    hub = self.hub
    if version is not None:
        wrec = hub.serving_weights.get(int(replica)) or {}
        if int(version) != int(wrec.get("version", 0)):
            return False
    _transport._sched_point("hub:swv:gap")
    with hub.lock:
        if int(epoch) != hub.serving_epoch.get(int(replica), 0):
            return False
        if version is not None:
            payload = dict(payload, version=int(version))
        hub.serving_results.append(
            dict(payload, replica=int(replica), epoch=int(epoch)))
    return True


def _admit_unlocked(self, seq, prompt_len: int, max_new: int):
    # The pre-fix BlockAllocator.admit: the capacity check reads the
    # headroom OUTSIDE the critical section that binds the blocks,
    # with an explicit schedule point in the TOCTOU window — two
    # admitters park in the gap, both pass against the same headroom,
    # and the pool overcommits (pledged > free), breaking the
    # reserve-on-admit guarantee as an empty-pool pop mid-decode.
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    if max_new < 0:
        raise ValueError(f"max_new must be >= 0, got {max_new}")
    _coord._sched_point("kvb:admit")
    with self._lock:
        if seq in self._tables:
            raise ValueError(f"sequence {seq!r} already admitted")
        avail = len(self._free) - self._pledged
    need = _kvb.blocks_needed(prompt_len + max_new, self.block_size)
    if need > avail:
        raise _kvb.CacheExhausted(
            f"need {need} blocks, {avail} available")
    _coord._sched_point("kvb:admit:gap")
    with self._lock:
        now = _kvb.blocks_needed(prompt_len, self.block_size)
        table = [self._free.pop() for _ in range(now)]
        self._tables[seq] = table
        self._lengths[seq] = prompt_len
        self._reserved[seq] = need
        self._pledged += need - now
        return list(table)


# name -> (class, attr, broken replacement)
MUTATIONS = {
    "dedup-evict": (TcpGangServer, "_evict_seen_locked",
                    _evict_seen_naive),
    "epoch-unlocked": (InProcTransport, "_locked",
                       _locked_epoch_unlocked),
    "result-unfenced": (InProcTransport, "_do_post_result",
                        _post_result_unfenced),
    "swap-unfenced": (InProcTransport, "_do_post_result",
                      _post_result_swap_unfenced),
    "admit-unlocked": (_kvb.BlockAllocator, "admit", _admit_unlocked),
}


@contextlib.contextmanager
def apply_mutations(names):
    """Temporarily re-introduce known bugs (class-level monkeypatch),
    restoring the fixed methods on exit — the mutation-test gate's
    switch."""
    saved = []
    try:
        for name in names:
            if name not in MUTATIONS:
                raise ValueError(
                    f"unknown mutation {name!r} (have: "
                    f"{sorted(MUTATIONS)})")
            cls, attr, repl = MUTATIONS[name]
            saved.append((cls, attr, cls.__dict__[attr]))
            setattr(cls, attr, repl)
        yield
    finally:
        for cls, attr, orig in reversed(saved):
            setattr(cls, attr, orig)


# ---------------------------------------------------------------------------
# Minimization + reproducers
# ---------------------------------------------------------------------------


def _minimize(build, choices, budget: int = 60) -> list[int]:
    """Greedy schedule shrink: find the shortest failing choice
    prefix, then zero out individual non-default choices.  Every
    candidate is re-run; only still-failing candidates are kept, and
    the result is re-confirmed (falls back to the original if the
    search was non-monotonic)."""
    remaining = [budget]

    def fails(cand) -> bool:
        if remaining[0] <= 0:
            return False
        remaining[0] -= 1
        return bool(_run_schedule(build, cand).violations)

    best = list(choices)
    lo, hi = 0, len(best)
    while lo < hi:
        mid = (lo + hi) // 2
        if fails(best[:mid]):
            hi = mid
        else:
            lo = mid + 1
    cand = best[:hi]
    if fails(cand):
        best = cand
    for i in range(len(best)):
        if best[i] != 0:
            cand = best[:i] + [0] + best[i + 1:]
            if fails(cand):
                best = cand
    while best and best[-1] == 0 and fails(best[:-1]):
        best = best[:-1]
    if not fails(best):
        return list(choices)
    return best


def format_trace(trace) -> str:
    """Annotated schedule trace: step x thread x schedule point."""
    lines = [f"  {'step':>4}  {'thread':<12} schedule point"]
    for i, (name, label) in enumerate(trace):
        lines.append(f"  {i:>4}  {name:<12} {label}")
    return "\n".join(lines)


def save_reproducer(path: str, scenario: str, size: str, mutate,
                    result: _ScheduleResult) -> str:
    payload = {
        "version": 1,
        "tool": "dmlcheck-layer3",
        "scenario": scenario,
        "size": size,
        "mutate": list(mutate),
        "choices": list(result.choices),
        "violations": list(result.violations),
        "trace": [list(step) for step in result.trace],
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return path


def replay_file(path: str) -> dict:
    """Re-run the exact interleaving a reproducer recorded.  Returns
    the replay verdict dict (violations, trace, plus what the
    reproducer expected) — deterministic, so two replays of one file
    fail identically."""
    with open(path) as f:
        payload = json.load(f)
    name = payload["scenario"]
    if name not in SCENARIOS:
        raise ValueError(f"reproducer names unknown scenario {name!r}")
    size = payload.get("size", "quick")
    build = SCENARIOS[name][size]
    with apply_mutations(payload.get("mutate", ())):
        res = _run_schedule(build, payload.get("choices", ()))
    return {
        "scenario": name,
        "size": size,
        "mutate": payload.get("mutate", []),
        "violations": res.violations,
        "expected_violations": payload.get("violations", []),
        "reproduced": bool(res.violations),
        "trace": [list(step) for step in res.trace],
    }


# ---------------------------------------------------------------------------
# The layer entry point
# ---------------------------------------------------------------------------


def run_layer3(quick: bool = True, scenarios=None, mutate=(),
               repro_dir: str | None = None,
               stop_on_violation: bool = True):
    """Run the interleaving exploration; returns ``(findings, stats)``.

    ``quick``: exhaustive small configs under per-scenario schedule
    caps — deterministic, CI-sized.  Full mode scales the configs up
    and leans on POR pruning + a preemption bound + a wall-clock
    deadline per scenario.  ``mutate`` re-introduces known bugs for
    the mutation-test gate.  A violated invariant becomes one DML301
    finding (DML302 for deadlocks) carrying the minimized schedule and
    the reproducer path."""
    size = "quick" if quick else "full"
    names = list(scenarios) if scenarios else sorted(SCENARIOS)
    for name in names:
        if name not in SCENARIOS:
            raise ValueError(f"unknown scenario {name!r} (have: "
                             f"{sorted(SCENARIOS)})")
    findings: list[Finding] = []
    stats = {"size": size, "mutate": list(mutate), "scenarios": {}}
    t0 = time.perf_counter()
    with apply_mutations(mutate):
        for name in names:
            spec = SCENARIOS[name]
            build = spec[size]
            if quick:
                st = explore(build, max_schedules=spec["quick_max"],
                             stop_on_violation=stop_on_violation)
            else:
                st = explore(build, max_schedules=spec["full_max"],
                             stop_on_violation=stop_on_violation,
                             preemption_bound=3, por=True,
                             deadline_s=60.0)
            entry = {"schedules": st.schedules,
                     "seconds": round(st.seconds, 3),
                     "capped": st.capped,
                     "violations": 0}
            if st.violation is not None:
                minimized = _minimize(build, st.violation.choices)
                res = _run_schedule(build, minimized)
                if not res.violations:
                    res = st.violation   # shrink lost the bug: keep it
                entry["violations"] = len(res.violations)
                repro_path = None
                if repro_dir is not None:
                    repro_path = save_reproducer(
                        os.path.join(repro_dir, f"{name}.repro.json"),
                        name, size, mutate, res)
                    entry["reproducer"] = repro_path
                rule = "DML302" if res.deadlock else "DML301"
                head = res.violations[0]
                tail = (f"; +{len(res.violations) - 1} more"
                        if len(res.violations) > 1 else "")
                findings.append(Finding(
                    rule=rule,
                    file=f"layer3:{name}",
                    line=0,
                    message=(
                        f"invariant '{spec['invariant']}' violated: "
                        f"{head}{tail} [{st.schedules} schedule(s) "
                        f"explored; minimized to {len(res.choices)} "
                        "choice(s); reproducer: "
                        f"{repro_path or 'pass --repro-dir to emit'}"
                        "]"),
                    snippet=" -> ".join(
                        f"{t}@{l}" for t, l in res.trace[:6]),
                    layer=3,
                ))
            stats["scenarios"][name] = entry
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    return findings, stats
