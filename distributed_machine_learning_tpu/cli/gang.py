"""Local gang launcher — N coordinated workers, one restart domain.

The smallest end-to-end surface for the gang fault-tolerance stack::

    python -m distributed_machine_learning_tpu.cli.gang \
        --workers 4 --steps 12 --save-every 5 \
        --ckpt-dir /tmp/run/ckpt --gang-dir /tmp/run/gang \
        --faults kill_rank@1:7 --telemetry-dir /tmp/run/telemetry

launches ``runtime/gang_worker.py`` once per rank (each its own OS
process, lock-stepped through the beat-directory barrier, checkpointing
into its own ``<ckpt-dir>/rank<r>`` — the per-host shard layout),
supervises them with ``runtime/supervisor.py::gang_supervise``, and
prints the resilience summary.  Worker logs land under
``<gang-dir>/logs/``.

Elastic by default: a rank that is gone for good (``lose_rank@r:k``
fired, or ``--rank-restart-budget`` spent) shrinks the gang to the
survivors instead of stranding the job — down to ``--min-world``
workers (default 1; 0 disables shrinking), with the per-host batch
rescaled so the ``--global-batch`` (and the LR schedule) is preserved
and every example still consumed exactly once per step.

Elastic GROW (ISSUE 10): ``--max-world N`` lets the gang grow back —
a recovered host (``recover_rank@r:k``, or any out-of-band
``announce_join``) is readmitted at the next coordinated boundary and
the world renumbers M→N through the same ``reshard_restore`` path a
shrink uses.  ``--spares K`` runs K warm-spare workers beside the gang
(heartbeating and prefetching the newest verified checkpoint, never
training); spares are promoted at planned boundaries — filling the
world after a grow admission, or, under
``--straggler-policy replace``, replacing a persistently slow rank
(demoted to spare, with ``--replace-after`` consecutive flagged health
feeds of hysteresis).  ``--scaling-rule`` picks how (global batch, LR)
respond to a world change (``train/scaling.py``): ``pinned`` keeps
PR 5's world-invariant batch, ``linear``/``lars`` grow the batch with
the world and compensate the LR so the loss trajectory stays
continuous; ``unscaled`` is the deliberately-wrong control.

Observable by default (ISSUE 6): the gang telemetry plane lands under
``<gang-dir>/telemetry`` — supervisor counters/spans at canonical
names, each worker's stream rank-suffixed beside them — with live
straggler detection (``--straggler-multiple``/
``--straggler-consecutive``) feeding ``gang_straggler{rank}`` counters,
the ``gang_skew_ratio`` gauge, and the ``gang_health.jsonl`` advisory
ledger; the run ends with a cross-rank skew summary.  Post-mortem:
``tools/gang_status.py <gang-dir>`` and ``tools/trace_merge.py
<gang-dir>/telemetry``.  ``--no-telemetry`` turns it all off.
"""

from __future__ import annotations

import argparse
import os
import sys


def scrubbed_worker_env(repo_root: str | None = None) -> dict:
    """A worker environment safe for a fresh multi-process rendezvous:
    force the CPU platform, drop any 8-way virtual-device split (each
    worker must own exactly one device for the mesh to really span the
    process boundary), and drop any sitecustomize that pre-initializes
    jax.distributed for its own single-process session (it would swallow
    the workers' N-process rendezvous)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    keep = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))
    ]
    if repo_root and not os.path.exists(
            os.path.join(repo_root, "sitecustomize.py")):
        # Re-adding the package's own root keeps workers importable —
        # but never when that root itself carries the sitecustomize the
        # scrub exists to drop (such layouts must expose the package on
        # a clean path instead).
        keep.insert(0, repo_root)
    env["PYTHONPATH"] = os.pathsep.join(keep)
    return env


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--workers", type=int, default=4,
                    help="gang size (one process, one CPU device each)")
    ap.add_argument("--steps", type=int, default=12,
                    help="training steps each worker must complete")
    ap.add_argument("--save-every", type=int, default=5,
                    help="checkpoint every N steps (plus a final save)")
    ap.add_argument("--ckpt-dir", required=True,
                    help="shared checkpoint directory (verified saves)")
    ap.add_argument("--gang-dir", required=True,
                    help="shared coordination directory (heartbeats, "
                         "abort latch, restore-point records)")
    ap.add_argument("--global-batch", dest="global_batch", type=int,
                    default=24,
                    help="examples per global step batch; each rank "
                         "consumes its exact shard, so a shrink "
                         "rescales the per-host batch while the global "
                         "batch (and LR schedule) is preserved")
    ap.add_argument("--faults", default=None,
                    help="fault spec forwarded to every worker, e.g. "
                         "'kill_rank@1:7' or 'lose_rank@1:7' "
                         "(runtime/faults.py)")
    ap.add_argument("--max-restarts", dest="max_restarts", type=int,
                    default=3,
                    help="coordinated gang relaunches before giving up")
    ap.add_argument("--min-world", dest="min_world", type=int, default=1,
                    help="smallest gang the supervisor may shrink to "
                         "when a rank is unrecoverable (lose_rank fired "
                         "or per-rank budget spent); 0 disables "
                         "shrinking — an unrecoverable rank then fails "
                         "the job")
    ap.add_argument("--max-world", dest="max_world", type=int, default=0,
                    help="largest gang the supervisor may GROW to when "
                         "a recovered/new host announces a join "
                         "(recover_rank fault or announce_join); 0 "
                         "(default) disables growing")
    ap.add_argument("--spares", type=int, default=0,
                    help="warm-spare workers run beside the gang: they "
                         "heartbeat on the join channel and prefetch "
                         "the newest verified checkpoint but never "
                         "train; promoted at planned boundaries")
    ap.add_argument("--straggler-policy", dest="straggler_policy",
                    default="advise", choices=("advise", "replace"),
                    help="what a straggler verdict does: 'advise' "
                         "(default) only flags; 'replace' demotes the "
                         "slow rank to spare and promotes a warm spare "
                         "in its place (requires --spares >= 1)")
    ap.add_argument("--replace-after", dest="replace_after", type=int,
                    default=2,
                    help="consecutive flagged health feeds before the "
                         "replace policy acts (hysteresis: one flag "
                         "never flips the gang)")
    ap.add_argument("--scaling-rule", dest="scaling_rule",
                    default="pinned",
                    choices=("pinned", "linear", "lars", "unscaled"),
                    help="how (global batch, LR) respond to a world "
                         "change (train/scaling.py); anchored at the "
                         "launch world")
    ap.add_argument("--base-lr", dest="base_lr", type=float, default=0.5,
                    help="learning rate at the launch world (the "
                         "scaling rule's anchor)")
    ap.add_argument("--feature-dim", dest="feature_dim", type=int,
                    default=8,
                    help="toy example dimensionality (the chaos "
                         "continuity proof uses a wider dim so the "
                         "per-step loss noise is small against the "
                         "floor shifts it measures)")
    ap.add_argument("--rank-restart-budget", dest="rank_restart_budget",
                    type=int, default=None,
                    help="failures attributable to one rank before it "
                         "is declared unrecoverable (default: "
                         "unlimited; lose_rank marks a rank "
                         "unrecoverable regardless)")
    ap.add_argument("--heartbeat-interval", dest="heartbeat_interval",
                    type=float, default=0.25,
                    help="seconds between heartbeat-file writes")
    ap.add_argument("--peer-timeout", dest="peer_timeout", type=float,
                    default=15.0,
                    help="seconds without peer progress before the gang "
                         "aborts and restarts together")
    ap.add_argument("--telemetry-dir", dest="telemetry_dir", default=None,
                    help="the gang telemetry plane (default: "
                         "<gang-dir>/telemetry): supervisor metrics "
                         "under canonical names, each worker under "
                         "rank-suffixed ones (metrics.rank<r>.jsonl) — "
                         "read back by telemetry/aggregator.py, "
                         "tools/gang_status.py, tools/trace_merge.py")
    ap.add_argument("--no-telemetry", dest="no_telemetry",
                    action="store_true",
                    help="disable the default-on gang telemetry")
    ap.add_argument("--straggler-multiple", dest="straggler_multiple",
                    type=float, default=4.0,
                    help="flag a rank whose effective step time exceeds "
                         "this multiple of the gang median (advisory "
                         "detection only)")
    ap.add_argument("--straggler-consecutive",
                    dest="straggler_consecutive", type=int, default=3,
                    help="consecutive over-threshold observations "
                         "before a straggler verdict")
    ap.add_argument("--gang-transport", dest="gang_transport",
                    default="file", choices=("file", "inproc", "tcp"),
                    help="control-plane backend (runtime/transport.py): "
                         "'file' = shared-directory channels in "
                         "--gang-dir (default, on-disk format "
                         "unchanged); 'inproc' = THREAD workers over "
                         "in-memory channels — no subprocess spawn, so "
                         "64-128-rank chaos campaigns run in seconds "
                         "(durable ledgers still mirror into "
                         "--gang-dir for gang_status; workers share "
                         "ONE checkpoint dir, rank 0 saves); 'tcp' = "
                         "this launcher hosts the gang server and "
                         "workers connect with per-op timeouts, "
                         "retry+backoff, and idempotent delivery")
    ap.add_argument("--net-model", dest="net_model", default=None,
                    help="attach the digital-twin network model "
                         "(runtime/netmodel.py) to the in-proc hub "
                         "(inproc only): 'INNER[:COMPUTE_US"
                         "[:STEP_MB]]' — inner-major nodes of INNER "
                         "ranks, intra-node fast / inter-node slow; "
                         "ranks report MODELED step times (virtual "
                         "seconds, no real sleeps) while liveness "
                         "stays on the real heartbeat clock, and the "
                         "gray fault kinds (--faults "
                         "'degrade_link@SRC-DST:STEP:K,"
                         "flaky_link@SRC-DST:STEP:P,"
                         "bw_collapse@NODE:STEP:K,"
                         "restore_link@SRC-DST:STEP') mutate the "
                         "model's links")
    ap.add_argument("--tx-chaos", dest="tx_chaos", default=None,
                    help="transport-level fault injection forwarded to "
                         "tcp workers (runtime/gang_worker.py): "
                         "'partition@RANK:AFTER_OPS' severs that "
                         "original rank's channel on attempt 0 — the "
                         "connection-loss-is-peer-death chaos proof")
    args = ap.parse_args(argv)
    if args.workers < 1:
        ap.error(f"--workers must be >= 1, got {args.workers}")
    if args.peer_timeout <= 2 * args.heartbeat_interval:
        ap.error("--peer-timeout must exceed two heartbeat intervals")
    if not 0 <= args.min_world <= args.workers:
        ap.error(f"--min-world must be in [0, {args.workers}], got "
                 f"{args.min_world}")
    if args.global_batch < 1:
        ap.error(f"--global-batch must be >= 1, got {args.global_batch}")
    if args.straggler_multiple <= 1.0:
        ap.error("--straggler-multiple must be > 1 (a rank at the "
                 "median is not a straggler)")
    if args.straggler_consecutive < 1:
        ap.error("--straggler-consecutive must be >= 1")
    if args.max_world and args.max_world < args.workers:
        ap.error(f"--max-world must be >= --workers ({args.workers}) "
                 f"or 0 to disable, got {args.max_world}")
    if args.spares < 0:
        ap.error(f"--spares must be >= 0, got {args.spares}")
    if args.straggler_policy == "replace" and args.spares < 1:
        ap.error("--straggler-policy replace needs at least one warm "
                 "spare to promote (--spares >= 1)")
    if args.spares and not args.max_world \
            and args.straggler_policy != "replace":
        ap.error("--spares without a promotion path: spares can only "
                 "be promoted at a grow (--max-world) or replacement "
                 "(--straggler-policy replace) boundary")
    if args.net_model and args.gang_transport != "inproc":
        ap.error("--net-model is the in-proc hub's digital-twin seam; "
                 "use --gang-transport inproc")
    if args.replace_after < 1:
        ap.error(f"--replace-after must be >= 1, got {args.replace_after}")
    if args.tx_chaos and args.gang_transport != "tcp":
        ap.error("--tx-chaos injects at the transport send boundary, "
                 "which only the lossy tcp backend has — it would "
                 "silently never fire under "
                 f"--gang-transport {args.gang_transport}")

    from distributed_machine_learning_tpu.runtime.faults import (
        FaultEvents,
        FaultInjector,
    )
    from distributed_machine_learning_tpu.runtime.supervisor import (
        GangFailure,
        gang_supervise,
    )
    from distributed_machine_learning_tpu.utils.summary import (
        resilience_summary,
    )

    if args.faults:
        try:  # validate before spawning anything
            probe = FaultInjector.parse(args.faults,
                                        horizon=max(args.steps, 2))
        except ValueError as e:
            ap.error(f"--faults: {e}")
        bad_targets = {r for r in probe.targeted_ranks()
                       if r >= args.workers}
        if bad_targets:
            ap.error(
                f"--faults targets rank(s) {sorted(bad_targets)} but the "
                f"gang only has ranks 0..{args.workers - 1} — the fault "
                "would silently never fire"
            )

    # The gang telemetry plane is ON by default: the supervisor writes
    # canonical filenames at the root, each worker rank-suffixed ones
    # beside them — one directory, no append collisions, readable as a
    # cross-rank whole by telemetry/aggregator.py and the tools.
    telemetry = None
    tel_dir = args.telemetry_dir or os.path.join(args.gang_dir,
                                                 "telemetry")
    if not args.no_telemetry:
        from distributed_machine_learning_tpu.telemetry import (
            Telemetry,
            set_telemetry,
        )

        telemetry = Telemetry(tel_dir)
        set_telemetry(telemetry)

    def worker_cmd(rank: int, attempt: int, world: int,
                   orig_rank: int) -> list[str]:
        # Elastic signature: the supervisor passes the CURRENT world
        # size (a shrink reduces it) and the rank's original identity
        # (its checkpoint dir and consumption ledger follow it across
        # renumberings).  No fresh ports needed: the beat-directory
        # protocol is portless.
        cmd = [
            sys.executable, "-m",
            "distributed_machine_learning_tpu.runtime.gang_worker",
            "--rank", str(rank), "--world", str(world),
            "--orig-rank", str(orig_rank), "--attempt", str(attempt),
            "--gang-dir", args.gang_dir, "--ckpt-dir", args.ckpt_dir,
            "--steps", str(args.steps),
            "--save-every", str(args.save_every),
            "--global-batch", str(args.global_batch),
            "--heartbeat-interval", str(args.heartbeat_interval),
            "--peer-timeout", str(args.peer_timeout),
            # The scaling rule anchors at the LAUNCH world: relaunches
            # at other worlds re-derive (batch, lr) from this fixed
            # base point, not from whatever world they wake up in.
            "--scaling-rule", args.scaling_rule,
            "--base-world", str(args.workers),
            "--base-lr", str(args.base_lr),
            "--feature-dim", str(args.feature_dim),
        ]
        if args.faults:
            cmd += ["--faults", args.faults]
        if args.no_telemetry:
            cmd += ["--no-telemetry"]
        else:
            # Workers share ONE telemetry dir; their default instance
            # tag (rank<orig>) keeps the streams collision-safe and
            # stable across shrink renumberings.
            cmd += ["--telemetry-dir", tel_dir]
        return cmd

    def spare_cmd(orig_rank: int, attempt: int) -> list[str]:
        # A warm spare never trains: it only needs its identity, the
        # join channel, and the checkpoint root it prefetches from/into.
        return [
            sys.executable, "-m",
            "distributed_machine_learning_tpu.runtime.gang_worker",
            "--spare", "--rank", str(orig_rank),
            "--world", str(args.workers),  # unused in spare mode
            "--orig-rank", str(orig_rank), "--attempt", str(attempt),
            "--gang-dir", args.gang_dir, "--ckpt-dir", args.ckpt_dir,
            "--heartbeat-interval", str(args.heartbeat_interval),
        ]

    events = FaultEvents()
    # The scrub may drop the very PYTHONPATH entry this package was
    # imported from (a sitecustomize'd tree); re-adding the package's
    # own root keeps the workers importable everywhere.
    import distributed_machine_learning_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        _pkg.__file__
    )))

    # -- control-plane backend (ISSUE 12) -------------------------------
    server = None
    transport = None
    ckpt_dirs = [os.path.join(args.ckpt_dir, f"rank{r}")
                 for r in range(args.workers + args.spares)]
    if args.gang_transport == "tcp":
        # The launcher hosts the gang server (on a pod: rank 0 / the
        # controller); workers get its address on their argv.  The
        # supervisor talks to its OWN server hub directly — it must
        # never compete with the workers for its socket.  Durable
        # ledgers mirror into --gang-dir for post-mortem tooling.
        from distributed_machine_learning_tpu.runtime.transport import (
            TcpGangServer,
        )

        server = TcpGangServer(mirror_dir=args.gang_dir).start()
        transport = server.local_transport(events=events)
        base_worker_cmd = worker_cmd

        def worker_cmd(rank, attempt, world, orig_rank):  # noqa: F811
            cmd = base_worker_cmd(rank, attempt, world, orig_rank) + [
                "--gang-transport", "tcp", "--gang-addr", server.address,
            ]
            if args.tx_chaos:
                cmd += ["--tx-chaos", args.tx_chaos]
            return cmd

        base_spare_cmd = spare_cmd

        def spare_cmd(orig_rank, attempt):  # noqa: F811
            return base_spare_cmd(orig_rank, attempt) + [
                "--gang-transport", "tcp", "--gang-addr", server.address,
            ]
    elif args.gang_transport == "inproc":
        # Thread ranks over in-memory channels: the 64-128-rank
        # campaign mode.  One SHARED checkpoint directory (replicated
        # dp state; rank 0 saves, the commit broadcasts over the hub),
        # durable ledgers mirrored into --gang-dir so gang_status and
        # the consumption audit read the run like any file gang.
        from distributed_machine_learning_tpu.runtime.inproc_worker import (
            InprocGangConfig,
            inproc_worker_cmds,
        )
        from distributed_machine_learning_tpu.runtime.transport import (
            InProcHub,
            InProcTransport,
        )

        hub = InProcHub(mirror_dir=args.gang_dir)
        if args.net_model:
            # The digital-twin seam (round 20): workers report modeled
            # step times, rank 0 advances the virtual clock, and gray
            # faults mutate these links.
            from distributed_machine_learning_tpu.runtime.netmodel import (  # noqa: E501
                NetModel,
            )

            parts = args.net_model.split(":")
            try:
                nm_inner = int(parts[0])
                nm_compute_us = (float(parts[1]) if len(parts) > 1
                                 else 2000.0)
                nm_step_mb = float(parts[2]) if len(parts) > 2 else 4.0
                hub.netmodel = NetModel(
                    args.workers, inner=nm_inner,
                    compute_s=nm_compute_us / 1e6,
                    step_bytes=int(nm_step_mb * 2**20))
            except ValueError as e:
                ap.error(f"bad --net-model spec {args.net_model!r} "
                         f"(expected INNER[:COMPUTE_US[:STEP_MB]]): {e}")
        transport = InProcTransport(hub, events=events)
        cfg = InprocGangConfig(
            ckpt_dir=args.ckpt_dir, steps=args.steps,
            save_every=args.save_every, global_batch=args.global_batch,
            scaling_rule=args.scaling_rule, base_world=args.workers,
            base_lr=args.base_lr, feature_dim=args.feature_dim,
            heartbeat_interval=min(args.heartbeat_interval, 0.1),
            # Modeled pod gangs run hundreds of thread ranks on a few
            # cores: startup alone can exceed the thread-campaign
            # clamp, and their death detection is exit-code/model
            # driven — honor the user's timeout there.
            peer_timeout=(args.peer_timeout if args.net_model
                          else min(args.peer_timeout, 5.0)),
            faults=args.faults,
        )
        worker_cmd, spare_cmd = inproc_worker_cmds(cfg, hub)
        ckpt_dirs = args.ckpt_dir  # shared: one dir for the whole gang
        os.makedirs(args.ckpt_dir, exist_ok=True)

    try:
        final_codes = gang_supervise(
            worker_cmd, args.workers, args.gang_dir,
            # Per-rank layout: spares hold original ids just past the
            # launch world and prefetch into their own rank<orig> dirs,
            # so the dir list covers workers AND spares.  The in-proc
            # campaign mode passes ONE shared directory instead.
            ckpt_dirs=ckpt_dirs,
            max_restarts=args.max_restarts,
            rank_restart_budget=args.rank_restart_budget,
            min_world=args.min_world if args.min_world > 0 else None,
            max_world=args.max_world if args.max_world > 0 else None,
            spares=args.spares, spare_cmd=spare_cmd,
            straggler_policy=args.straggler_policy,
            replace_after=args.replace_after,
            events=events, env=scrubbed_worker_env(pkg_root),
            log_dir=os.path.join(args.gang_dir, "logs"),
            straggler_multiple=args.straggler_multiple,
            straggler_consecutive=args.straggler_consecutive,
            transport=transport,
        )
    except GangFailure as e:
        print(f"gang failed: {e}", file=sys.stderr, flush=True)
        print(resilience_summary(events), flush=True)
        return 1
    finally:
        if server is not None:
            server.stop()
        if telemetry is not None:
            telemetry.close()
    final_world = len(final_codes)
    print(resilience_summary(events), flush=True)
    print(f"gang of {args.workers} finished {args.steps} steps at "
          f"world size {final_world} ({events.gang_restarts} coordinated "
          f"restart(s), {events.gang_shrinks} shrink(s), "
          f"{events.gang_grows} grow(s), {events.spare_promotions} "
          f"spare promotion(s))", flush=True)
    if not args.no_telemetry:
        _print_gang_rollup(tel_dir, args)
    return 0


def _print_gang_rollup(tel_dir: str, args) -> None:
    """Post-run cross-rank summary from the per-rank streams — the
    one-line answer to "was anyone slow?" plus pointers to the deeper
    tools.  Best-effort: a rollup failure must never fail the run it
    summarizes."""
    try:
        from distributed_machine_learning_tpu.telemetry.aggregator import (
            aggregate_gang_metrics,
        )

        rollup = aggregate_gang_metrics(
            tel_dir, multiple=args.straggler_multiple,
            consecutive=args.straggler_consecutive,
        )
    except Exception as e:  # diagnostics-only path
        print(f"[gang] cross-rank rollup unavailable: {e}", flush=True)
        return
    if not rollup.ranks:
        return
    print(f"cross-rank step-time skew (slowest/median): "
          f"p95 {rollup.skew['p95']:.2f}x  max {rollup.skew['max']:.2f}x"
          f" over {len(rollup.steps)} step(s), "
          f"{len(rollup.ranks)} rank stream(s)", flush=True)
    for v in rollup.stragglers:
        print(f"  straggler (offline): rank {v['rank']} at step "
              f"{v['step']} ({v['ratio']:.1f}x median)", flush=True)
    print(f"inspect: python tools/gang_status.py {args.gang_dir}  |  "
          f"python tools/trace_merge.py {tel_dir}", flush=True)


if __name__ == "__main__":
    raise SystemExit(main())
