"""Pod-scale digital-twin chaos campaigns (ISSUE 20).

The in-proc gang at 64-128 ranks (ISSUE 12) proved the control plane;
what it could not exercise was *gray* failure — links that get slow,
flaky, or starved without anyone dying, the failure mode straggler
detection exists for.  With the modeled network
(``runtime/netmodel.py``) attached to the hub, thread ranks report
MODELED step times (virtual seconds over per-link latency/bandwidth)
while liveness keeps riding the real heartbeat clock, so:

- a **512-rank gang** with one gray-degraded link sees exactly that
  link's source rank flagged by the straggler detector and swapped for
  a warm spare under ``straggler_policy="replace"`` — world unchanged,
  loss-continuous, exactly-once — and a hard ``kill_rank`` later in
  the same run proves the fault LEDGER keeps the gray injection
  exactly-once across a full gang relaunch;
- a **1024-rank** beat-batching sanity run: one transport snapshot
  returns all 1024 beats, the sampler feeds the detector pure modeled
  times (no wall-clock age pollution — 1024 threads share one CI
  core), and only the gray ranks flag;
- the **serving fleet over the modeled network**: two replicas' links
  degrade, the PR 6 detector evicts both, warm spares take their
  slots, and the post-eviction p99 returns to the healthy baseline.

Campaign wall-clock caps are asserted IN the tests (the ISSUE 12
convention): a pod twin that stops finishing in tier-1 time must fail
loudly, not eat the suite budget.
"""

from __future__ import annotations

import collections
import concurrent.futures
import os
import time

import numpy as np
import pytest

from distributed_machine_learning_tpu.runtime.faults import (
    FaultEvents,
    FaultInjector,
)
from distributed_machine_learning_tpu.runtime.inproc_worker import (
    InprocGangConfig,
    inproc_worker_cmds,
)
from distributed_machine_learning_tpu.runtime.netmodel import NetModel
from distributed_machine_learning_tpu.runtime.serving import (
    ServingConfig,
    ServingRouter,
)
from distributed_machine_learning_tpu.runtime.supervisor import (
    gang_supervise,
)
from distributed_machine_learning_tpu.runtime.transport import (
    InProcHub,
    InProcTransport,
)
from distributed_machine_learning_tpu.telemetry.aggregator import (
    HeartbeatSampler,
    StragglerDetector,
)

from tests.test_chaos_campaign import (
    _assert_exactly_once_chained,
    _final_losses,
    _gang_status_tool,
)

POD_512_BUDGET_S = 150.0
POD_1024_BUDGET_S = 180.0


# ---------------------------------------------------------------------------
# 512-rank gray campaign: degrade -> flag -> replace, ledger-latched
# across a later hard relaunch
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_pod_512_gray_link_flagged_and_replaced(tmp_path):
    """The flagship twin campaign: 512 thread ranks over a modeled
    64-node pod (inner=8).  ``degrade_link@100-101`` multiplies one
    intra-node link's latency 200x at step 2; only rank 100's modeled
    step inflates, the detector flags it within the replace
    hysteresis, and the supervisor demotes it for a warm spare at a
    planned boundary — world stays 512 throughout.  A ``kill_rank`` at
    step 6 then forces a full coordinated relaunch, proving the gray
    fault's ledger latch: the relaunched attempt replays the spec but
    never re-fires the consumed link fault."""
    world = 512
    hub = InProcHub(mirror_dir=os.path.join(str(tmp_path), "gang"))
    hub.netmodel = NetModel(world, inner=8, compute_s=0.002,
                            step_bytes=4 << 20)
    tx = InProcTransport(hub)
    cfg = InprocGangConfig(
        ckpt_dir=os.path.join(str(tmp_path), "ckpt"), steps=8,
        save_every=4, global_batch=world, scaling_rule="pinned",
        base_world=world, feature_dim=32, heartbeat_interval=0.05,
        # 512 threads on one core need tens of seconds just to all
        # start beating; the gray campaign's death detection is
        # exit-code- and model-driven, not timeout-driven.
        peer_timeout=60.0,
        faults="degrade_link@100-101:2:200,kill_rank@7:6",
    )
    os.makedirs(cfg.ckpt_dir, exist_ok=True)
    worker_cmd, spare_cmd = inproc_worker_cmds(cfg, hub)
    events = FaultEvents()
    start = time.monotonic()
    codes = gang_supervise(
        worker_cmd, world, None, ckpt_dirs=cfg.ckpt_dir, events=events,
        spares=2, spare_cmd=spare_cmd, grace_s=3.0, transport=tx,
        max_restarts=4, straggler_policy="replace", replace_after=2,
        straggler_multiple=4.0, straggler_consecutive=3,
    )
    elapsed = time.monotonic() - start
    assert elapsed < POD_512_BUDGET_S, (
        f"512-rank twin campaign took {elapsed:.1f}s — the pod twin "
        "stopped being tier-1 fast"
    )
    # World unchanged: every one of the 512 slots finished clean.
    assert len(codes) == world and set(codes) == {0}
    assert events.spare_demotions == 1
    assert events.spare_promotions == 1
    assert events.gang_restarts >= 1      # the kill_rank relaunch
    assert events.gang_shrinks == 0 and events.gang_grows == 0

    health = tx.read_health_events()
    kinds = collections.Counter(e["kind"] for e in health)
    assert kinds["replace"] == 1
    # The demoted rank is exactly the gray link's source.
    assert [e["rank"] for e in health if e["kind"] == "demote"] == [100]
    stragglers = [e for e in health if e["kind"] == "straggler"]
    assert stragglers and all(e["rank"] == 100 for e in stragglers), (
        "a rank off the gray link was flagged — modeled attribution "
        "leaked wall-clock time"
    )
    degraded = [e for e in health if e["kind"] == "link_degraded"]
    assert len(degraded) == 1, (
        "link_degraded recorded more than once — the gray fault "
        "re-fired across the relaunch"
    )
    assert degraded[0]["src"] == 100 and degraded[0]["dst"] == 101
    assert degraded[0]["latency_mult"] == 200.0
    assert degraded[0]["axis"] == "inner"

    # Ledger latch: one firing per fault, ever — including across the
    # kill_rank relaunch that replayed the whole spec.
    fired = collections.Counter(
        e["kind"] for e in tx.read_fault_entries())
    assert fired["degrade_link"] == 1 and fired["kill_rank"] == 1

    # The model keeps the physics: the link is STILL degraded after
    # the campaign (restore_link was never injected) and virtual time
    # advanced without any real sleeps.
    links = hub.netmodel.degraded_links()
    assert [(r["src"], r["dst"]) for r in links] == [(100, 101)]
    assert hub.netmodel.clock.now() > 0.0

    # The ops view: tools/gang_status.py replays the mirrored health
    # ledger into a degraded-link table — link, axis, effective
    # modeled latency/bandwidth, and the fault spec that put it there.
    tool = _gang_status_tool()
    gang_dir = os.path.join(str(tmp_path), "gang")
    status = tool.collect(gang_dir, os.path.join(gang_dir, "telemetry"))
    assert [(e["src"], e["dst"]) for e in status["degraded_links"]] \
        == [(100, 101)]
    text = tool.render(status)
    assert "Modeled network: degraded links" in text
    assert "degrade_link@100-101:2:200" in text

    # Exactly-once consumption chained across the replace AND the
    # relaunch, at world 512 for every step.
    rows = tx.read_consumed()
    worlds = _assert_exactly_once_chained(rows, cfg.steps)
    assert set(worlds.values()) == {world}

    # Loss continuity: pinned rule, world unchanged => the replicated
    # trajectory starts at the optimum (w=0) and settles onto the
    # world-invariant stationary floor ``lr/(2-lr)·dim/B``.  Neither
    # the replace boundary (step 4) nor the kill relaunch (step 6) may
    # kick a step off that floor: every post-warmup loss stays inside
    # a 4x band around the run's own median (chi-square noise at
    # dim 32 is ~25% — a restart discontinuity would be a multiple).
    losses = _final_losses(rows)
    assert sorted(losses) == list(range(cfg.steps))
    tail = [losses[s] for s in range(1, cfg.steps)]
    med = sorted(tail)[len(tail) // 2]
    for s in range(1, cfg.steps):
        assert med / 4 < losses[s] < 4 * med, (
            f"loss discontinuity at step {s}: {losses[s]} vs "
            f"stationary median {med}"
        )


# ---------------------------------------------------------------------------
# 1024-rank heartbeat/beat-batching sanity
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_pod_1024_beat_batching_and_modeled_attribution():
    """1024 ranks' heartbeats through one hub: a 32-thread pool
    publishes all beats (the batched-publisher shape a real pod's
    per-host agents have), ONE transport snapshot returns all 1024,
    and the sampler->detector chain flags exactly the two gray ranks —
    from pure modeled times, with zero wall-clock age pollution even
    though 1024 "ranks" share one CI core."""
    world, inner = 1024, 8
    start = time.monotonic()
    nm = NetModel(world, inner=inner, compute_s=0.002,
                  step_bytes=4 << 20)
    nm.degrade_link(100, 101, 500.0)
    nm.degrade_link(900, 901, 500.0)
    hub = InProcHub()
    tx = InProcTransport(hub)

    def publish(block: int, seq: int, step: int) -> None:
        btx = InProcTransport(hub)
        for rank in range(block * 32, (block + 1) * 32):
            btx.publish_beat(rank, {
                "rank": rank, "seq": seq, "step": step, "beat_age": 0.0,
                "suspended": False, "done": False, "time": time.time(),
                "metrics": {"step_time_s": nm.step_time(rank),
                            "steps_timed": 1, "phases": {},
                            "modeled": True},
            })

    sampler = HeartbeatSampler()
    detector = StragglerDetector(multiple=4.0, consecutive=3)
    with concurrent.futures.ThreadPoolExecutor(32) as pool:
        for seq in range(3):  # three observation rounds
            list(pool.map(lambda b: publish(b, seq, seq + 1),
                          range(world // 32)))
            beats = tx.read_beat_payloads()
            assert len(beats) == world  # one batched read, whole pod
            samples = sampler.sample(None, beats=beats)
            feed = {r: s.eff_step_time_s for r, s in samples.items()}
            # Modeled attribution: the effective time IS the modeled
            # time, bit-exact — never inflated by how long the busy CI
            # core took to schedule the publisher threads.
            for r, s in samples.items():
                assert s.eff_step_time_s == nm.step_time(r)
            detector.update(feed)
    assert detector.flagged == {100, 900}
    nm.clock.advance(max(nm.step_time(r) for r in range(world)))
    assert nm.clock.now() > 0.0

    # The pod-scale cadence and barrier seams: the poll interval
    # stretches with the beat table and the copy-free barrier probe
    # answers directly against the hub.
    assert tx.barrier_poll_s() == pytest.approx(0.002 * world / 128)
    assert tx.barrier_ready(1, 0, world)
    tx.publish_beat(777, {"rank": 777, "seq": 99, "step": 0,
                          "done": False})
    assert not tx.barrier_ready(1, 0, world)

    elapsed = time.monotonic() - start
    assert elapsed < POD_1024_BUDGET_S, (
        f"1024-rank sanity run took {elapsed:.1f}s"
    )


# ---------------------------------------------------------------------------
# Serving fleet over the modeled network
# ---------------------------------------------------------------------------


def _p99(values: list[float]) -> float:
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(0.99 * (len(ordered) - 1)))]


@pytest.mark.faultinject
def test_serving_fleet_gray_degrade_evicts_and_p99_recovers():
    """Two replicas' modeled links degrade mid-load: their ``computed``
    stage deltas (the detector feed since ISSUE 17) inflate 10x+, the
    detector evicts both, warm spares take their slots, and the next
    wave's p99 is back at the healthy baseline — the serving-tier
    statement of the gray-failure loop, with every latency a modeled
    number (no sleeps anywhere)."""
    nm = NetModel(8, inner=1, compute_s=0.02, step_bytes=1 << 20)
    hub = InProcHub()
    tx = InProcTransport(hub)
    events = FaultEvents()
    router = ServingRouter(
        InProcTransport(hub),
        ServingConfig(replicas=6, replica_timeout_s=60.0),
        events=events)
    for rank in range(8):
        tx.announce_join(rank, {"rank": rank, "spare": True,
                                "kind": "serving", "time": time.time()})
    router.pump()
    assert sorted(router._replicas) == [0, 1, 2, 3, 4, 5]

    def serve_wave(batches_per_replica: int) -> dict[int, list[float]]:
        """Dispatch one wave — enough requests that EVERY replica
        receives work (micro_batch per replica per batch round) — and
        fabricate completions whose compute interval is each replica's
        MODELED step time."""
        latencies: dict[int, list[float]] = collections.defaultdict(list)
        n = (batches_per_replica * len(router._replicas)
             * router.cfg.micro_batch)
        for _ in range(n):
            router.submit([1, 2])
        router.pump()
        for rank in list(router._replicas):
            for req in tx.take_requests(rank, 64):
                dt = nm.step_time(rank)
                req["events"].append({
                    "stage": "computed", "by": f"replica{rank}",
                    "dt": dt})
                assert tx.post_result(rank, req["epoch"], {
                    "rid": req["rid"], "output": req["prompt"],
                    "events": req["events"]})
                latencies[rank].append(dt)
        return latencies

    healthy = serve_wave(2)
    router.pump()
    base_p99 = _p99([v for vs in healthy.values() for v in vs])

    # Gray-degrade the links under replicas 2 and 5 (their outgoing
    # ring links): only those two replicas' modeled service inflates.
    nm.degrade_link(2, 3, 5000.0)
    nm.degrade_link(5, 6, 5000.0)
    degraded = serve_wave(2)
    for _ in range(5):  # collect + consecutive judgments
        router.pump()
    assert router.evictions == 2
    assert events.replica_evictions == 2
    assert 2 not in router._replicas and 5 not in router._replicas
    assert 6 in router._replicas and 7 in router._replicas
    assert tx.read_serving(2)["role"] == "spare"
    assert max(degraded[2]) > 10.0 * base_p99  # the gray signal

    # Post-eviction: the fleet's p99 is back at baseline — the
    # degraded links still exist in the model, but nothing routes over
    # them any more.
    recovered = serve_wave(2)
    router.pump()
    rec_p99 = _p99([v for vs in recovered.values() for v in vs])
    assert rec_p99 < 2.0 * base_p99, (
        f"post-eviction p99 {rec_p99:.4f}s never recovered "
        f"(healthy baseline {base_p99:.4f}s)"
    )
    evict = [e for e in tx.read_health_events()
             if e.get("kind") == "serve_evict"]
    assert sorted(e["rank"] for e in evict) == [2, 5]
    assert all("straggler" in e["why"] for e in evict)


# ---------------------------------------------------------------------------
# Determinism and the ledger latch, unit form
# ---------------------------------------------------------------------------


def test_gray_trajectory_is_deterministic_per_seed(tmp_path):
    """Same spec + same seed => the same firing steps and the same
    final link state, run twice from scratch.  The flaky model is an
    expected-value factor (no RNG) and randomized ``?`` steps derive
    from the seed alone, so the whole trajectory is a pure function of
    (spec, seed)."""
    spec = "degrade_link@3-4:?:50,flaky_link@0-1:?:0.5,bw_collapse@1:?:8"

    def run(seed: int):
        inj = FaultInjector.from_flags(spec, seed=seed, horizon=8,
                                       rank=0)
        inj.current_rank = 0
        nm = NetModel(8, inner=4, compute_s=0.001)
        inj.netmodel = nm
        fired_at: list[tuple[str, int]] = []
        for f in inj._faults:
            fired_at.append((f.kind, f.at))
        list(inj.wrap_batches(range(8), FaultEvents()))
        links = [(r["src"], r["dst"], r["latency_mult"], r["flaky_p"],
                  r["bw_div"]) for r in nm.degraded_links()]
        return fired_at, links

    assert run(7) == run(7)
    assert run(11) == run(11)


def test_gray_fault_ledger_latches_across_injector_relaunch(tmp_path):
    """The relaunch contract at unit scale: once a link fault's firing
    is in the ledger, a FRESH injector parsing the same spec replays
    it as consumed — the model is mutated exactly once, ever."""
    from distributed_machine_learning_tpu.runtime.faults import (
        FAULT_LEDGER_FILE,
    )

    ledger = os.path.join(str(tmp_path), FAULT_LEDGER_FILE)
    nm = NetModel(8, inner=4, compute_s=0.001)
    inj = FaultInjector.parse("degrade_link@3-4:2:50", rank=3)
    inj.current_rank = 3
    inj.netmodel = nm
    inj.attach_ledger(ledger)
    ev1 = FaultEvents()
    list(inj.wrap_batches(range(6), ev1))
    assert ev1.link_degradations == 1
    assert nm.link_params(3, 4)["latency_mult"] == 50.0

    # Relaunch: new injector, same spec, same ledger.  The fault reads
    # as consumed; the (hub-scoped, still-degraded) model is not
    # touched again.
    nm.restore_link(3, 4)  # sentinel: a re-fire would re-degrade
    fresh = FaultInjector.parse("degrade_link@3-4:2:50", rank=3)
    fresh.current_rank = 3
    fresh.netmodel = nm
    fresh.attach_ledger(ledger)
    assert fresh.pending() == []
    ev2 = FaultEvents()
    assert list(fresh.wrap_batches(range(6), ev2)) == list(range(6))
    assert ev2.link_degradations == 0
    assert nm.link_params(3, 4)["latency_mult"] == 1.0  # untouched

    # And the latch is GANG-WIDE: any other rank's injector sees it
    # consumed too (a link fault names its endpoints, not the local
    # process).
    other = FaultInjector.parse("degrade_link@3-4:2:50", rank=6)
    other.current_rank = 6
    other.netmodel = nm
    other.attach_ledger(ledger)
    assert other.pending() == []
