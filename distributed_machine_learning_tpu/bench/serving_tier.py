"""Serving-tier A/B: continuous batching vs the batch-static path
(ISSUE 19).

The question this bench answers with numbers: what do the paged
KV-cache allocator + iteration-level scheduler
(``inference/continuous.py``) buy over the batch-static
``make_serving_step`` dispatch loop, per offered load?  The
batch-static path loses on two axes the engine was built to remove:

* **padding**: every request in a dispatch decodes the GLOBAL
  ``max_new`` cap even when its own budget is a quarter of it — the
  compute for the padded tail is pure waste;
* **head-of-line**: a micro-batch is grouped by prompt length and each
  group runs as one full-length program, serially; a ragged 4-batch
  can cost four whole scans, and nothing new starts until the whole
  dispatch returns.  The engine retires per sequence, backfills the
  freed lane the same step, and advances mixed lengths in ONE
  dispatch.

Method: a **virtual-clock discrete-event simulation** — no sleeps.
Seeded Poisson arrivals land on a virtual clock T; T advances by the
*measured wall time of each real compute call* (an engine ``step()``
or a batch-static dispatch) and jumps to the next arrival when idle.
A request's e2e is completion-T minus arrival-T, so queueing physics
(waits, HOL, backfill) are exact while the compute costs are real
measured numbers.  Both systems serve the identical seeded workload:
one replica, greedy decoding, the same micro width (``max_lanes`` ==
``micro_batch``), no EOS (raggedness comes from per-request
``max_new`` budgets, which the engine honors natively and the
batch-static path must pad to the cap).  Compile costs are paid
before the timed pass for both sides (every (batch, length) shape the
sweep can hit is pre-warmed).

Throughput counts USEFUL tokens only — the tokens a request asked
for — so the baseline's padded tail is counted as the waste it is.

Run::

    python -m distributed_machine_learning_tpu.bench.serving_tier \
        --rates 6,16,48 --requests 80 --out BENCH_r19_serving.json
"""

from __future__ import annotations

import argparse
import json
import random
import time

PROMPT_LENS = (4, 8, 12, 16)
BUDGETS = (4, 8, 16, 48)


def make_model(d_model: int = 320, n_layers: int = 4, n_heads: int = 8,
               n_kv_heads: int = 2, vocab: int = 128):
    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.train.lm_step import (
        init_lm_state,
    )

    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, n_kv_heads=n_kv_heads,
    )
    params = init_lm_state(model).params
    return model, params


def make_workload(n_requests: int, rate_rps: float, seed: int,
                  prompt_lens=PROMPT_LENS, budgets=BUDGETS,
                  vocab: int = 128):
    """Seeded Poisson arrivals with ragged prompts AND ragged decode
    budgets.  Returns arrival-time-sorted request dicts."""
    rng = random.Random(seed)
    t = 0.0
    out = []
    for i in range(n_requests):
        t += rng.expovariate(rate_rps)
        lp = rng.choice(prompt_lens)
        out.append({
            "rid": f"q{i:03d}",
            "t_arr": t,
            "prompt": [rng.randrange(1, vocab) for _ in range(lp)],
            "max_new": rng.choice(budgets),
        })
    return out


def _quantiles(values):
    xs = sorted(values)

    def q(p):
        if not xs:
            return 0.0
        idx = min(len(xs) - 1, max(0, int(round(p * (len(xs) - 1)))))
        return xs[idx]

    return {"p50_e2e_s": q(0.50), "p95_e2e_s": q(0.95),
            "p99_e2e_s": q(0.99), "max_e2e_s": xs[-1] if xs else 0.0}


def build_engine(model, params, *, max_lanes: int,
                 prompt_lens=PROMPT_LENS, budgets=BUDGETS,
                 block_size: int = 8, num_blocks: int = 64):
    """One warmed engine, reused across the whole rate sweep so XLA
    compiles (per-lever decode, per-prompt-length prefill) are paid
    exactly once, outside every timed pass."""
    from distributed_machine_learning_tpu.inference.continuous import (
        ContinuousEngine,
        EngineConfig,
    )
    from distributed_machine_learning_tpu.runtime.scheduler import (
        LATENCY,
    )

    cfg = EngineConfig(
        max_lanes=max_lanes, block_size=block_size,
        num_blocks=num_blocks,
        max_len=max(prompt_lens) + max(budgets),
        max_new=max(budgets), levers=(LATENCY,),
    )
    engine = ContinuousEngine(model, params, cfg)
    engine.warmup(prompt_lens=sorted(set(prompt_lens)))
    return engine


def simulate_engine(engine, workload):
    """Continuous-batching side: arrivals with ``t_arr <= T`` submit,
    each real ``engine.step()`` advances T by its measured wall time,
    retirements complete at the post-step T."""
    arrivals = {r["rid"]: r["t_arr"] for r in workload}
    clock = 0.0
    nxt = 0
    e2e: dict = {}
    steps = 0
    while len(e2e) < len(workload):
        while nxt < len(workload) and workload[nxt]["t_arr"] <= clock:
            r = workload[nxt]
            engine.submit(r["rid"], r["prompt"], max_new=r["max_new"])
            nxt += 1
        if not engine.has_work():
            clock = workload[nxt]["t_arr"]
            continue
        t0 = time.perf_counter()
        done = engine.step()
        clock += time.perf_counter() - t0
        steps += 1
        for d in done:
            e2e[d["rid"]] = clock - arrivals[d["rid"]]
    engine.allocator.check_invariants()
    useful = sum(r["max_new"] for r in workload)
    return {"e2e": e2e, "makespan_s": clock, "useful_tokens": useful,
            "dispatches": steps}


def build_baseline(model, params, *, micro_batch: int,
                   prompt_lens=PROMPT_LENS, budgets=BUDGETS):
    """The batch-static step callable, with every (group size, prompt
    length) program the sweep can hit pre-warmed so timed dispatches
    measure decode, not XLA."""
    from distributed_machine_learning_tpu.inference.generate import (
        make_serving_step,
    )

    cap = max(budgets)
    step = make_serving_step(model, params, cap)
    for lp in sorted(set(prompt_lens)):
        for g in range(1, micro_batch + 1):
            step([[1] * lp] * g)
    return step, cap


def simulate_baseline(step, cap, workload, *, micro_batch: int):
    """Batch-static side: the router loop ``serving_worker`` drives —
    pull up to ``micro_batch`` queued arrivals, run ONE
    ``make_serving_step`` dispatch (grouped by prompt length, every
    row decoding the global cap), the whole batch completes when the
    dispatch returns."""
    clock = 0.0
    nxt = 0
    queue: list = []
    e2e: dict = {}
    dispatches = 0
    while len(e2e) < len(workload):
        while nxt < len(workload) and workload[nxt]["t_arr"] <= clock:
            queue.append(workload[nxt])
            nxt += 1
        if not queue:
            clock = workload[nxt]["t_arr"]
            continue
        batch = queue[:micro_batch]
        del queue[:micro_batch]
        t0 = time.perf_counter()
        outs = step([r["prompt"] for r in batch])
        clock += time.perf_counter() - t0
        dispatches += 1
        for r, tokens in zip(batch, outs):
            # Delivery truncates the padded tail to the request's own
            # budget — the compute for it was still paid above.
            assert len(tokens) == len(r["prompt"]) + cap
            e2e[r["rid"]] = clock - r["t_arr"]
    useful = sum(r["max_new"] for r in workload)
    return {"e2e": e2e, "makespan_s": clock, "useful_tokens": useful,
            "dispatches": dispatches}


def run_sweep(rates, n_requests: int, seed: int = 0, *, width: int = 4,
              model=None, params=None, prompt_lens=PROMPT_LENS,
              budgets=BUDGETS, num_blocks: int = 64,
              modeled_network: bool = False):
    """One row per (rate, system), rates ascending.  The engine rows
    carry the head-to-head verdicts the acceptance gate reads.  The
    same seed drives every rate, so the request mix (prompts, budgets)
    is identical across the sweep and only the arrival spacing moves."""
    if model is None:
        model, params = make_model()
    engine = build_engine(model, params, max_lanes=width,
                          prompt_lens=prompt_lens, budgets=budgets,
                          num_blocks=num_blocks)
    step, cap = build_baseline(model, params, micro_batch=width,
                               prompt_lens=prompt_lens, budgets=budgets)
    rows = []
    for rate in sorted(rates):
        wl = make_workload(n_requests, rate, seed,
                           prompt_lens=prompt_lens, budgets=budgets,
                           vocab=model.vocab_size)
        base = simulate_baseline(step, cap, wl, micro_batch=width)
        eng = simulate_engine(engine, wl)
        for system, res in (("batch_static", base), ("engine", eng)):
            row = {
                "bench": "serving_tier",
                "system": system,
                "rate_rps": rate,
                "n_requests": n_requests,
                "width": width,
                "seed": seed,
                "useful_tokens": res["useful_tokens"],
                "tokens_per_sec": round(
                    res["useful_tokens"] / res["makespan_s"], 1),
                "makespan_s": round(res["makespan_s"], 4),
                "dispatches": res["dispatches"],
            }
            row.update({k: round(v, 4) for k, v in
                        _quantiles(list(res["e2e"].values())).items()})
            if modeled_network:
                # Router<->replica transit over the modeled inter-node
                # link (round 20): one round trip per dispatch (the
                # per-hop overhead both directions) plus the token
                # payload — prompts out, completions back — priced at
                # the calibrated outer bandwidth.  Reported NEXT TO the
                # measured numbers, never folded into the simulation:
                # the column is what a pod adds on top of the CPU
                # compute the rows measured.
                from distributed_machine_learning_tpu.ops.topology import (  # noqa: E501
                    DEFAULT_LINK_MODEL,
                )

                link = DEFAULT_LINK_MODEL
                payload = sum(
                    (len(r["prompt"]) + r["max_new"]) * 4 for r in wl)
                net_s = (res["dispatches"] * 2 * link.outer_overhead_s
                         + 2 * payload / link.outer_bytes_per_s)
                row["modeled_net_s"] = round(net_s, 6)
                row["tokens_per_sec_modeled_pod"] = round(
                    res["useful_tokens"]
                    / (res["makespan_s"] + net_s), 1)
            rows.append(row)
            print(json.dumps(row), flush=True)
        erow, brow = rows[-1], rows[-2]
        erow["engine_wins_tokens_per_sec"] = bool(
            erow["tokens_per_sec"] > brow["tokens_per_sec"])
        erow["engine_wins_p95_e2e"] = bool(
            erow["p95_e2e_s"] < brow["p95_e2e_s"])
    return rows


def acceptance(rows) -> dict:
    """The r19 gate: the engine must beat batch-static on useful
    tokens/sec at the HIGHEST offered load and on p95 e2e at the
    LOWEST."""
    engine = [r for r in rows if r["system"] == "engine"]
    lo = min(engine, key=lambda r: r["rate_rps"])
    hi = max(engine, key=lambda r: r["rate_rps"])
    return {
        "bench": "serving_tier_acceptance",
        "highest_rate_rps": hi["rate_rps"],
        "engine_beats_tokens_per_sec_at_highest_load":
            hi["engine_wins_tokens_per_sec"],
        "lowest_rate_rps": lo["rate_rps"],
        "engine_beats_p95_e2e_at_lowest_load":
            lo["engine_wins_p95_e2e"],
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rates", default="6,16,48",
                   help="offered loads, requests/sec (ascending)")
    p.add_argument("--requests", default=80, type=int)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--width", default=4, type=int,
                   help="micro_batch == max_lanes")
    p.add_argument("--d-model", dest="d_model", default=320, type=int)
    p.add_argument("--n-layers", dest="n_layers", default=4, type=int)
    p.add_argument("--modeled-network", action="store_true",
                   help="add modeled_net_s / tokens_per_sec_modeled_pod "
                        "columns: router<->replica transit priced on "
                        "the calibrated inter-node LinkModel next to "
                        "the measured CPU numbers (round 20)")
    p.add_argument("--out", default=None,
                   help="write the row list as JSON (BENCH idiom)")
    args = p.parse_args()
    rates = [float(r) for r in args.rates.split(",")]
    model, params = make_model(d_model=args.d_model,
                               n_layers=args.n_layers)
    rows = run_sweep(rates, args.requests, args.seed, width=args.width,
                     model=model, params=params,
                     modeled_network=args.modeled_network)
    verdict = acceptance(rows)
    rows.append(verdict)
    print(json.dumps(verdict), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=2)
            f.write("\n")


if __name__ == "__main__":
    main()
