"""Per-iteration timing harness.

Reproduces the reference's measurement protocol (``part1/main.py:36,53-58``):
wall-clock per iteration, iteration 0 excluded as warm-up, totals and the
average over the remaining iterations printed at the end.  On TPU the
warm-up iteration is where XLA compilation lands, so excluding iteration 0
is exactly the right protocol here too — but the caller must block on the
device result (``jax.block_until_ready``) before stopping the clock, since
JAX dispatch is asynchronous (unlike the reference's synchronous CPU torch).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Sequence


def percentile(times: Sequence[float], q: float) -> float:
    """Exact ``q``-quantile (``q`` in [0, 1]) by linear interpolation
    between order statistics (numpy's default method, stdlib-only so the
    bench/tools layer can share it without dependencies)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"q must be in [0, 1], got {q}")
    if not times:
        return 0.0
    xs = sorted(times)
    rank = q * (len(xs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (rank - lo) * (xs[hi] - xs[lo])


def percentile_stats(times: Sequence[float]) -> dict:
    """{p50, p95, p99, max} of a sample — the tail-latency block every
    timing surface (timer summary, bench result dicts) shares, because a
    mean hides exactly the straggler steps production debugging needs
    (ISSUE 2; arxiv 1811.05233's per-phase accounting)."""
    return {
        "p50": percentile(times, 0.50),
        "p95": percentile(times, 0.95),
        "p99": percentile(times, 0.99),
        "max": max(times) if times else 0.0,
    }


@dataclass
class IterationTimer:
    """Accumulates per-iteration wall-clock, excluding `skip_first` iters.

    The reference runs 40 iterations and divides total by 39
    (``part1/main.py:53-58``): iteration 0 is measured but not accumulated.
    """

    skip_first: int = 1
    times: list = field(default_factory=list)
    _start: float = 0.0
    _iter: int = 0

    def start(self) -> None:
        self._start = time.perf_counter()

    def stop(self) -> float:
        """Stop the clock; returns this iteration's time (always), and
        accumulates it unless it is among the first `skip_first` iters."""
        elapsed = time.perf_counter() - self._start
        if self._iter >= self.skip_first:
            self.times.append(elapsed)
        self._iter += 1
        return elapsed

    @property
    def total(self) -> float:
        return sum(self.times)

    @property
    def average(self) -> float:
        return self.total / len(self.times) if self.times else 0.0

    @property
    def count(self) -> int:
        return len(self.times)

    def percentiles(self) -> dict:
        """{p50, p95, p99, max} over the accumulated iterations."""
        return percentile_stats(self.times)

    def summary(self) -> str:
        # Same first two lines as the reference (part1/main.py:57-58);
        # the tail line is ours — the reference's average hides the
        # straggler iterations a per-step timeline exists to expose.
        p = self.percentiles()
        return (
            f"Total execution time is : {self.total} seconds\n"
            f"Average execution time is  : {self.average} seconds\n"
            f"Iteration time p50/p95/p99/max : {p['p50']:.6f}/"
            f"{p['p95']:.6f}/{p['p99']:.6f}/{p['max']:.6f} seconds"
        )
