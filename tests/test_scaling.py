"""World-size-aware batch/LR scaling rules (ISSUE 10): fixed
trajectories for every kind, the exact-partition share accounting an
elastic grow relies on, the schedule hook, and the statistical property
the chaos proof leans on — under the linear rule the stationary loss
floor of noisy SGD is world-size-invariant, while the unscaled control
moves it by the world ratio.

All host-side (numpy only, no jax, no compile): the rule is consulted
at relaunch boundaries, never inside a compiled step.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from distributed_machine_learning_tpu.data.sharding import (
    exact_shard_indices,
)
from distributed_machine_learning_tpu.train.scaling import (
    SCALING_KINDS,
    ScalingRule,
    WorldScaling,
    scaled_schedule,
)


# ---------------------------------------------------------------------------
# Fixed trajectories: (world -> batch, lr) golden tables per kind
# ---------------------------------------------------------------------------


def test_pinned_rule_is_world_invariant():
    rule = ScalingRule("pinned", base_lr=0.1, base_global_batch=24,
                       base_world=4)
    for w in (1, 3, 4, 5, 7):
        ws = rule.at_world(w)
        assert (ws.global_batch, ws.lr, ws.lr_factor) == (24, 0.1, 1.0)


def test_linear_rule_fixed_trajectory():
    """The 4→3→5 chaos schedule, as golden numbers: batch tracks the
    world and the LR tracks the ACTUAL batch ratio (ragged rounding
    included)."""
    rule = ScalingRule("linear", base_lr=0.2, base_global_batch=24,
                       base_world=4)
    got = [(w, rule.at_world(w).global_batch,
            round(rule.at_world(w).lr, 6)) for w in (4, 3, 5, 1, 7)]
    assert got == [(4, 24, 0.2), (3, 18, 0.15), (5, 30, 0.25),
                   (1, 6, 0.05), (7, 42, 0.35)]


def test_linear_rule_ragged_base_uses_actual_batch_ratio():
    """base 10 @ world 4 → world 3 rounds to 8 (not 7.5); the LR factor
    is 8/10, not 3/4 — the rounding never silently changes the
    step-to-batch ratio."""
    rule = ScalingRule("linear", base_lr=1.0, base_global_batch=10,
                       base_world=4)
    ws = rule.at_world(3)
    assert ws.global_batch == 8
    assert ws.lr == pytest.approx(0.8)


def test_lars_rule_sqrt_trajectory():
    rule = ScalingRule("lars", base_lr=0.4, base_global_batch=16,
                       base_world=2)
    ws = rule.at_world(8)  # batch x4 -> lr x2
    assert ws.global_batch == 64
    assert ws.lr == pytest.approx(0.8)
    assert rule.at_world(2).lr == pytest.approx(0.4)
    assert rule.at_world(1).lr == pytest.approx(0.4 * math.sqrt(0.5))


def test_unscaled_control_moves_batch_but_not_lr():
    rule = ScalingRule("unscaled", base_lr=0.3, base_global_batch=24,
                       base_world=4)
    ws = rule.at_world(6)
    assert ws.global_batch == 36 and ws.lr == pytest.approx(0.3)
    assert ws.lr_factor == 1.0


def test_rule_validation_and_roundtrip():
    with pytest.raises(ValueError):
        ScalingRule("quadratic")
    with pytest.raises(ValueError):
        ScalingRule("linear", base_lr=0.0)
    with pytest.raises(ValueError):
        ScalingRule("linear", base_global_batch=0)
    with pytest.raises(ValueError):
        ScalingRule("linear", base_world=0)
    with pytest.raises(ValueError):
        ScalingRule("linear").at_world(0)
    rule = ScalingRule("lars", base_lr=0.2, base_global_batch=32,
                       base_world=8)
    assert ScalingRule.from_dict(rule.as_dict()) == rule
    assert set(SCALING_KINDS) == {"pinned", "linear", "lars", "unscaled"}


# ---------------------------------------------------------------------------
# Per-rank shares: exact partition at every world the rule can produce
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [1, 3, 4, 5, 7])
def test_shard_sizes_partition_the_scaled_batch(world):
    rule = ScalingRule("linear", base_lr=0.1, base_global_batch=24,
                       base_world=4)
    ws = rule.at_world(world)
    sizes = [ws.shard_size(r) for r in range(world)]
    assert sum(sizes) == ws.global_batch
    assert max(sizes) - min(sizes) <= 1
    # And they are exactly the exact_shard_indices counts — the worker's
    # id assignment and the rule's accounting can never disagree.
    assert sizes == [len(exact_shard_indices(ws.global_batch, r, world))
                     for r in range(world)]
    with pytest.raises(ValueError):
        ws.shard_size(world)


# ---------------------------------------------------------------------------
# Schedule hook
# ---------------------------------------------------------------------------


def test_scaled_schedule_multiplies_base_curve():
    rule = ScalingRule("linear", base_lr=0.1, base_global_batch=24,
                       base_world=4)
    base = lambda step: 0.1 * (step + 1)  # noqa: E731
    sched5 = scaled_schedule(rule, 5, base)
    assert sched5(0) == pytest.approx(0.1 * 1.25)
    assert sched5(9) == pytest.approx(1.0 * 1.25)
    # pinned (factor 1) returns the base schedule object untouched.
    assert scaled_schedule(ScalingRule("pinned"), 5, base) is base


# ---------------------------------------------------------------------------
# The property the chaos proof leans on: linear keeps the noisy-SGD
# stationary floor world-invariant; the unscaled control does not.
# ---------------------------------------------------------------------------


def _stationary_floor(rule: ScalingRule, world: int, *, dim=64,
                      steps=400, tail=200, seed=0) -> float:
    """Mean ||w||^2 over the tail of mean-estimation SGD: per step draw
    a global batch of B(world) unit-normal examples, step
    w -= lr (w - mean) toward the true optimum 0.  The floor is the
    gradient-noise equilibrium ~ lr/(2-lr) * dim/B — the quantity the
    slow chaos test measures across the 4→3→5 transitions."""
    ws = rule.at_world(world)
    rng = np.random.default_rng(seed)
    w = np.zeros(dim)
    floors = []
    for t in range(steps):
        mu = rng.standard_normal((ws.global_batch, dim)).mean(0)
        w = w - ws.lr * (w - mu)
        if t >= steps - tail:
            floors.append(float(w @ w))
    return float(np.mean(floors))


def test_linear_rule_keeps_loss_floor_while_control_shifts_it():
    base = dict(base_lr=0.2, base_global_batch=24, base_world=4)
    lin3 = _stationary_floor(ScalingRule("linear", **base), 3)
    lin6 = _stationary_floor(ScalingRule("linear", **base), 6)
    assert lin6 / lin3 == pytest.approx(1.0, rel=0.25)
    un3 = _stationary_floor(ScalingRule("unscaled", **base), 3)
    un6 = _stationary_floor(ScalingRule("unscaled", **base), 6)
    # Doubling the batch without touching the LR halves the floor: the
    # control's trajectory is NOT continuous across a world change.
    assert un6 / un3 < 0.65
    assert lin6 / lin3 > 1.5 * (un6 / un3)
