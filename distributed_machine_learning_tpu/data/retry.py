"""Retrying batch iterator — the data leg of the self-healing runtime.

A Python iterator that raises is dead: you cannot ``next()`` it again.
So retrying a data path means *recreating* the source from a factory —
and the factory must be **seekable** (``make_iter(start_index)`` yields
the stream from absolute batch ``start_index``), because a
deterministically bad batch would otherwise kill every replay that has
to pass through it.  Every loader in this repo is deterministic and
sliceable (seeded windows, contiguous slicing; SURVEY.md §2.2's sampler
contract), so seeking is a cheap slice, not a re-read.

The wrapper adds exponential backoff between attempts, a bound on total
retries, and skip-bad-batch semantics: a batch that keeps failing after
``max_attempts_per_batch`` tries is skipped (counted, never silent) so
one corrupt record can't wedge a million-step run — the skip/retry
ladder every production data service ends up with.

Threaded through :class:`data.loader.BatchLoader` via its ``retry``
argument; used by ``runtime/supervisor.py`` around its cursor-keyed
batch factories.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Iterable, Iterator

from distributed_machine_learning_tpu.utils.logging import rank0_print


def _mirror_retry_counter(kind: str) -> None:
    """Registry counter for a retry event with no FaultEvents attached —
    same naming as the FaultEvents mirror so dashboards see one series."""
    from distributed_machine_learning_tpu.telemetry import get_telemetry

    tel = get_telemetry()
    if tel is not None:
        tel.registry.counter("fault_events", kind=kind).inc()


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounds for :func:`retry_batches`.

    ``max_retries`` caps total source recreations across the stream
    (exhaustion re-raises the last error — a persistently dead source
    must surface, not spin).  ``max_attempts_per_batch`` is the
    skip-bad-batch threshold: once one batch index has failed this many
    times it is skipped and the stream continues past it.
    """

    max_retries: int = 3
    max_attempts_per_batch: int = 2
    backoff_s: float = 0.05
    backoff_mult: float = 2.0
    max_backoff_s: float = 5.0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}"
            )
        if self.max_attempts_per_batch < 1:
            raise ValueError(
                f"max_attempts_per_batch must be >= 1, got "
                f"{self.max_attempts_per_batch}"
            )
        if self.backoff_s < 0 or self.backoff_mult < 1:
            raise ValueError(
                f"backoff_s must be >= 0 and backoff_mult >= 1, got "
                f"{self.backoff_s}, {self.backoff_mult}"
            )


def retry_batches(
    make_iter: Callable[[int], Iterable],
    policy: RetryPolicy | None = None,
    events=None,
    start: int = 0,
) -> Iterator:
    """Yield batches from ``make_iter(index)``, surviving exceptions.

    ``make_iter(i)`` must return an iterable positioned at absolute
    batch index ``i`` of the underlying stream.  On an exception at
    index ``i`` the source is rebuilt at ``i`` (retry) or ``i + 1``
    (skip, once the index's attempts are spent).  ``events`` (a
    ``runtime/faults.FaultEvents``) counts ``loader_retries`` and
    ``skipped_batches`` so recoveries are observable, never silent.

    KeyboardInterrupt/SystemExit are never swallowed.
    """
    policy = policy or RetryPolicy()
    pos = start           # absolute index of the next batch to deliver
    retries = 0
    attempts: dict[int, int] = {}
    backoff = policy.backoff_s
    while True:
        it = iter(make_iter(pos))
        try:
            for batch in it:
                yield batch
                pos += 1
            return
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as exc:
            attempts[pos] = attempts.get(pos, 0) + 1
            retries += 1
            if events is not None:
                events.loader_retries += 1
            else:
                # No FaultEvents wired (bare BatchLoader(retry=...) use):
                # the registry is then the only observer.  With events,
                # the FaultEvents mirror (runtime/faults.py) already
                # lands the count — counting here too would double it.
                _mirror_retry_counter("loader_retries")
            if retries > policy.max_retries:
                # Exhaustion is checked BEFORE the skip accounting: when
                # a batch crosses its skip threshold on the same failure
                # that spends the last retry, nothing was recovered — a
                # summary reporting a "skipped batch" here would claim a
                # recovery that never happened.
                rank0_print(
                    f"[data-retry] batch {pos} failed and the retry "
                    f"budget is spent ({retries - 1}/{policy.max_retries} "
                    f"used); giving up ({type(exc).__name__}: {exc})"
                )
                raise
            if attempts[pos] >= policy.max_attempts_per_batch:
                if events is not None:
                    events.skipped_batches += 1
                else:
                    _mirror_retry_counter("skipped_batches")
                rank0_print(
                    f"[data-retry] batch {pos} failed {attempts[pos]} "
                    f"time(s) ({type(exc).__name__}: {exc}); skipping it"
                )
                pos += 1
            else:
                rank0_print(
                    f"[data-retry] batch {pos} failed "
                    f"({type(exc).__name__}: {exc}); retrying "
                    f"(attempt {attempts[pos]}/"
                    f"{policy.max_attempts_per_batch})"
                )
            if backoff:
                time.sleep(backoff)
                backoff = min(backoff * policy.backoff_mult,
                              policy.max_backoff_s)
