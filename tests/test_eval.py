"""Sharded evaluation: the mesh eval step must reproduce the single-device
eval exactly (pmean of equal-shard means == batch mean; psum of counts)."""

import jax
import numpy as np
import pytest

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.models.vgg import VGGTest
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.step import make_eval_step


@pytest.mark.parametrize("use_bn", [False, True])
def test_sharded_eval_matches_single_device(use_bn):
    model = VGGTest(use_bn=use_bn)
    state = init_model_and_state(model)
    rng = np.random.default_rng(5)
    x = rng.integers(0, 256, (64, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, 64).astype(np.int32)

    single = make_eval_step(model)
    loss_s, correct_s = single(state.params, state.batch_stats, x, y)

    mesh = make_mesh(8)
    sharded = make_eval_step(model, mesh=mesh)
    loss_m, correct_m = sharded(state.params, state.batch_stats, x, y)

    np.testing.assert_allclose(float(loss_m), float(loss_s), rtol=1e-6)
    assert int(correct_m) == int(correct_s)


def test_cli_dist_eval_flag_runs(capsys):
    """part2b with --dist-eval prints the same eval surface."""
    from distributed_machine_learning_tpu.cli.common import (
        make_flag_parser,
        parse_flags,
        run_part,
    )

    parser = make_flag_parser("t")
    args = parse_flags(
        parser,
        ["--batch-size", "4", "--max-iters", "2", "--eval-batches", "2",
         "--model", "vggtest", "--eval-batch-size", "16", "--dist-eval"],
    )
    run_part("all_reduce", 4, use_bn=False, args=args)
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out
