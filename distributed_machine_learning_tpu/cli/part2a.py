"""part2a — centralized gather/scatter sync (reference ``part2/2a/main.py``).

The reference gathers every gradient to rank 0, sums, scatters back
(``part2/2a/main.py:89-116``; SUM semantics, batch 64/worker).  Here the
strategy is ``gather_scatter``: all-gather + rank-order sum on every
device (SURVEY.md §7.3).  Flags kept verbatim from
``part2/2a/main.py:210-218``.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.cli.common import make_flag_parser, parse_flags, run_part

BATCH_SIZE = 64  # per worker — part2/2a/main.py:33


def main(argv=None) -> None:
    args = parse_flags(make_flag_parser(__doc__), argv)
    run_part("gather_scatter", per_rank_batch=BATCH_SIZE, use_bn=False, args=args)


if __name__ == "__main__":
    main()
