"""Speculative decoding — draft-and-verify autoregressive generation.

Decode is bound by HBM reads of the target model's weights per token
(docs/PERF.md); speculative decoding (Leviathan et al.) buys tokens per
weight-read: a cheap DRAFT model proposes ``gamma`` tokens
autoregressively, the TARGET verifies all of them in ONE forward pass
(γ+1 positions against its cache — compute-parallel, the same weight
bytes as a single decode step), and a rejection rule keeps the output
distribution EXACTLY the target's:

- greedy (``temperature=0``): accept the longest prefix where the
  draft's token equals the target argmax, then emit the target argmax
  at the first mismatch (or the bonus token when all γ survive) — the
  output is bitwise the target-only greedy stream under matched
  numerics (f32 compute, as the tests pin it).  bf16-serving caveat,
  measured not hypothesized: where the top-2 logits tie within one
  bf16 ulp, DIFFERENTLY-SHAPED programs break the tie differently —
  the Lq=γ+1 verify pass vs the Lq=1 decode step, but equally the
  Lq=1 decode step vs the teacher-forced full forward (at the first
  observed flip on a trained bf16 model, the teacher-forced argmax
  matched NEITHER stream; top-2 gap exactly one bf16 ulp).  Ties are
  equal-probability choices, so the served distribution is unchanged;
  this is a property of shape-dependent XLA numerics, not of
  speculation;
- sampled: accept ``d_i`` with probability ``min(1, p_i(d_i)/q_i(d_i))``
  (p = target, q = draft, both WARPED — temperature/top-k/top-p — so
  the preserved distribution is the one the plain sampler uses); on
  rejection sample from ``norm(max(p_i − q_i, 0))``; on full acceptance
  sample the bonus from ``p_γ``.  The tests pin this branch against a
  NumPy oracle of the rule and check the served empirical distribution
  against plain sampling (tests/test_speculative.py).

TPU-shaped implementation notes:

- **Cache rollback is free.**  The KV caches index slots by absolute
  position with an ``idx`` frontier counter; slots past the frontier
  are causally masked (``slot <= pos``) and overwritten by the next
  write.  Rejecting draft tokens is therefore just rewinding the
  counter in the carried cache pytree — no K/V copy, no re-prefill.
- The draft phase runs γ+1 steps (it processes its own last proposal),
  keeping its cache exactly one token behind the committed stream at
  every round — the invariant that makes the loop shape-static.
- One ``lax.while_loop`` emits a variable 1..γ+1 tokens per round into
  a fixed output buffer at a moving pointer; every slot below the final
  pointer is committed before it can be read.
- **Batched** (B > 1): acceptance length is data-dependent PER ROW, so
  the models are cloned with ``decode_batched_frontier=True`` — the
  cache frontier becomes a [B] counter, positions/RoPE/masks go
  per-row (``models/transformer.py``), and every round each row
  rewinds by its own rejection count.  Rows that reach
  ``max_new_tokens`` freeze (their frontier, pointer, and last token
  stop advancing) and keep verifying dead tokens until the slowest
  row finishes — the standard batched-speculation shape; per-row
  output is token-exact vs the row served alone (tested at batch 8).
  Batch 1 keeps the scalar frontier (and its measured perf numbers).

The reference has no inference path at all (SURVEY.md §2); this extends
the serving surface of ``inference/generate.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_machine_learning_tpu.inference.generate import warp_logits


def sampled_acceptance(d, q, p, u):
    """The Leviathan accept/reject-residual rule, vectorized per row —
    the exact math the sampled branch runs, factored out so the tests
    can pin it against a NumPy oracle (tests/test_speculative.py).

    ``d``: [B, γ] draft proposals; ``q``: [B, γ, V] draft probabilities
    and ``p``: [B, γ+1, V] target probabilities (both already WARPED —
    the preserved distribution is the warped one); ``u``: [B, γ]
    uniforms.  Returns ``(n_acc, resid)``: ``n_acc[b]`` = length of row
    b's accepted prefix (accept d_i iff u_i·q_i(d_i) < p_i(d_i), i.e.
    u_i < p/q), and ``resid[b]`` = the [V] distribution the correction
    token samples from — ``norm(max(p_i − q_i, 0))`` at the first
    rejection i, or the bonus row ``p_γ`` on full acceptance (q_row is
    zeroed there, so the residual IS p_γ).  Emitting ``d_{<n_acc}``
    then one draw from ``resid`` makes each committed token exactly
    target-distributed (Leviathan et al., Theorem 1)."""
    gamma = d.shape[1]
    p_d = jnp.take_along_axis(p[:, :gamma], d[..., None], axis=2)[..., 0]
    q_d = jnp.take_along_axis(q, d[..., None], axis=2)[..., 0]
    acc = u * q_d < p_d  # accept iff u < p/q (q>0 where sampled)
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
    # Residual at the first rejection; bonus row at γ.
    p_row = jnp.take_along_axis(p, n_acc[:, None, None], axis=1)[:, 0]
    q_row = jnp.where(
        (n_acc < gamma)[:, None],
        jnp.take_along_axis(
            q, jnp.minimum(n_acc, gamma - 1)[:, None, None], axis=1
        )[:, 0],
        jnp.zeros_like(p_row),
    )
    resid = jnp.maximum(p_row - q_row, 0.0)
    resid = resid / jnp.maximum(resid.sum(axis=-1, keepdims=True), 1e-30)
    return n_acc, resid


def _validate_speculative_args(target_model, draft_model,
                               max_new_tokens: int, gamma: int,
                               quantize, draft_quantize) -> None:
    """The speculative factories' shared contract — one copy, so the
    single-device and TP entry points cannot drift."""
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if target_model.vocab_size != draft_model.vocab_size:
        raise ValueError(
            f"target and draft must share a vocabulary (got "
            f"{target_model.vocab_size} vs {draft_model.vocab_size})"
        )
    for name, q in (("quantize", quantize),
                    ("draft_quantize", draft_quantize)):
        if q not in (None, "int8"):
            raise ValueError(f"{name} must be None or 'int8', got {q!r}")


def make_speculative_generate_fn(
    target_model,
    draft_model,
    max_new_tokens: int,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    quantize: str | None = None,
    draft_quantize: str | None = None,
):
    """Build ``fn(target_params, draft_params, prompt, rng) -> tokens``.

    ``prompt``: [B, Lp] int32 (any batch; rows share the prompt length
    but not content — each decodes its own stream); returns
    [B, Lp + max_new_tokens].  ``gamma``: draft tokens per verify round.
    ``quantize``/``draft_quantize``: "int8" serves that model through
    the weight-only kernel (``ops/quant.py``) — pass params converted by
    ``quantize_lm_params``.

    Correctness contract: each row's emitted stream follows the TARGET's
    sampling distribution exactly (greedy: bitwise-identical to
    ``make_generate_fn`` with the same flags — tested, per row at batch
    8); the draft only changes HOW FAST tokens appear, never WHICH
    distribution they come from.
    """
    _validate_speculative_args(target_model, draft_model, max_new_tokens,
                               gamma, quantize, draft_quantize)
    tm = target_model.clone(attn_impl="dense", decode=True,
                            weight_quant=quantize)
    dm = draft_model.clone(attn_impl="dense", decode=True,
                           weight_quant=draft_quantize)
    from functools import partial

    return jax.jit(partial(
        _speculative_body, tm, dm, max_new_tokens, gamma, temperature,
        top_k, top_p,
    ))


def _speculative_body(tm, dm, max_new_tokens, gamma, temperature, top_k,
                      top_p, tparams, dparams, prompt, rng):
    """The traced speculative program (prefill + draft/verify rounds) —
    shared by the single-device jit (:func:`make_speculative_generate_fn`)
    and the manual-TP shard_map wrap (:func:`make_tp_speculative_generate_fn`),
    so the two paths can never drift.  ``tm``/``dm`` are decode-mode
    clones (the TP path passes a LOCAL-width target whose ``tp_axis``
    psums complete each projection)."""
    greedy = temperature == 0.0
    V = tm.vocab_size

    def warp(logits):
        return warp_logits(logits, temperature, top_k, top_p)

    B, Lp = prompt.shape
    # Batch 1 keeps the scalar cache frontier (the measured-perf
    # latency path); B > 1 switches the models to per-row frontiers.
    batched = B > 1
    tm_b = tm.clone(decode_batched_frontier=batched)
    dm_b = dm.clone(decode_batched_frontier=batched)
    # The verify pass applies γ+1 tokens MID-STREAM: it must attend
    # the full cache, not take the start-0 prefill fast path — the
    # continuation clone routes multi-token decode through
    # _cached_attention (same params, same cache layout).
    tm_verify = tm_b.clone(decode_continuation=True)
    # Output slack: an ACTIVE row's pointer tops out at
    # max_new−1 + (γ+1); a FROZEN row's window writes span γ+1 more
    # slots — 2(γ+1) covers both without DUS clamping ever shifting
    # a write into committed slots.  Batch 1 never freezes, so it
    # keeps the tighter γ+1 slack (the extra slots could bump
    # cache_len across a 512 tile and tax every einsum read).
    budget = max_new_tokens + (gamma + 1) * (2 if batched else 1)
    cache_len = -(-(Lp + budget + 1) // 512) * 512

    def init_cache(model):
        shapes = jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((B, cache_len), jnp.int32),
                train=False,
            )
        )["cache"]
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

    tcache, dcache = init_cache(tm_b), init_cache(dm_b)

    # Prefill both models on the prompt; the target's last logits
    # sample the first committed token.
    tlogits, tvars = tm_b.apply(
        {"params": tparams, "cache": tcache}, prompt, train=False,
        mutable=["cache"],
    )
    _, dvars = dm_b.apply(
        {"params": dparams, "cache": dcache}, prompt, train=False,
        mutable=["cache"],
    )
    tcache, dcache = tvars["cache"], dvars["cache"]
    rng, r0 = jax.random.split(rng)
    if greedy:
        cur = jnp.argmax(tlogits[:, -1], axis=-1).astype(jnp.int32)
    else:
        cur = jax.random.categorical(
            r0, warp(tlogits[:, -1]), axis=-1
        ).astype(jnp.int32)

    out = jnp.zeros((B, budget), jnp.int32)
    out = lax.dynamic_update_slice(out, cur[:, None], (0, 0))
    # ptr[b]: tokens EMITTED by row b so far (cur at slot 0 counts).
    ptr = jnp.ones((B,), jnp.int32)
    state = (tcache, dcache, cur, out, ptr, rng)

    def round_body(state):
        tcache, dcache, cur, out, ptr, rng = state
        # Frozen rows (only possible when batched): done decoding,
        # still riding the loop until the slowest row finishes.
        done = ptr >= max_new_tokens  # [B]

        # ---- draft phase: γ+1 steps (the last processes its own
        # final proposal, keeping the draft cache one token behind
        # the committed stream after any acceptance count).
        def dstep(carry, r):
            dcache, tok = carry
            logits, vars_ = dm_b.apply(
                {"params": dparams, "cache": dcache}, tok[:, None],
                train=False, mutable=["cache"],
            )
            lg = logits[:, -1]
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                q = jnp.zeros((B, V), jnp.float32)  # unused
            else:
                w = warp(lg)  # one warp per step: probs AND sample
                q = jax.nn.softmax(w, axis=-1)
                nxt = jax.random.categorical(r, w, axis=-1).astype(
                    jnp.int32
                )
            return (vars_["cache"], nxt), (nxt, q)

        rng, *draft_keys = jax.random.split(rng, gamma + 2)
        (dcache2, _), (draft_toks, draft_q) = lax.scan(
            dstep, (dcache, cur), jnp.stack(draft_keys)
        )
        # draft_toks: [γ+1, B]; proposals are the first γ.
        d = draft_toks[:gamma].swapaxes(0, 1)  # [B, γ] int32
        q = draft_q[:gamma].swapaxes(0, 1)  # [B, γ, V]

        # ---- verify: one target pass over [cur, d_0..d_{γ-1}].
        verify_in = jnp.concatenate([cur[:, None], d], axis=1)
        vlogits, tvars = tm_verify.apply(
            {"params": tparams, "cache": tcache}, verify_in,
            train=False, mutable=["cache"],
        )  # [B, γ+1, V]; row (b, i) predicts the slot of d_i.

        rng, r_acc, r_fix = jax.random.split(rng, 3)
        if greedy:
            tbest = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            acc = d == tbest[:, :gamma]  # [B, γ]
            # n_acc[b] = length of row b's all-accepted prefix.
            n_acc = jnp.sum(
                jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1
            )
            # Correction/bonus token: target argmax at slot n_acc.
            t_new = jnp.take_along_axis(
                tbest, n_acc[:, None], axis=1
            )[:, 0]
        else:
            p = jax.nn.softmax(warp(vlogits), axis=-1)  # [B, γ+1, V]
            u = jax.random.uniform(r_acc, (B, gamma))
            n_acc, resid = sampled_acceptance(d, q, p, u)
            t_new = jax.random.categorical(
                r_fix, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1
            ).astype(jnp.int32)

        # Tokens row b commits this round (frozen rows commit none).
        adv = jnp.where(done, 0, n_acc + 1)  # [B]

        # ---- commit: window = [d_0..d_{n_acc-1}, t_new, junk...];
        # the junk beyond n_acc is overwritten by the next round's
        # window (or never read past the final pointer); frozen
        # rows' windows land entirely past max_new_tokens.
        window = jnp.where(
            jnp.arange(gamma + 1)[None] == n_acc[:, None],
            t_new[:, None],
            jnp.concatenate([d, jnp.zeros((B, 1), jnp.int32)], axis=1),
        )
        out = jax.vmap(
            lambda o, w, p0: lax.dynamic_update_slice(o, w, (p0,))
        )(out, window, ptr)

        # ---- cache rewinds (the free rollback): target holds the
        # committed stream MINUS t_new; draft holds one token less.
        # Frozen rows rewind the full γ+1 — their frontier is pinned.
        delta = adv - (gamma + 1)  # [B], <= 0
        back = delta if batched else delta[0]
        tcache = dict(tvars["cache"])
        tcache["idx"] = tcache["idx"] + back
        dcache2 = dict(dcache2)
        dcache2["idx"] = dcache2["idx"] + back
        cur = jnp.where(done, cur, t_new)
        return (tcache, dcache2, cur, out, ptr + adv, rng)

    def cond(state):
        return jnp.any(state[4] < max_new_tokens)

    _, _, _, out, _, _ = lax.while_loop(cond, round_body, state)
    return jnp.concatenate([prompt, out[:, :max_new_tokens]], axis=1)



def make_tp_speculative_generate_fn(
    target_model,
    draft_model,
    max_new_tokens: int,
    mesh,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    quantize: str | None = None,
    draft_quantize: str | None = None,
    model_axis: str = "model",
):
    """Speculative decoding with a TENSOR-PARALLEL target: the whole
    draft/verify/accept program runs inside one shard_map over
    ``model_axis`` (the Megatron decode layout of
    ``inference/generate.py::make_tp_generate_fn``).

    The TARGET runs at its LOCAL width (heads, KV cache, and d_ff ÷ tp;
    ``tp_axis`` psums complete each row-parallel projection), so the
    expensive verify pass — the reason TP serves the model at all —
    is sharded exactly like plain TP decode.  The DRAFT is replicated:
    it exists to be small, so sharding it would trade its whole matmul
    for ICI latency γ times per round.  Acceptance, sampling, and the
    round loop run replicated on every device (same rng ⇒ same
    control flow ⇒ the emitted tokens are identical across devices).

    ``target_params`` must be pre-arranged by
    ``parallel.tensor_parallel.tp_decode_params``; draft params pass
    through whole.  Output is token-exact vs single-device speculative
    decoding (tested on the virtual mesh).
    """
    from jax.sharding import PartitionSpec as P

    from distributed_machine_learning_tpu.inference.generate import (
        tp_local_decode_clone,
        tp_param_specs,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    _validate_speculative_args(target_model, draft_model, max_new_tokens,
                               gamma, quantize, draft_quantize)
    # Layout rules + local-width clone shared with make_tp_generate_fn
    # (inference/generate.py::tp_local_decode_clone).
    local_target = tp_local_decode_clone(
        target_model, mesh, model_axis, quantize
    )
    dm = draft_model.clone(attn_impl="dense", decode=True,
                           weight_quant=draft_quantize)
    from functools import partial

    body = partial(_speculative_body, local_target, dm, max_new_tokens,
                   gamma, temperature, top_k, top_p)

    jitted: dict = {}

    def run(tparams, dparams, prompt, rng):
        key = (jax.tree_util.tree_structure(tparams),
               jax.tree_util.tree_structure(dparams))
        fn = jitted.get(key)
        if fn is None:
            dspecs = jax.tree_util.tree_map(lambda _: P(), dparams)
            fn = jitted[key] = jax.jit(shard_map_no_check(
                body,
                mesh=mesh,
                in_specs=(tp_param_specs(tparams, model_axis), dspecs,
                          P(), P()),
                out_specs=P(),
            ))
        return fn(tparams, dparams, prompt, rng)

    return run
