"""LARS — layer-wise adaptive rate scaling for large-batch SGD.

The retrieved large-batch literature (PAPERS.md: "Extremely Large
Minibatch SGD", "Massively Distributed SGD") scales data-parallel
training to batch sizes where plain SGD+momentum diverges; the fix both
lines of work rely on is LARS (You et al., "Large Batch Training of
Convolutional Networks"): each layer's step is normalized by the ratio
of its weight norm to its gradient norm, so no layer's update can run
away from its weights no matter how the global batch (and with it the
summed gradient) grows.

Update rule (the apex/LARC convention, momentum on the scaled step):

    scale = trust_coefficient · ||w|| / (||g|| + wd·||w|| + eps)
            if both norms > 0, else 1   (zero-norm leaves — e.g.
            zero-init biases at step 0 — take the PLAIN lr; trust
            applies only to the adaptive ratio)
    step  = lr · scale · (g + wd·w)
    m     = momentum · m + step
    w    -= m

Drop-in companion to ``train/sgd.py``: same ``(params, momentum, grads,
config, lr=None)`` signature, same zero-initialized momentum buffers, so
``make_train_step(optimizer="lars")`` swaps it into the jitted step (and
every sync strategy / schedule / clipping option composes unchanged).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from distributed_machine_learning_tpu.train.sgd import SGDConfig, apply_update


@dataclass(frozen=True)
class LARSConfig(SGDConfig):
    # Reference-parity base hyperparams (part1/main.py:120-121) plus the
    # LARS trust coefficient (paper's η, typically 1e-3).
    trust_coefficient: float = 1e-3
    eps: float = 1e-9

    def __post_init__(self):
        # Inherited from SGDConfig, but lars_update has no f32-upcast
        # path for a narrowed carry — refuse rather than silently run
        # the whole momentum accumulation in the narrow dtype.
        if self.momentum_dtype is not None:
            raise ValueError(
                "LARSConfig does not support momentum_dtype (the LARS "
                "update accumulates in the buffer dtype); use sgd for "
                "narrowed optimizer state"
            )


def lars_update(params, momentum_buf, grads, config: LARSConfig, lr=None,
                step=None):
    """One LARS step; returns (new_params, new_momentum_buf).  ``step``
    is accepted for signature uniformity (AdamW) and ignored."""
    del step
    if not isinstance(config, LARSConfig):
        # Fail loudly: a plain SGDConfig here means the state was built
        # without config=LARSConfig() and the momentum semantics (raw-
        # gradient scale vs lr·trust·ratio-scaled steps) would not match.
        raise TypeError(
            f"lars_update needs a LARSConfig on the TrainState, got "
            f"{type(config).__name__}; build the state with "
            "init_model_and_state(model, config=LARSConfig())"
        )
    lr = config.learning_rate if lr is None else lr
    trust = config.trust_coefficient
    eps = config.eps

    def _update(p, m, g):
        p32 = p.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        w_norm = jnp.linalg.norm(p32.reshape(-1))
        g_norm = jnp.linalg.norm(g32.reshape(-1))
        # The trust coefficient applies only to the adaptive ratio (the
        # apex/LARC convention): zero-norm leaves (e.g. zero-init biases
        # at step 0) fall back to the PLAIN lr — multiplying trust into
        # the fallback would freeze them ~1/trust-fold vs SGD.
        scale = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            trust * w_norm / (g_norm + config.weight_decay * w_norm + eps),
            1.0,
        )
        step = lr * scale * (g32 + config.weight_decay * p32)
        m = config.momentum * m + step.astype(m.dtype)
        p = p - m.astype(p.dtype)
        return p, m

    return apply_update(_update, params, momentum_buf, grads)
