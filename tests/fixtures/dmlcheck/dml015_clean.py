# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/serving_worker.py
"""DML015 clean cases: every sanctioned span idiom (with-item,
conditional span assigned then with-ed, Telemetry.span forwarding via
return, enter_context) and a worker loop whose stage journey always
reaches a terminal stamp (requeued/fenced/posted) on every exit path."""
import contextlib

from distributed_machine_learning_tpu.runtime.transport import stamp_stage


def with_item_span(tracer, rid):
    with tracer.span("request", rid=rid):
        return do_work(rid)


def conditional_span(tel, rid):
    span = (tel.span("request", rid=rid)
            if tel is not None else contextlib.nullcontext())
    with span:
        return do_work(rid)


class Telemetry:
    def __init__(self, tracer):
        self.tracer = tracer

    def span(self, name, **args):
        return self.tracer.span(name, **args)   # caller manages it


def stacked_span(tracer, rid):
    with contextlib.ExitStack() as stack:
        stack.enter_context(tracer.span("request", rid=rid))
        return do_work(rid)


def full_journey(reqs, step_fn, rank, epoch, bound_epoch, tx):
    by = f"replica{rank}"
    keep = []
    for req in reqs:
        if epoch != bound_epoch:
            stamp_stage(req, "requeued", by, epoch=epoch)
            tx.push_request(req)
            continue
        stamp_stage(req, "bound", by, epoch=bound_epoch)
        keep.append(req)
    outs = step_fn([r["prompt"] for r in keep])
    for req in keep:
        stamp_stage(req, "computed", by)
    for req, out in zip(keep, outs):
        if not tx.post_result(rank, bound_epoch, dict(req, output=out)):
            stamp_stage(req, "fenced", by, epoch=bound_epoch)
    return outs


def do_work(rid):
    return rid
