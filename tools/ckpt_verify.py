#!/usr/bin/env python3
"""Verify checkpoints against their manifests — stdlib only, no JAX.

Usage::

    python tools/ckpt_verify.py PATH [--quiet] [--json]

``PATH`` may be a single ``step_<n>`` checkpoint directory or any
directory containing them (a run's ``--ckpt-dir``, or a gang's
per-rank root ``.../ckpt/rank<r>/`` — the scan is recursive).  For each
checkpoint: completeness (state dir + config), the quarantine marker,
and every file's sha256 + byte size against ``manifest.json``
(``train/checkpoint.py`` writes it between the state dir and the config
file).  Prints per-file status and the per-leaf digest table the
manifest records (leaf *content* re-verification needs the array
runtime, so it happens at restore time — ``restore_checkpoint`` — not
here).  Exits nonzero on any mismatch, quarantined dir, or incomplete
checkpoint; legacy (pre-manifest) checkpoints report UNVERIFIABLE
without failing the run.

Deliberately dependency-free (hashlib + json + os): this is the tool an
operator runs on a storage node at 3am to decide whether a run can be
resumed, where the training environment may not even be installed.  The
on-disk format it checks is defined by ``train/checkpoint.py``; the two
must stay in sync.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

CONFIG_FILE = "sgd_config.json"
STATE_DIR = "state"
MANIFEST_FILE = "manifest.json"
INVALID_MARKER = ".invalid"


def sha256_of(path: str) -> tuple[str, int]:
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            n += len(chunk)
            h.update(chunk)
    return h.hexdigest(), n


def find_step_dirs(root: str) -> list[str]:
    """Every ``step_<n>`` directory under ``root`` (or ``root`` itself),
    sorted by path then step for stable output."""
    root = os.path.abspath(root)
    name = os.path.basename(root)
    if name.startswith("step_") and name[5:].isdigit():
        return [root]
    found = []
    for dirpath, dirnames, _ in os.walk(root):
        for d in sorted(dirnames):
            if d.startswith("step_") and d[5:].isdigit():
                found.append(os.path.join(dirpath, d))
        # don't descend into checkpoints themselves
        dirnames[:] = [d for d in dirnames
                       if not (d.startswith("step_") and d[5:].isdigit())]
    return sorted(found, key=lambda p: (os.path.dirname(p),
                                        int(os.path.basename(p)[5:])))


def verify_step_dir(path: str, quiet: bool) -> tuple[bool, str, dict]:
    """(ok, status line, json record) for one checkpoint; prints detail
    unless quiet.  The record is the machine half of the verdict —
    supervisors/CI consume it through ``--json`` instead of parsing the
    human lines."""
    rel = path

    def result(ok: bool, status: str, detail: str, **extra):
        record = {"path": path, "ok": ok, "status": status,
                  "detail": detail, **extra}
        return ok, f"{status:<11} {rel}  ({detail})", record

    def emit(line: str) -> None:
        if not quiet:
            print(line)

    marker = os.path.join(path, INVALID_MARKER)
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                reason = json.load(f).get("reason", "unknown")
        except (OSError, json.JSONDecodeError):
            reason = "unreadable marker"
        return result(False, "QUARANTINED", reason)
    complete = (os.path.isdir(os.path.join(path, STATE_DIR))
                and os.path.isfile(os.path.join(path, CONFIG_FILE)))
    if not complete:
        return result(False, "INCOMPLETE", "state dir or config missing")
    manifest_path = os.path.join(path, MANIFEST_FILE)
    if not os.path.isfile(manifest_path):
        return result(True, "UNVERIFIABLE",
                      "legacy checkpoint: no manifest")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return result(False, "BAD-MANIFEST", str(e))

    bad = 0
    bad_files = []
    files = manifest.get("files", {})
    for relf, entry in sorted(files.items()):
        fp = os.path.join(path, relf)
        if not os.path.isfile(fp):
            emit(f"  MISSING  {relf}")
            bad_files.append({"file": relf, "problem": "missing"})
            bad += 1
            continue
        size = os.path.getsize(fp)
        if size != entry.get("bytes"):
            emit(f"  SIZE     {relf}  {size} != {entry.get('bytes')}")
            bad_files.append({"file": relf, "problem": "size mismatch"})
            bad += 1
            continue
        sha, _ = sha256_of(fp)
        if sha != entry.get("sha256"):
            emit(f"  CORRUPT  {relf}  (sha256 mismatch)")
            bad_files.append({"file": relf, "problem": "sha256 mismatch"})
            bad += 1
    leaves = manifest.get("leaves", {})
    if leaves and not quiet:
        emit(f"  {len(files)} file(s) checked; recorded leaves:")
        width = max((len(n) for n in leaves), default=0)
        for name, entry in sorted(leaves.items()):
            if "sha256" not in entry:
                emit(f"    {name:<{width}}  "
                     f"UNVERIFIED ({entry.get('unverified', '?')})")
                continue
            shape = "x".join(str(d) for d in entry.get("shape", [])) or "()"
            logical = entry.get("logical_elems")
            status = "ok" if bad == 0 else "suspect"
            emit(f"    {name:<{width}}  {shape:>12}  "
                 f"{entry.get('dtype', '?'):>9}  "
                 f"{entry.get('bytes', 0):>10,}B  "
                 f"crc32={entry.get('crc32', 0):>10}  "
                 f"sha256={entry['sha256'][:12]}  "
                 + (f"logical={logical}  " if logical is not None else "")
                 + f"[{status}]")
    extra = {"files": len(files), "leaves": len(leaves),
             "shard_spec": manifest.get("shard_spec")}
    if bad:
        return result(False, "CORRUPT", f"{bad} bad file(s)",
                      bad_files=bad_files, **extra)
    return result(True, "OK",
                  f"{len(files)} files, {len(leaves)} leaves verified "
                  "against manifest", **extra)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="verify checkpoint manifests (stdlib only)"
    )
    ap.add_argument("path", help="a step_<n> dir, or a directory "
                                 "containing them (scanned recursively)")
    ap.add_argument("--quiet", action="store_true",
                    help="one status line per checkpoint, no detail")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON summary to "
                         "stdout instead of the human report — the "
                         "form supervisors/CI consume (same exit code)")
    args = ap.parse_args(argv)

    if not os.path.exists(args.path):
        if args.json:
            print(json.dumps({"error": f"no such path: {args.path}",
                              "checkpoints": [], "total": 0,
                              "invalid": 0}))
        else:
            print(f"ckpt_verify: no such path: {args.path}",
                  file=sys.stderr)
        return 2
    dirs = find_step_dirs(args.path)
    if not dirs:
        if args.json:
            print(json.dumps({
                "error": f"no step_<n> checkpoints under {args.path}",
                "checkpoints": [], "total": 0, "invalid": 0,
            }))
        else:
            print(f"ckpt_verify: no step_<n> checkpoints under "
                  f"{args.path}", file=sys.stderr)
        return 2
    failures = 0
    records = []
    for d in dirs:
        ok, status, record = verify_step_dir(d, args.quiet or args.json)
        records.append(record)
        if not args.json:
            print(status)
        if not ok:
            failures += 1
    if args.json:
        print(json.dumps({"checkpoints": records, "total": len(dirs),
                          "invalid": failures}, indent=1))
    else:
        print(f"{len(dirs)} checkpoint(s), {failures} invalid")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
