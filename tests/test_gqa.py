"""Grouped-query attention: param layout, cache narrowing, decode
correctness, and composition with TP / pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
)

VOCAB = 32


def _gqa_model(n_kv_heads, **kw):
    return TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=2,
                        n_heads=4, n_kv_heads=n_kv_heads, **kw)


def test_gqa_param_layout_and_train_step(rng):
    model = _gqa_model(2)
    state = init_lm_state(model)
    attn = state.params["block_0"]["attn"]
    assert set(attn) >= {"q", "kv"} and "qkv" not in attn
    assert attn["kv"]["kernel"].shape == (16, 2, 2, 4)  # [E, 2, Hkv, Dh]
    assert attn["q"]["kernel"].shape == (16, 4, 4)  # [E, H, Dh]

    step = make_lm_train_step(model)
    toks = jnp.asarray(rng.integers(0, VOCAB, (2, 9)), jnp.int32)
    state, loss = step(state, toks[:, :-1], toks[:, 1:])
    assert np.isfinite(float(loss))


def test_mha_layout_unchanged():
    # n_kv_heads=None (and == n_heads) keeps the fused qkv layout, so
    # existing checkpoints stay loadable.
    for n_kv in (None, 4):
        model = TransformerLM(vocab_size=VOCAB, d_model=16, n_layers=1,
                              n_heads=4, n_kv_heads=n_kv)
        params = init_lm_state(model).params
        assert "qkv" in params["block_0"]["attn"]


def test_kv_heads_must_divide():
    model = _gqa_model(3)
    with pytest.raises(ValueError, match="n_kv_heads"):
        init_lm_state(model)


@pytest.mark.parametrize("n_kv", [1, 2])
def test_gqa_greedy_decode_matches_teacher_forced(rng, n_kv):
    # The narrow KV cache must reproduce full teacher-forced decoding
    # exactly — covers MQA (1) and grouped (2).
    from distributed_machine_learning_tpu.inference.generate import generate

    model = _gqa_model(n_kv)
    params = init_lm_state(model).params
    prompt = jnp.asarray(rng.integers(0, VOCAB, (2, 4)), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=5)
    full_logits = model.apply({"params": params}, out, train=False)
    want = np.argmax(np.asarray(full_logits[:, 3:-1]), axis=-1)
    np.testing.assert_array_equal(np.asarray(out[:, 4:]), want)


def test_decode_cache_is_narrow(rng):
    # The cache stores n_kv_heads heads — the GQA memory win.
    model = _gqa_model(1).clone(decode=True)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32), train=False)
    )["cache"]
    cached_key = shapes["block_0"]["attn"]["cached_key"]
    assert cached_key.shape == (1, 1, 8, 4)  # [B, Hkv=1, S, Dh] head-major


@pytest.mark.slow
def test_gqa_under_tensor_parallel(rng):
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        make_tp_lm_train_step,
        shard_tp_batch,
        shard_tp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(4, ("batch", "model"), (2, 2))
    model = _gqa_model(2)
    state = shard_tp_state(init_lm_state(model), mesh)
    step = make_tp_lm_train_step(model, mesh)
    toks = rng.integers(0, VOCAB, (4, 9)).astype(np.int32)
    x, y = shard_tp_batch(mesh, toks[:, :-1], toks[:, 1:])
    state, loss = step(state, x, y)
    assert np.isfinite(float(loss))

    with pytest.raises(ValueError, match="n_kv_heads"):
        make_tp_lm_train_step(_gqa_model(1), mesh)  # 1 % 2 != 0


@pytest.mark.slow
def test_gqa_under_pipeline(rng):
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pp_lm_train_step,
        microbatch,
        shard_pp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(2, ("pipe",))
    model = _gqa_model(2)
    state = shard_pp_state(init_pipeline_state(model), mesh)
    step = make_pp_lm_train_step(model, mesh, num_microbatches=2)
    toks = rng.integers(0, VOCAB, (4, 9)).astype(np.int32)
    px, py = microbatch(toks[:, :-1], toks[:, 1:], 2)
    state, loss = step(state, px, py)
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_gqa_ring_matches_dense(rng):
    # Sequence-sharded ring attention with grouped K/V must equal the
    # unsharded dense forward (the exactness contract, now under GQA).
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.lm_step import shard_lm_batch

    mesh = make_mesh(4, ("batch", "seq"), (1, 4))
    ring = _gqa_model(2, attn_impl="ring")
    state = init_lm_state(ring)
    toks = rng.integers(0, VOCAB, (2, 17)).astype(np.int32)
    x, y = shard_lm_batch(mesh, toks[:, :-1], toks[:, 1:])
    rstep = make_lm_train_step(ring, mesh=mesh)
    _, ring_loss = rstep(state, x, y)

    dense = _gqa_model(2)
    dstate = init_lm_state(dense)
    dstep = make_lm_train_step(dense)
    _, dense_loss = dstep(dstate, jnp.asarray(toks[:, :-1]),
                          jnp.asarray(toks[:, 1:]))
    np.testing.assert_allclose(float(ring_loss), float(dense_loss),
                               rtol=1e-5)
