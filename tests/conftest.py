"""Test harness: 8 virtual CPU devices (SURVEY.md §4 test strategy).

Force the host platform and split it into 8 XLA devices so every
distributed test exercises a real 8-way mesh without TPU hardware — the
TPU-native analogue of the reference's 4-node gloo cluster.

Note: this environment's sitecustomize imports jax at interpreter start
with JAX_PLATFORMS=axon baked in, so setting the env var here is too late;
``jax.config.update`` works post-import as long as no backend has
initialized yet.  XLA_FLAGS must still land in os.environ before the CPU
client spins up — which happens at the first ``jax.devices()`` call, i.e.
after this module runs.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite is dominated by XLA compiles of
# shard_map programs (single-core CPU here); caching them makes reruns
# minutes instead of tens of minutes.  Harmless if the dir is wiped.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("DML_TEST_CACHE", "/tmp/jax_test_cache"),
)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Every custom marker used in tests/ must be registered in
    pytest.ini — tier-1 headroom depends on ``slow``/``faultinject``
    gating, and a typo'd marker (``@pytest.mark.solw``) silently pulls
    a heavy test back into the default run instead of failing loudly.
    pytest core registers its own built-ins (parametrize, skipif, ...)
    through the same ini mechanism, so one registry covers both."""
    registered = {
        line.split(":", 1)[0].split("(", 1)[0].strip()
        for line in config.getini("markers")
    }
    unknown = {}
    for item in items:
        for mark in item.iter_markers():
            if mark.name not in registered:
                unknown.setdefault(mark.name, item.nodeid)
    if unknown:
        raise pytest.UsageError(
            "unregistered pytest marker(s) used in tests/: "
            + "; ".join(f"{name!r} (first use: {nodeid})"
                        for name, nodeid in sorted(unknown.items()))
            + " — register them under [pytest] markers in pytest.ini"
        )


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8():
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    return make_mesh(8)


@pytest.fixture(scope="session")
def mesh4():
    """4-device mesh — the reference's world size (group25.pdf p.1)."""
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    return make_mesh(4)


@pytest.fixture()
def rng():
    return np.random.default_rng(69143)


def shard_map_compat(fn, mesh, in_specs, out_specs, check_vma=False):
    """Replication-check-off shard_map for tests, across jax's API
    rename (``runtime/mesh.py::shard_map_no_check`` owns the version
    shim — new jax spells the flag ``check_vma``, the experimental API
    ``check_rep``).  Drop-in for the old per-file
    ``from jax import shard_map`` + ``check_vma=False`` pattern, which
    breaks on jax versions where the top-level ``shard_map`` lacks the
    kwarg."""
    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    return shard_map_no_check(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )
