"""Schedule-walker unit tests for the ring overlap audit
(bench/overlap_audit.py); the TPU AOT compile itself is exercised by
the audit's __main__ on TPU-capable hosts.  The wire-byte audit
(--wire-bytes) additionally gets a REAL compile check here: the CPU
backend names collective-permute identically, so the int8-vs-exact
byte ratio is asserted against actual compiled executables in CI."""

import pytest

from distributed_machine_learning_tpu.bench.overlap_audit import (
    audit_schedule,
    compile_ring_hlo,
    wire_bytes_from_hlo,
)

HLO = """\
HloModule m

ENTRY main {
  p0 = f32[8]{0} parameter(0)
  cps.1 = (f32[8]{0}, f32[8]{0}) collective-permute-start(p0), source_target_pairs={{0,1}}
  f.1 = f32[8]{0} fusion(p0), kind=kLoop, calls=fused_add
  cpd.1 = f32[8]{0} collective-permute-done(cps.1)
  cps.2 = (f32[8]{0}, f32[8]{0}) collective-permute-start(cpd.1), source_target_pairs={{0,1}}
  cpd.2 = f32[8]{0} collective-permute-done(cps.2)
  ROOT r = f32[8]{0} add(cpd.1, cpd.2)
}
"""


def test_audit_counts_windows_and_overlap():
    s = audit_schedule(HLO)
    assert s["async_ppermute_pairs"] == 2
    assert s["pairs_with_compute_in_window"] == 1  # f.1 inside window 1
    assert s["distinct_compute_ops_in_windows"] == 1
    assert s["op_kinds_in_windows"] == {"fusion": 1}
    assert s["max_concurrent_in_flight"] == 1


def test_audit_rejects_entryless_text():
    with pytest.raises(ValueError, match="ENTRY"):
        audit_schedule("HloModule empty")


WIRE_HLO = """\
HloModule m

ENTRY main {
  p0 = f32[64]{0} parameter(0)
  q = s8[64]{0} convert(p0)
  cp.1 = s8[64]{0} collective-permute(q), source_target_pairs={{0,1}}
  s = f32[1]{0} constant({1.0})
  cp.2 = f32[1]{0} collective-permute(s), source_target_pairs={{0,1}}
  cps.1 = (f32[2,8]{1,0}, f32[2,8]{1,0}) collective-permute-start(p0), source_target_pairs={{0,1}}
  cpd.1 = f32[2,8]{1,0} collective-permute-done(cps.1)
  ROOT r = f32[64]{0} convert(cp.1)
}
"""


def test_wire_bytes_parser_counts_defs_once():
    """Sync and async forms both count; a start's tuple result counts
    the operand buffer only (not the paired result buffer), and -done
    lines are uses, never double-counted."""
    got = wire_bytes_from_hlo(WIRE_HLO)
    assert got["count"] == 3
    # s8[64]=64B + f32[1]=4B + first tuple element f32[2,8]=64B
    assert got["total_bytes"] == 64 + 4 + 64
    assert got["by_dtype"] == {"s8": 64, "f32": 68}


def test_wire_bytes_parser_empty_module():
    got = wire_bytes_from_hlo("HloModule m\nENTRY main { ROOT r = f32[] constant(0) }")
    assert got == {"total_bytes": 0, "count": 0, "by_dtype": {}}


def test_wire_bytes_ci_regression_int8_vs_exact(mesh8):
    """The fast CI gate (ISSUE 7 satellite): compile a real bucketed
    ring for the 8-device mesh, exact and int8, and assert the
    compressed executable moves ≤ 1/3 of the exact one's
    collective-permute bytes — read from the compiled programs, so a
    regression that silently decompresses the wire fails here."""
    from distributed_machine_learning_tpu.ops.ring import ring_wire_bytes
    from distributed_machine_learning_tpu.ops.ring import get_wire_scheme

    length = 4096
    exact = wire_bytes_from_hlo(
        compile_ring_hlo(mesh8, length, bucket_bytes=8192)
    )
    int8 = wire_bytes_from_hlo(
        compile_ring_hlo(mesh8, length, compress="int8", bucket_bytes=8192)
    )
    assert exact["count"] > 0 and int8["count"] > 0
    assert int8["total_bytes"] * 3 <= exact["total_bytes"]
    # The compiled programs' byte totals match the static accounting the
    # telemetry counter uses — the two can never drift apart silently.
    assert exact["total_bytes"] == ring_wire_bytes(
        length, 8, bucket_bytes=8192
    )
    assert int8["total_bytes"] == ring_wire_bytes(
        length, 8, bucket_bytes=8192, scheme=get_wire_scheme("int8")
    )
