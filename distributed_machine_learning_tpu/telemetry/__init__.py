"""Streaming telemetry subsystem — registry, crash-safe sinks, spans.

Three pieces, one facade:

- :class:`~.registry.MetricsRegistry` — process-wide named
  counters/gauges/histograms (``telemetry/registry.py``);
- :class:`~.sink.JsonlSink` — append-mode, fsynced, rank-0-gated JSONL
  (``telemetry/sink.py``), plus a Prometheus-textfile export of the
  final registry state;
- :class:`~.tracer.SpanTracer` — host-side Chrome trace-event spans
  (``telemetry/tracer.py``), the driver-phase complement to the
  ``jax.profiler`` xplane trace.

:class:`Telemetry` bundles them over one output directory::

    telemetry_dir/
      metrics.jsonl   per-step rows, attempt-tagged, appended live
      trace.json      Chrome trace (open in ui.perfetto.dev)
      registry.json   final registry snapshot (counters, quantiles)
      metrics.prom    Prometheus textfile export of the final values

Everything is OFF by default: ``get_telemetry()`` returns ``None``
unless a CLI installed an instance (``--telemetry-dir``), and every
integration point guards with ``if tel is not None`` — the hot loop
pays one pointer test per step when telemetry is off, no allocations,
no syscalls.  The module-level install (:func:`set_telemetry`) is what
makes deep layers (loaders, checkpointing, fault counters) observable
without threading a handle through every signature.

Attempt tagging: the supervisor (``runtime/supervisor.py``) calls
:meth:`Telemetry.set_attempt` before each attempt, so every metrics row
carries the attempt that produced it, and a fresh process resuming into
the same directory continues from the attempt after the last one on
disk — restarts APPEND history, never truncate it.
"""

from __future__ import annotations

import json
import os
import time

from distributed_machine_learning_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_latency_buckets,
)
from distributed_machine_learning_tpu.telemetry.sink import (
    JsonlSink,
    read_jsonl,
    write_prometheus,
)
from distributed_machine_learning_tpu.telemetry.tracer import (
    SpanTracer,
    read_trace,
)
from distributed_machine_learning_tpu.telemetry.aggregator import (
    GangRollup,
    HeartbeatSampler,
    StragglerDetector,
    StragglerVerdict,
    aggregate_gang_metrics,
    discover_rank_streams,
    publish_rollup,
    serving_stage_samples,
)
from distributed_machine_learning_tpu.telemetry.slo import (
    SLOEngine,
    SLOSpec,
    format_verdict,
    parse_slo,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_latency_buckets",
    "JsonlSink", "read_jsonl", "write_prometheus",
    "SpanTracer", "read_trace",
    "GangRollup", "HeartbeatSampler", "StragglerDetector",
    "StragglerVerdict", "aggregate_gang_metrics",
    "discover_rank_streams", "publish_rollup",
    "serving_stage_samples",
    "SLOEngine", "SLOSpec", "format_verdict", "parse_slo",
    "Telemetry", "telemetry_from_flags",
    "get_telemetry", "set_telemetry", "instance_file",
]

METRICS_FILE = "metrics.jsonl"
TRACE_FILE = "trace.json"
REGISTRY_FILE = "registry.json"
PROM_FILE = "metrics.prom"


def instance_file(name: str, instance: str | None) -> str:
    """``metrics.jsonl`` + instance ``rank2`` -> ``metrics.rank2.jsonl``.

    The collision-safety contract: two processes pointed at the SAME
    telemetry directory must never append to the same stream (append
    interleaving welds their rows into garbage neither reader
    tolerates), so each gets an instance tag spliced in front of the
    extension.  ``None`` keeps the canonical single-process names."""
    if not instance:
        return name
    if "/" in instance or os.sep in instance:
        raise ValueError(f"instance must be a bare tag, got {instance!r}")
    stem, dot, ext = name.rpartition(".")
    return f"{stem}.{instance}{dot}{ext}" if dot else f"{name}.{instance}"


def _last_attempt_on_disk(path: str) -> int | None:
    """The ``attempt`` tag of the last parseable row in a metrics
    stream, or None for no/empty stream.

    Attempts only ever increase along the stream (rows are appended in
    attempt order), so the last row carries the max — a bounded TAIL
    read, not a full parse: the metrics JSONL is the long-horizon
    artifact, and a supervisor re-exec must not re-parse a multi-GB
    history before training can start.  Tolerates the torn final row a
    kill leaves (scans back to the last parseable line).
    """
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return None
            back = min(size, 1 << 20)
            f.seek(size - back)
            tail = f.read(back)
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn final row, or the truncated first tail line
        if isinstance(row, dict) and isinstance(row.get("attempt"), int):
            return row["attempt"]
    return None


def _rehydrate_counters(registry_path: str, registry: MetricsRegistry
                        ) -> None:
    """Seed ``registry`` with the counter totals a prior process left in
    its ``registry.json`` (corrupt/absent snapshots are ignored — the
    stream artifacts still hold the full history)."""
    try:
        with open(registry_path) as f:
            snap = json.load(f)
    except (OSError, json.JSONDecodeError):
        return
    for entry in snap.get("counters", []):
        try:
            registry.counter(entry["name"], **entry.get("labels", {})).inc(
                entry["value"]
            )
        except (KeyError, TypeError, ValueError):
            continue


class Telemetry:
    """One run's telemetry: registry + metrics sink + span tracer over a
    single output directory.

    ``attempt`` starts after the last attempt already on disk (a
    supervisor re-exec into the same directory appends as attempt N+1);
    in-process restarts advance it via :meth:`set_attempt`.

    ``instance``: a per-process tag (e.g. ``rank2``) spliced into every
    artifact filename (``metrics.rank2.jsonl``, ``trace.rank2.json``,
    ...) so N processes can share one telemetry directory without their
    appends ever interleaving — the gang layout
    ``telemetry/aggregator.py`` reads back as one cross-rank plane.
    """

    def __init__(self, out_dir: str | os.PathLike, flush_every: int = 20,
                 enabled: bool | None = None, fsync: bool = True,
                 instance: str | None = None):
        self.out_dir = os.fspath(out_dir)
        self.instance = instance or None
        self.registry = MetricsRegistry()
        metrics_path = os.path.join(
            self.out_dir, instance_file(METRICS_FILE, self.instance)
        )
        prior = _last_attempt_on_disk(metrics_path)
        self.attempt = 0 if prior is None else prior + 1
        if prior is not None:
            # Resuming into a prior run's directory: carry its COUNTER
            # totals forward so the exported registry keeps whole-run
            # semantics (fault_events across every attempt), matching
            # the append-not-truncate contract of the other artifacts.
            # Gauges are instantaneous and histogram snapshots hold only
            # quantiles (not bucket counts), so those restart.
            _rehydrate_counters(self._artifact(REGISTRY_FILE),
                                self.registry)
        self.metrics = JsonlSink(metrics_path, flush_every=flush_every,
                                 fsync=fsync, enabled=enabled)
        self.tracer = SpanTracer(self._artifact(TRACE_FILE),
                                 flush_every=flush_every, enabled=enabled)
        # Optional cost model for MFU: the CLI sets whichever it knows.
        self.flops_per_example: float | None = None
        self.flops_per_token: float | None = None
        self.peak_tflops: float | None = None
        # Static per-step counter increments the train loop applies on
        # every completed step (e.g. ``ring_wire_bytes``: the compressed
        # ring's bytes-on-the-wire are a compile-time constant of the
        # program, so the CLI computes the increment once and the loop
        # just accumulates it).  Empty by default: one dict iteration
        # per step when telemetry is on, nothing when off.
        self.step_counters: dict[str, float] = {}
        self._closed = False

    def _artifact(self, name: str) -> str:
        return os.path.join(self.out_dir,
                            instance_file(name, self.instance))

    # -- per-step surface ------------------------------------------------
    def log_step(self, step: int, **metrics) -> None:
        """One attempt-tagged metrics row, streamed (not buffered to
        end-of-run — the crash-loss fix this subsystem exists for).
        The registry snapshot is re-exported once per sink flush window,
        so a hard kill loses at most one window of counter updates, the
        same durability bound the rows get."""
        self.metrics.write({
            "step": step, "time": time.time(), "attempt": self.attempt,
            **metrics,
        })
        if self.metrics.rows_written % self.metrics.flush_every == 0:
            self._export_registry()

    def span(self, name: str, **args):
        return self.tracer.span(name, **args)

    def set_attempt(self, attempt: int) -> None:
        """Tag subsequent rows/spans with this restart attempt (called by
        ``runtime/supervisor.py::run_attempts``).  Never moves backwards:
        a fresh process that already resumed past attempt 0 keeps its
        offset when the in-process supervisor starts counting from 0."""
        attempt = max(attempt, self.attempt)
        if attempt != self.attempt:
            self.attempt = attempt
            self.flush()  # the prior attempt's rows are now history

    def mfu_of(self, examples_per_s: float, tokens_per_s: float | None
               ) -> float | None:
        """MFU from whichever cost model the CLI installed, or None."""
        from distributed_machine_learning_tpu.utils.flops import (
            DEFAULT_PEAK_TFLOPS,
            mfu,
        )

        peak = self.peak_tflops or DEFAULT_PEAK_TFLOPS
        if self.flops_per_token is not None and tokens_per_s is not None:
            return mfu(tokens_per_s * self.flops_per_token, peak)
        if self.flops_per_example is not None:
            return mfu(examples_per_s * self.flops_per_example, peak)
        return None

    # -- lifecycle -------------------------------------------------------
    def flush(self) -> None:
        self.metrics.flush()
        self.tracer.flush()
        self._export_registry()

    def _export_registry(self) -> None:
        if not self.metrics.enabled:
            return
        os.makedirs(self.out_dir, exist_ok=True)
        snap_path = self._artifact(REGISTRY_FILE)
        tmp = snap_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.registry.snapshot(), f, indent=1)
        os.replace(tmp, snap_path)
        write_prometheus(self._artifact(PROM_FILE), self.registry)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.metrics.close()
        self.tracer.close()
        self._export_registry()

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- process-wide install -------------------------------------------------
_active: Telemetry | None = None


def get_telemetry() -> Telemetry | None:
    """The installed telemetry, or None (the default: everything off)."""
    return _active


def set_telemetry(tel: Telemetry | None) -> Telemetry | None:
    """Install ``tel`` process-wide (None uninstalls); returns the
    previous instance so scoped users can restore it."""
    global _active
    prev = _active
    _active = tel
    return prev


def telemetry_from_flags(args) -> Telemetry | None:
    """Telemetry from the shared CLI flags (``--telemetry-dir``,
    ``--telemetry-flush-every``), or None when the flag is unset — the
    single construction point both CLIs share."""
    out_dir = getattr(args, "telemetry_dir", None)
    if not out_dir:
        return None
    return Telemetry(out_dir,
                     flush_every=getattr(args, "telemetry_flush_every", 20))
