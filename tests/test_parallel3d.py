"""Composed 3-D (data × pipeline × tensor) parallelism correctness.

The invariant is the same one every other strategy test asserts: the
distributed step must take exactly the step the single-device dense
baseline takes — here with all three parallelism dimensions active at
once on a (2, 2, 2) mesh of the 8 virtual CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.parallel3d import (
    make_3d_lm_train_step,
    make_3d_mesh,
    microbatch,
    init_pipeline_state,
    p3_param_spec,
    shard_3d_batch,
    shard_3d_state,
)
from distributed_machine_learning_tpu.parallel.pipeline import stack_lm_params
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
)

MODEL = TransformerLM(vocab_size=64, d_model=32, n_layers=4, n_heads=4)


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    toks = rng.integers(0, 64, (4, 17))
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


@pytest.fixture(scope="module")
def dense_step_result(batch):
    x, y = batch
    state = init_lm_state(MODEL)
    step = make_lm_train_step(MODEL)
    state, loss = step(state, jnp.asarray(x), jnp.asarray(y))
    return state, float(loss)


@pytest.mark.parametrize(
    "shape",
    [(2, 2, 2),
     pytest.param((1, 4, 2), marks=pytest.mark.slow),
     pytest.param((2, 4, 1), marks=pytest.mark.slow),
     pytest.param((1, 2, 4), marks=pytest.mark.slow)],
)
def test_3d_matches_dense_baseline(batch, dense_step_result, shape):
    dp, pp, tp = shape
    x, y = batch
    mesh = make_3d_mesh(dp, pp, tp)
    state = shard_3d_state(init_pipeline_state(MODEL), mesh)
    step = make_3d_lm_train_step(MODEL, mesh, num_microbatches=2)
    mx, my = shard_3d_batch(mesh, *microbatch(x, y, 2))
    state, loss = step(state, mx, my)

    dstate, dloss = dense_step_result
    np.testing.assert_allclose(float(loss), dloss, rtol=1e-5)
    ref = stack_lm_params(dstate.params, MODEL.n_layers)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        )


def test_3d_two_steps_stay_in_sync(batch):
    """Error doesn't accumulate: two consecutive 3-D steps track the dense
    trajectory."""
    x, y = batch
    mesh = make_3d_mesh(2, 2, 2)
    state = shard_3d_state(init_pipeline_state(MODEL), mesh)
    step = make_3d_lm_train_step(MODEL, mesh, num_microbatches=2)
    mx, my = shard_3d_batch(mesh, *microbatch(x, y, 2))

    dstate = init_lm_state(MODEL)
    dstep = make_lm_train_step(MODEL)

    for _ in range(2):
        state, loss = step(state, mx, my)
        dstate, dloss = dstep(dstate, jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(float(loss), float(dloss), rtol=1e-4)


def test_3d_param_specs():
    """Spot-check the layout rules: pipe on the stacked dim, Megatron
    splits inside blocks, embed fully replicated."""
    from jax.sharding import PartitionSpec as P

    assert p3_param_spec(("blocks", "attn", "qkv", "kernel"), 5) == P(
        "pipe", None, None, "model", None
    )
    assert p3_param_spec(("blocks", "fc_in", "kernel"), 3) == P(
        "pipe", None, "model"
    )
    assert p3_param_spec(("blocks", "ln1", "scale"), 2) == P("pipe", None)
    assert p3_param_spec(("embed", "embedding"), 2) == P(None, None)
    assert p3_param_spec(("lm_head", "kernel"), 2) == P(None, "model")


def test_3d_validations():
    mesh = make_3d_mesh(2, 2, 2)
    with pytest.raises(ValueError, match="pipeline stages"):
        make_3d_lm_train_step(MODEL.clone(n_layers=3), mesh, 2)
    with pytest.raises(ValueError, match="model-axis"):
        make_3d_lm_train_step(MODEL.clone(n_heads=3), mesh, 2)
    with pytest.raises(ValueError, match="attn_impl"):
        make_3d_lm_train_step(MODEL.clone(attn_impl="ring"), mesh, 2)


def test_3d_flash_matches_3d_dense():
    """Flash inside the 3-D step: the model's wrap manualizes the
    remaining (batch, model) axes from within the pipe-manual region —
    a nested partial-manual shard_map whose union covers the mesh.
    Must match the dense 3-D step within kernel tolerance."""
    import numpy as np

    from distributed_machine_learning_tpu.models.transformer import (
        TransformerLM,
    )
    from distributed_machine_learning_tpu.parallel.parallel3d import (
        make_3d_lm_train_step,
        make_3d_mesh,
        shard_3d_batch,
        shard_3d_state,
    )
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        microbatch,
    )

    mesh = make_3d_mesh(2, 2, 2)
    rng = np.random.default_rng(31)
    toks = rng.integers(0, 64, (8, 13)).astype(np.int32)
    results = {}
    for attn in ("dense", "flash"):
        model = TransformerLM(vocab_size=64, d_model=32, n_layers=4,
                              n_heads=4, attn_impl=attn)
        step = make_3d_lm_train_step(model, mesh, num_microbatches=2)
        state = shard_3d_state(init_pipeline_state(model), mesh)
        mx, my = microbatch(toks[:, :-1], toks[:, 1:], 2)
        sx, sy = shard_3d_batch(mesh, mx, my)
        state, loss = step(state, sx, sy)
        results[attn] = (float(loss), state.params)
    d_loss, d_params = results["dense"]
    f_loss, f_params = results["flash"]
    np.testing.assert_allclose(f_loss, d_loss, rtol=1e-4)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(f_params),
                    jax.tree_util.tree_leaves(d_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-6)
