"""VGG model family: shapes, parameter count, BN flag (reference
``part1/model.py`` / ``part3/model.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.vgg import VGG, VGG11


def _param_count(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def test_vgg11_output_shape_and_param_count():
    model = VGG11()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)))
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)))
    assert logits.shape == (2, 10)
    # Reference report: ~9.2M parameters (group25.pdf p.2; SURVEY.md §0.1).
    n = _param_count(variables["params"])
    assert 9_100_000 < n < 9_400_000, n


@pytest.mark.parametrize("name", ["VGG11", "VGG13", "VGG16", "VGG19"])
def test_whole_cfg_table_builds(name):
    # part1/model.py:3-8 defines all four; we expose all four.
    model = VGG(name_cfg=name)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    assert model.apply(variables, jnp.zeros((1, 32, 32, 3))).shape == (1, 10)


def test_bn_flag_part3_parity():
    # part3/model.py:24 enables BatchNorm; part1 has it commented out.
    plain = VGG11().init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    assert "batch_stats" not in plain
    bn = VGG11(use_bn=True)
    variables = bn.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    assert "batch_stats" in variables
    # train=True mutates running stats
    logits, mutated = bn.apply(
        variables, jnp.ones((4, 32, 32, 3)), train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (4, 10)
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(o, n) for o, n in zip(old, new))


def test_bf16_compute_fp32_logits():
    model = VGG11(compute_dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    # Params stay fp32 (master weights), logits come back fp32.
    assert all(
        p.dtype == jnp.float32 for p in jax.tree_util.tree_leaves(variables["params"])
    )
    assert model.apply(variables, jnp.zeros((1, 32, 32, 3))).dtype == jnp.float32
