"""Benchmark harness — prints ONE JSON line for the driver.

Flagship workload: VGG-11/CIFAR-10 jitted train step (the reference's
part1 measurement: 39 timed iterations at batch 256, iteration 0 excluded
— ``part1/main.py:32-58``; 2.39 s/iter on its CPU node, group25.pdf p.2).

Metric: images/sec through the train step on the available device.
``vs_baseline`` compares against the reference's measured part1 rate
(256 / 2.39 s ≈ 107.1 imgs/sec — BASELINE.md).

The trunk runs in bfloat16 (MXU-native; master weights and loss stay
fp32).  Uses the synthetic CIFAR stand-in when the real dataset is not on
disk — identical shapes/dtypes, so the throughput number is unaffected.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_machine_learning_tpu.cli.common import init_model_and_state
from distributed_machine_learning_tpu.data.cifar10 import load_cifar10
from distributed_machine_learning_tpu.models.registry import get_model, list_models
from distributed_machine_learning_tpu.train.step import make_train_step

BATCH = 256  # part1/main.py:18
TIMED_ITERS = 39  # part1 protocol: 40 iters, iteration 0 excluded
BASELINE_IMGS_PER_SEC = 256 / 2.39  # group25.pdf p.2 → 107.1


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="vgg11", choices=list_models())
    args = parser.parse_args()
    model = get_model(args.model, compute_dtype=jnp.bfloat16)
    state = init_model_and_state(model)
    step = make_train_step(model, mesh=None, augment=True)

    train = load_cifar10("./data", train=True)
    images = train.images[: BATCH * 8]
    labels = train.labels[: BATCH * 8]

    def batch(i):
        s = (i * BATCH) % (len(labels) - BATCH + 1)
        return (
            jnp.asarray(images[s : s + BATCH]),
            jnp.asarray(labels[s : s + BATCH]),
        )

    # Warm-up / compile (the reference's excluded iteration 0).
    x, y = batch(0)
    state, loss = step(state, x, y)
    jax.block_until_ready(loss)

    start = time.perf_counter()
    for i in range(1, TIMED_ITERS + 1):
        x, y = batch(i)
        state, loss = step(state, x, y)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start

    imgs_per_sec = BATCH * TIMED_ITERS / elapsed
    # The reference measured only VGG-11 (group25.pdf p.2); comparing any
    # other model against that number would be apples-to-oranges.
    vs_baseline = (
        round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 2)
        if args.model == "vgg11"
        else None
    )
    print(
        json.dumps(
            {
                "metric": f"{args.model}_cifar10_train_imgs_per_sec",
                "value": round(imgs_per_sec, 2),
                "unit": "imgs/sec",
                "vs_baseline": vs_baseline,
            }
        )
    )


if __name__ == "__main__":
    main()
