"""Schedule-walker unit tests for the ring overlap audit
(bench/overlap_audit.py); the TPU AOT compile itself is exercised by
the audit's __main__ on TPU-capable hosts.  The wire-byte audit
(--wire-bytes) additionally gets a REAL compile check here: the CPU
backend names collective-permute identically, so the int8-vs-exact
byte ratio is asserted against actual compiled executables in CI."""

import pytest

from distributed_machine_learning_tpu.bench.overlap_audit import (
    audit_schedule,
    compile_ring_hlo,
    wire_bytes_from_hlo,
)

HLO = """\
HloModule m

ENTRY main {
  p0 = f32[8]{0} parameter(0)
  cps.1 = (f32[8]{0}, f32[8]{0}) collective-permute-start(p0), source_target_pairs={{0,1}}
  f.1 = f32[8]{0} fusion(p0), kind=kLoop, calls=fused_add
  cpd.1 = f32[8]{0} collective-permute-done(cps.1)
  cps.2 = (f32[8]{0}, f32[8]{0}) collective-permute-start(cpd.1), source_target_pairs={{0,1}}
  cpd.2 = f32[8]{0} collective-permute-done(cps.2)
  ROOT r = f32[8]{0} add(cpd.1, cpd.2)
}
"""


def test_audit_counts_windows_and_overlap():
    s = audit_schedule(HLO)
    assert s["async_ppermute_pairs"] == 2
    assert s["pairs_with_compute_in_window"] == 1  # f.1 inside window 1
    assert s["distinct_compute_ops_in_windows"] == 1
    assert s["op_kinds_in_windows"] == {"fusion": 1}
    assert s["max_concurrent_in_flight"] == 1


def test_audit_rejects_entryless_text():
    with pytest.raises(ValueError, match="ENTRY"):
        audit_schedule("HloModule empty")


WIRE_HLO = """\
HloModule m

ENTRY main {
  p0 = f32[64]{0} parameter(0)
  q = s8[64]{0} convert(p0)
  cp.1 = s8[64]{0} collective-permute(q), source_target_pairs={{0,1}}
  s = f32[1]{0} constant({1.0})
  cp.2 = f32[1]{0} collective-permute(s), source_target_pairs={{0,1}}
  cps.1 = (f32[2,8]{1,0}, f32[2,8]{1,0}) collective-permute-start(p0), source_target_pairs={{0,1}}
  cpd.1 = f32[2,8]{1,0} collective-permute-done(cps.1)
  ROOT r = f32[64]{0} convert(cp.1)
}
"""


def test_wire_bytes_parser_counts_defs_once():
    """Sync and async forms both count; a start's tuple result counts
    the operand buffer only (not the paired result buffer), and -done
    lines are uses, never double-counted."""
    got = wire_bytes_from_hlo(WIRE_HLO)
    assert got["count"] == 3
    # s8[64]=64B + f32[1]=4B + first tuple element f32[2,8]=64B
    assert got["total_bytes"] == 64 + 4 + 64
    assert got["by_dtype"] == {"s8": 64, "f32": 68}


def test_wire_bytes_parser_empty_module():
    got = wire_bytes_from_hlo("HloModule m\nENTRY main { ROOT r = f32[] constant(0) }")
    assert got == {"total_bytes": 0, "count": 0, "by_dtype": {}}


TPU_STYLE_ASYNC_HLO = """\
HloModule m

ENTRY main {
  p0 = f32[1066]{0} parameter(0)
  collective-permute-start = (f32[1066]{0:T(1024)}, f32[1066]{0:T(1024)}, u32[]{:S(2)}, u32[]{:S(2)}) collective-permute-start(p0), source_target_pairs={{0,1}}
  f.1 = f32[8,1066]{1,0} fusion(p0), kind=kLoop, calls=fused_dus
  collective-permute-done = f32[1066]{0:T(1024)} collective-permute-done((f32[1066]{0:T(1024)}, f32[1066]{0:T(1024)}, u32[]{:S(2)}, u32[]{:S(2)}) %collective-permute-start)
  ROOT r = f32[1066]{0} add(collective-permute-done, p0)
}
"""


def test_audit_closes_tuple_typed_done_windows():
    """The TPU backend spells the -done operand's full tuple type
    inline (``...-done((f32[...]{0:T(1024)}, ...) %start)``); the
    walker must still close the window — a lazy scan-to-first-paren
    used to mis-capture ``1024`` and leave every window open (so
    max_in_flight counted starts, never overlap)."""
    s = audit_schedule(TPU_STYLE_ASYNC_HLO)
    assert s["async_ppermute_pairs"] == 1
    assert s["pairs_with_compute_in_window"] == 1
    assert s["max_concurrent_in_flight"] == 1


GTE_ROOT_HLO = """\
HloModule m

ENTRY main {
  p0 = f32[1066]{0} parameter(0)
  ar = (f32[8528]{0}, f32[]) all-reduce(p0, p0), replica_groups={{0,1}}, to_apply=add
  gte0 = f32[8528]{0} get-tuple-element((f32[8528]{0}, f32[]) %ar), index=0
  ROOT r = (f32[8528]{0}) tuple(%gte0)
}
"""


def test_sync_collectives_feed_root_through_gte():
    """Tuple-fused collectives (the TPU backend folds the zero1 gather
    into a variadic all-reduce) reach ROOT via get-tuple-element; the
    feeds_root attribution must see through one GTE hop, or the sync
    baseline's critical-path collective reads as innocent."""
    from distributed_machine_learning_tpu.bench.overlap_audit import (
        sync_collectives_from_hlo,
    )

    recs = sync_collectives_from_hlo(GTE_ROOT_HLO)
    assert len(recs) == 1
    assert recs[0]["kind"] == "all-reduce"
    assert recs[0]["feeds_root"] is True


def test_zero1_overlap_audit_ci_regression(mesh8):
    """The ISSUE-9 acceptance gate, on real compiled executables (CPU
    mesh — structural checks): the sync baseline's weight-update
    all-gather IS on the critical path feeding ROOT (the 2004.13336
    anti-pattern), and the overlap build kills it — the update program
    contains no all-gather and no root-feeding collective of any kind;
    the consume program is permute-only.  A future change that
    re-serializes the gather fails here."""
    from distributed_machine_learning_tpu.bench.overlap_audit import (
        zero1_overlap_audit,
    )

    summary = zero1_overlap_audit(mesh8, global_batch=16)
    assert summary["sync_build"]["gather_on_critical_path"], (
        "the sync baseline must still exhibit the anti-pattern the "
        "overlap build is measured against"
    )
    ov = summary["overlap_build"]
    assert ov["update_all_gathers"] == []
    assert ov["update_root_feeding_collectives"] == []
    # The consume program is permute-chained: a regression back to one
    # monolithic all-gather shows up as zero permutes and/or a
    # non-permute collective, and must fail the gate.
    assert ov["gather_sync_nonpermute_collectives"] == []
    assert ov["gather_permutes"] > 0
    assert summary["passes"], summary


def test_ring_all_gather_bitwise_and_bucketed(mesh8):
    """The consume-phase primitive: the bucketed ppermute ring gather
    is bit-identical to ``lax.all_gather(tiled=True)`` for every bucket
    count (pure data movement — the overlap builds' parity rests on
    this), and compiles to (N−1)·buckets permutes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from distributed_machine_learning_tpu.ops.ring import (
        ring_all_gather_flat,
    )
    from distributed_machine_learning_tpu.runtime.mesh import (
        shard_map_no_check,
    )

    x = np.random.default_rng(0).normal(size=(8, 97)).astype(np.float32)
    ref = jax.jit(shard_map_no_check(
        lambda s: lax.all_gather(s.reshape(-1), "batch", tiled=True)[None],
        mesh=mesh8, in_specs=P("batch"), out_specs=P("batch")))(x)
    for k in (1, 3, 4):
        fn = jax.jit(shard_map_no_check(
            lambda s, k=k: ring_all_gather_flat(
                s.reshape(-1), "batch", 8, n_buckets=k)[None],
            mesh=mesh8, in_specs=P("batch"), out_specs=P("batch")))
        np.testing.assert_array_equal(np.asarray(fn(x)), np.asarray(ref))
        hlo = fn.lower(
            jax.ShapeDtypeStruct((8, 97), jnp.float32)
        ).compile().as_text()
        permutes = wire_bytes_from_hlo(hlo)["count"]
        assert permutes == 7 * k, (k, permutes)


def test_hier_wire_bytes_per_axis_ci_regression(mesh8):
    """The round-11 acceptance gate, read off COMPILED executables:

    1. per-axis attribution: every permute's ``source_target_pairs``
       routing classifies to the inner/outer axis, and the compiled
       per-axis bytes equal the static ``ring_wire_bytes_by_axis``
       accounting for none/int8/topk — the labeled telemetry counters
       and the executable can never drift apart silently;
    2. the inter-node reduction: the exact hierarchical build's
       OUTER-axis bytes are ≤ (1/inner + 5%) of the exact FLAT ring's
       total, for both 2x4 and 4x2 factorizations of the 8-mesh.
    """
    from distributed_machine_learning_tpu.ops.ring import (
        ring_wire_bytes,
        ring_wire_bytes_by_axis,
    )
    from distributed_machine_learning_tpu.ops.topology import Topology

    length, bb = 4096, 8192
    flat_total = wire_bytes_from_hlo(
        compile_ring_hlo(mesh8, length, bucket_bytes=bb)
    )["total_bytes"]
    assert flat_total == ring_wire_bytes(length, 8, bucket_bytes=bb)
    for inner, outer in ((2, 4), (4, 2)):
        spec = f"{inner}x{outer}"
        for compress in ("none", "int8", "topk"):
            got = wire_bytes_from_hlo(
                compile_ring_hlo(mesh8, length, compress=compress,
                                 bucket_bytes=bb, topology=spec,
                                 hd_max_bytes=0),
                inner=inner,
            )
            topo = Topology(inner, outer, outer_scheme=compress,
                            hd_max_bytes=0)
            want = ring_wire_bytes_by_axis(
                length, 8, bucket_bytes=bb, topology=topo)
            assert got["by_axis"] == want, (spec, compress, got, want)
            if compress == "none":
                bound = (1.0 / inner + 0.05) * flat_total
                assert got["by_axis"]["outer"] <= bound, (
                    spec, got["by_axis"], flat_total)


def test_hd_wire_bytes_attribution(mesh8):
    """The halving-doubling path's compiled permutes attribute by
    exchange distance: distance-1 exchanges stay intra-node on a
    2-wide inner axis, distances 2 and 4 cross — and the compiled
    per-axis bytes equal the static accounting."""
    from distributed_machine_learning_tpu.ops.ring import (
        ring_wire_bytes_by_axis,
    )
    from distributed_machine_learning_tpu.ops.topology import Topology

    hlo = compile_ring_hlo(mesh8, 256, bucket_bytes=8192, topology="2x4",
                           hd_max_bytes=1 << 30)
    got = wire_bytes_from_hlo(hlo, inner=2)
    topo = Topology(2, 4, hd_max_bytes=1 << 30)
    want = ring_wire_bytes_by_axis(256, 8, bucket_bytes=8192,
                                   topology=topo)
    assert got["by_axis"] == want
    assert got["by_axis"]["inner"] > 0 and got["by_axis"]["outer"] > 0
    # 2·log2(8) = 6 exchange steps, each one ppermute.
    assert got["count"] == 6


def test_predicted_plan_bytes_match_hlo_audit(mesh8):
    """Round-20 acceptance: ``Topology.select`` is PREDICTION-driven
    (no ``hd_max_bytes`` override anywhere here), and the plan the cost
    model picks prices exactly the bytes the compiled executable moves:
    for 2x4/4x2 × {none,int8,topk}, the per-axis payloads of
    ``plan_hops`` under the selected plan equal the per-axis bytes the
    DML103 HLO walker reads off ``source_target_pairs`` — the link
    model can never cost a different program than the one that runs."""
    from distributed_machine_learning_tpu.ops.ring import (
        ring_wire_bytes_by_axis,
    )
    from distributed_machine_learning_tpu.ops.topology import Topology

    length, bb = 4096, 8192  # two 8 KiB buckets
    for inner, outer in ((2, 4), (4, 2)):
        for compress in ("none", "int8", "topk"):
            topo = Topology(inner, outer, outer_scheme=compress)
            plan = topo.select(bb)
            # The cost model's regime split at this bucket size: exact
            # 8 KiB buckets sit below both topologies' hd/hier
            # crossovers (latency path); a requested codec forbids hd
            # above the fidelity bound (hier keeps the codec).
            assert plan == ("hd" if compress == "none" else "hier"), (
                inner, outer, compress, plan)
            priced = {"inner": 0, "outer": 0}
            for axis, _dist, nbytes in topo.plan_hops(bb, plan):
                priced[axis] += nbytes
            priced = {k: 2 * v for k, v in priced.items()}  # two buckets
            got = wire_bytes_from_hlo(
                compile_ring_hlo(mesh8, length, compress=compress,
                                 bucket_bytes=bb,
                                 topology=f"{inner}x{outer}"),
                inner=inner,
            )
            assert got["by_axis"] == priced, (
                inner, outer, compress, plan, got["by_axis"], priced)
            # And the static telemetry accounting dispatches through
            # the SAME selector, so all three agree.
            assert priced == ring_wire_bytes_by_axis(
                length, 8, bucket_bytes=bb, topology=topo)


def test_wire_bytes_ci_regression_int8_vs_exact(mesh8):
    """The fast CI gate (ISSUE 7 satellite): compile a real bucketed
    ring for the 8-device mesh, exact and int8, and assert the
    compressed executable moves ≤ 1/3 of the exact one's
    collective-permute bytes — read from the compiled programs, so a
    regression that silently decompresses the wire fails here."""
    from distributed_machine_learning_tpu.ops.ring import ring_wire_bytes
    from distributed_machine_learning_tpu.ops.ring import get_wire_scheme

    length = 4096
    exact = wire_bytes_from_hlo(
        compile_ring_hlo(mesh8, length, bucket_bytes=8192)
    )
    int8 = wire_bytes_from_hlo(
        compile_ring_hlo(mesh8, length, compress="int8", bucket_bytes=8192)
    )
    assert exact["count"] > 0 and int8["count"] > 0
    assert int8["total_bytes"] * 3 <= exact["total_bytes"]
    # The compiled programs' byte totals match the static accounting the
    # telemetry counter uses — the two can never drift apart silently.
    assert exact["total_bytes"] == ring_wire_bytes(
        length, 8, bucket_bytes=8192
    )
    assert int8["total_bytes"] == ring_wire_bytes(
        length, 8, bucket_bytes=8192, scheme=get_wire_scheme("int8")
    )
