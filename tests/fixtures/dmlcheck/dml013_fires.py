# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/transport.py
"""DML013 firing cases: lock-owned shared state of the gang control
plane mutated without holding the owning lock — the data race every
transport correctness claim (exactly-once, first-writer-wins abort)
sits on."""
import threading


class InProcHub:
    def __init__(self):
        self.lock = threading.RLock()
        self.beats = {}
        self.abort = None
        self.health = []

    def publish(self, rank, payload):
        self.beats[rank] = (1, dict(payload))   # unlocked store

    def latch(self, payload):
        self.abort = dict(payload)              # unlocked assign

    def record(self, payload):
        self.health.append(dict(payload))       # unlocked mutator call

    def wipe(self):
        self.beats.clear()                      # unlocked clear
