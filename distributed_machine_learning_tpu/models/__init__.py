from distributed_machine_learning_tpu.models.vgg import VGG, VGG11, VGG13, VGG16, VGG19

__all__ = ["VGG", "VGG11", "VGG13", "VGG16", "VGG19"]
