from distributed_machine_learning_tpu.train.sgd import sgd_init, sgd_update, SGDConfig
from distributed_machine_learning_tpu.train.state import TrainState

__all__ = ["sgd_init", "sgd_update", "SGDConfig", "TrainState"]
