"""Compressed-ring weak-scaling bench: wire bytes, step tails, parity.

Measures the round-7 tentpole (``ops/ring.py`` wire schemes +
``parallel/strategies.py::RingAllReduce`` error feedback) three ways,
per world size and codec:

- **wire bytes/step** — the static accounting
  (``ring_wire_bytes``; the HLO audit in ``overlap_audit.py
  --wire-bytes`` verifies the same number against the compiled
  program's collective-permute shapes);
- **step time p50/p95** — the mandatory-tail protocol (PERF.md round-6
  mandate).  NOTE on CPU hosts the ppermute "wire" is a memcpy, so
  compression costs compute and saves nothing — the honest reading of
  a CPU row is *overhead of the codec*, while the byte column is the
  bandwidth win an ICI-bound pod realizes;
- **loss parity** — final-loss relative delta vs the exact ring over
  the same fixed-seed synthetic batch stream (error feedback on).

Weak scaling: per-device batch is FIXED (default 16); the global batch
grows with the world, the reference's scaling protocol.

**Topology sweep** (round 11, ``--topology``): every entry beyond
``flat`` reruns the matrix through the topology-aware hierarchical
ring (``ops/topology.py``) — rows gain the per-axis wire split
(``wire_bytes_by_axis``: the inter-node reduction is the point) and
the auto-selector's chosen ``plan`` for the gradient's bucket (exact
small gradients ride the halving-doubling latency path; compressed
ones the hierarchical ring).  The flat rows are the selector's
baseline: the acceptance bar is auto-selected p50 ≤ flat p50.

Run:  python -m distributed_machine_learning_tpu.bench.ring_compress \
          [--worlds 2,4,8] [--iters 24] [--model vggtest] \
          [--topology flat,2x4,4x2] [--json out]
"""

from __future__ import annotations

import argparse
import json
import time


def bench_ring_compress(worlds=(2, 4, 8), iters: int = 24,
                        per_device_batch: int = 16,
                        model_name: str = "vggtest",
                        topk_frac: float = 0.125,
                        bucket_mb: int = 25,
                        topologies=("flat",),
                        modeled_network: bool = False) -> list[dict]:
    import jax
    import numpy as np

    from distributed_machine_learning_tpu.cli.common import (
        SEED,
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.ops.ring import WIRE_SCHEMES
    from distributed_machine_learning_tpu.parallel.strategies import (
        get_strategy,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )
    from distributed_machine_learning_tpu.utils.timing import (
        percentile_stats,
    )

    model = get_model(model_name, use_bn=False)
    rows = []
    for world in worlds:
        if world > jax.device_count():
            continue
        mesh = make_mesh(world)
        B = per_device_batch * world
        rng = np.random.default_rng(SEED)
        batches = [
            (rng.integers(0, 256, (B, 32, 32, 3), dtype=np.uint8),
             rng.integers(0, 10, B).astype(np.int32))
            for _ in range(iters)
        ]
        final_exact = None
        for topology in topologies:
            if topology != "flat":
                from distributed_machine_learning_tpu.ops.topology import (
                    parse_topology,
                )

                ti, to = parse_topology(topology)
                if ti * to != world:
                    continue  # this spec does not factor this world
            for compress in WIRE_SCHEMES:  # "none" first: parity anchor
                kwargs = {"bucket_bytes": bucket_mb * 2**20}
                if compress != "none":
                    kwargs.update(compress=compress, topk_frac=topk_frac)
                if topology != "flat":
                    kwargs["topology"] = topology
                strategy = get_strategy("ring", **kwargs)
                state = init_model_and_state(
                    model,
                    config=SGDConfig(learning_rate=0.1, weight_decay=0.0),
                )
                n_elems = sum(
                    int(l.size)
                    for l in jax.tree_util.tree_leaves(state.params)
                )
                step = make_train_step(model, strategy, mesh=mesh,
                                       augment=False)
                times = []
                loss = None
                for i, (x, y) in enumerate(batches):
                    xs, ys = shard_batch(mesh, x, y)
                    t0 = time.perf_counter()
                    state, loss = step(state, xs, ys)
                    loss = jax.block_until_ready(loss)
                    if i > 0:  # iteration 0 holds the compile
                        times.append(time.perf_counter() - t0)
                final = float(loss)
                if compress == "none" and final_exact is None:
                    # Parity anchor: the flat exact ring when 'flat'
                    # leads the sweep (the default), else the first
                    # exact plan — exact plans differ only by
                    # association order, so the column stays meaningful
                    # when a rerun sweeps topologies alone.
                    final_exact = final
                stats = percentile_stats(times)
                topo = strategy.topology_for(world)
                if topo is None:
                    plan = "flat"
                else:
                    # Per-BUCKET, matching the dispatch that actually
                    # runs (a multi-bucket gradient can mix plans, e.g.
                    # a small tail bucket riding hd): unique plans in
                    # bucket order, joined.
                    from distributed_machine_learning_tpu.ops.ring import (
                        _bucket_bounds,
                    )

                    plans = []
                    for b0, b1 in _bucket_bounds(
                        n_elems, bucket_mb * 2**20, 4
                    ):
                        p = topo.select((b1 - b0) * 4)
                        if p not in plans:
                            plans.append(p)
                    plan = "+".join(plans)
                row = {
                    "world": world,
                    "global_batch": B,
                    "topology": topology,
                    "compress": compress,
                    "error_feedback": getattr(strategy, "stateful",
                                              False),
                    "wire_bytes_per_step": strategy.wire_bytes_per_step(
                        n_elems, world
                    ),
                    "wire_bytes_by_axis": strategy.wire_bytes_by_axis(
                        n_elems, world
                    ),
                    "plan": plan,
                    "compression_ratio": strategy.compression_ratio(
                        n_elems, world
                    ),
                    "iter_p50_s": stats["p50"],
                    "iter_p95_s": stats["p95"],
                    "final_loss": final,
                    "final_loss_rel_delta_vs_exact": (
                        None if final_exact is None
                        else abs(final - final_exact)
                        / max(abs(final_exact), 1e-30)
                    ),
                }
                if modeled_network:
                    # The pod claim, priced instead of measured: seconds
                    # one bucketed all-reduce costs under the calibrated
                    # LinkModel (round 20) — the number the CPU rows
                    # cannot show because their ppermute "wire" is a
                    # memcpy.  Topology rows ride the selector's own
                    # cost model; a flat ring on a multi-node pod is
                    # topology-unaware, so every hop is priced at the
                    # inter-node link (Topology._flat_axis).
                    from distributed_machine_learning_tpu.ops.ring import (
                        _bucket_bounds,
                    )
                    from distributed_machine_learning_tpu.ops.topology import (  # noqa: E501
                        DEFAULT_LINK_MODEL,
                        Topology,
                        predict_all_reduce_time,
                    )

                    if topo is not None:
                        modeled = predict_all_reduce_time(
                            n_elems, topo, bucket_mb * 2**20)
                    else:
                        pod = Topology(
                            inner=1, outer=world,
                            outer_scheme=compress, topk_frac=topk_frac)
                        modeled = sum(
                            pod.predict_bucket_time(
                                (b1 - b0) * 4, plan="flat",
                                link=DEFAULT_LINK_MODEL)
                            for b0, b1 in _bucket_bounds(
                                n_elems, bucket_mb * 2**20, 4))
                    row["modeled_pod_step_s"] = modeled
                rows.append(row)
                print(json.dumps(row))
    return rows


def bench_selector_ab(world: int = 8, topology: str = "2x4",
                      iters: int = 60, per_device_batch: int = 16,
                      model_name: str = "vggtest") -> list[dict]:
    """The selector acceptance instrument: INTERLEAVED A/B of the flat
    ring vs the selector's plans (hd for the small exact bucket, hier
    with the codec) on the SAME batch stream — the shared protocol of
    ``bench/harness.py::interleaved_ab`` (one iteration of each config
    per round, so the 1-core host's ±5% sequential drift cancels
    instead of masquerading as a plan cost; the PR-9 overlap bench's
    protocol).  The bar: neither selected plan slower than flat at
    p50."""
    import dataclasses

    import jax
    import numpy as np

    from distributed_machine_learning_tpu.bench.harness import (
        interleaved_ab,
    )
    from distributed_machine_learning_tpu.cli.common import (
        SEED,
        init_model_and_state,
    )
    from distributed_machine_learning_tpu.models.registry import get_model
    from distributed_machine_learning_tpu.parallel.strategies import (
        RingAllReduce,
        get_strategy,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh
    from distributed_machine_learning_tpu.train.sgd import SGDConfig
    from distributed_machine_learning_tpu.train.step import (
        make_train_step,
        shard_batch,
    )
    from distributed_machine_learning_tpu.utils.timing import (
        percentile_stats,
    )

    class _HierOnly(RingAllReduce):
        """The topology strategy with the hd path pinned off — isolates
        the hierarchical plan in the A/B (the selector would route the
        small exact bucket to hd)."""

        def topology_for(self, axis_size):
            topo = super().topology_for(axis_size)
            return (None if topo is None
                    else dataclasses.replace(topo, hd_max_bytes=0))

    mesh = make_mesh(world)
    model = get_model(model_name, use_bn=False)
    rng = np.random.default_rng(SEED)
    B = per_device_batch * world
    batches = [
        (rng.integers(0, 256, (B, 32, 32, 3), dtype=np.uint8),
         rng.integers(0, 10, B).astype(np.int32))
        for _ in range(4)
    ]
    configs = {
        "flat": get_strategy("ring"),
        "auto_hd": get_strategy("ring", topology=topology),
        "auto_hier_int8": get_strategy("ring", compress="int8",
                                       topology=topology),
        "hier_exact": _HierOnly(topology=topology),
    }
    steps, states = {}, {}
    for k, strat in configs.items():
        states[k] = init_model_and_state(
            model, config=SGDConfig(learning_rate=0.1, weight_decay=0.0)
        )
        steps[k] = make_train_step(model, strat, mesh=mesh, augment=False)

    def one_iter(k):
        def run(rep):
            xs, ys = shard_batch(mesh, *batches[rep % len(batches)])
            states[k], loss = steps[k](states[k], xs, ys)
            jax.block_until_ready(loss)
        return run

    times = interleaved_ab({k: one_iter(k) for k in configs}, iters,
                           warmup=1)
    rows = []
    flat_p50 = percentile_stats(times["flat"])["p50"]
    for k, ts in times.items():
        stats = percentile_stats(ts)
        topo = configs[k].topology_for(world)
        n_elems = sum(
            int(l.size)
            for l in jax.tree_util.tree_leaves(states[k].params)
        )
        rows.append({
            "bench": "selector_ab",
            "world": world,
            "config": k,
            "plan": ("flat" if topo is None
                     else topo.select(n_elems * 4)),
            "iter_p50_s": stats["p50"],
            "iter_p95_s": stats["p95"],
            "p50_vs_flat": stats["p50"] / flat_p50 - 1.0,
        })
        print(json.dumps(rows[-1]))
    return rows


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--worlds", default="2,4,8")
    parser.add_argument("--iters", default=24, type=int)
    parser.add_argument("--batch-size", default=16, type=int,
                        help="PER-DEVICE batch (weak scaling)")
    parser.add_argument("--model", default="vggtest")
    parser.add_argument("--topk-frac", default=0.125, type=float)
    parser.add_argument("--bucket-mb", default=25, type=int)
    parser.add_argument("--topology", default="flat",
                        help="comma list of sweep entries: 'flat' "
                             "and/or INNERxOUTER specs (e.g. "
                             "'flat,2x4,4x2'); specs that do not "
                             "factor a world are skipped for it")
    parser.add_argument("--selector-ab", action="store_true",
                        help="run the interleaved selector A/B "
                             "(flat vs auto-selected hd/hier, one "
                             "iteration of each per round — drift "
                             "cancels) instead of the sweep; the "
                             "first --topology entry that is not "
                             "'flat' is the factorization under test")
    parser.add_argument("--modeled-network", action="store_true",
                        help="add a modeled_pod_step_s column: the "
                             "calibrated LinkModel's predicted pod "
                             "all-reduce seconds next to the measured "
                             "CPU time (the digital-twin pricing, "
                             "round 20)")
    parser.add_argument("--json", dest="json_out", default=None)
    args = parser.parse_args(argv)
    if args.selector_ab:
        specs = [t.strip() for t in args.topology.split(",")
                 if t.strip() != "flat"]
        rows = bench_selector_ab(
            world=int(args.worlds.split(",")[0]),
            topology=specs[0] if specs else "2x4",
            iters=args.iters,
            per_device_batch=args.batch_size,
            model_name=args.model,
        )
    else:
        rows = bench_ring_compress(
            worlds=tuple(int(w) for w in args.worlds.split(",")),
            iters=args.iters,
            per_device_batch=args.batch_size,
            model_name=args.model,
            topk_frac=args.topk_frac,
            bucket_mb=args.bucket_mb,
            topologies=tuple(t.strip() for t in args.topology.split(",")),
            modeled_network=args.modeled_network,
        )
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rows, f, indent=2)
        print(f"wrote {args.json_out}")


if __name__ == "__main__":
    main()
