"""Continuous-batching decode engine over a paged KV pool (ISSUE 19).

The batch-static serving path (``make_serving_step``) holds a whole
micro-batch hostage to its slowest member: requests are grouped by
prompt length, every group decodes to its own worst case, and nothing
new starts until the whole dispatch returns.  This engine replaces
that with **iteration-level scheduling** (the Orca/vLLM discipline):

* one *step* = one jitted decode dispatch advancing EVERY in-flight
  sequence by one token, each at its own cache frontier;
* newly admitted prompts prefill and join the very next step;
* a sequence that finishes (EOS or its own ``max_new``) retires
  mid-flight, its KV blocks free immediately, and the freed lane
  backfills from the waiting queue in the same ``step()`` call.

KV residency is a shared **paged pool** — per layer, a
``[num_blocks + 1, Hkv, block_size, D]`` array whose rows are handed
out by ``inference/kv_blocks.py``'s :class:`BlockAllocator` (the +1
row is a scratch block that idle lanes point at).  The decode step
gathers each lane's pages through its block table, runs the model's
batched-frontier cached attention (``models/transformer.py``
``decode_batched_frontier=True`` — per-row ``idx``, per-row masks),
and scatters the one newly written (Hkv, D) row per lane back into
the pool.  The gather formulation is numerically identical to
``ops/pallas/decode_attention.paged_attention_reference`` (asserted
in tests); on TPU hardware the same pool + tables feed
``paged_flash_attention``, whose scalar-prefetched table walk makes
each lane's reads O(position) without materializing the gather.

The **regime lever** (``runtime/scheduler.py``): per step the engine
asks its :class:`~..runtime.scheduler.RegimeScheduler` (or honors the
router's stamped hint) which dispatch variant to run — ``"latency"``
(full-precision weights; the thin-batch regime where speculative
decoding's economics apply) or ``"throughput"`` (int8 weight-only via
``quantize_lm_params``, the measured wide-batch lever).  Lever
variants share the KV pool — they are the same weights at different
precision — so flipping between steps is free; *weight versions* (hot
swap) are different weights, and :meth:`swap_params` refuses to land
while any sequence is in flight (the engine-step-boundary fence the
deploy pipeline drains to).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from distributed_machine_learning_tpu.inference.generate import _sample
from distributed_machine_learning_tpu.inference.kv_blocks import (
    BlockAllocator,
    CacheExhausted,
    blocks_needed,
)
from distributed_machine_learning_tpu.runtime.scheduler import (
    LATENCY,
    THROUGHPUT,
)
from distributed_machine_learning_tpu.telemetry.registry import (
    default_latency_buckets,
)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """``max_lanes`` is the decode batch width W (one jitted program,
    idle lanes ride as masked work); ``num_blocks * block_size`` is
    the shared cache budget in token slots; ``max_len`` caps
    ``prompt_len + max_new`` per request and fixes the per-lane block
    table width (the jit-static gather shape)."""

    max_lanes: int = 4
    block_size: int = 16
    num_blocks: int = 64
    max_len: int = 128
    max_new: int = 32              # default per-request cap
    eos_id: int | None = None
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    levers: tuple = (LATENCY, THROUGHPUT)

    def __post_init__(self):
        if self.max_lanes < 1:
            raise ValueError(f"max_lanes must be >= 1: {self.max_lanes}")
        if self.max_len > self.num_blocks * self.block_size:
            raise ValueError(
                f"max_len={self.max_len} exceeds the pool "
                f"({self.num_blocks} x {self.block_size} slots)"
            )
        if not self.levers or any(
            l not in (LATENCY, THROUGHPUT) for l in self.levers
        ):
            raise ValueError(f"unknown levers: {self.levers}")


@dataclasses.dataclass
class _Lane:
    rid: object
    prompt_len: int
    max_new: int
    tokens: list
    request: dict | None
    version: object
    lever: str
    t_submit: float
    t_ready: float        # prefill completed
    prefill_s: float


def _gather_cache(mb, bs, pools, tables, positions):
    """Pool pages -> one dense batched-frontier cache tree."""
    def leaf(pool):
        g = pool[tables]  # [W, MB, Hkv, bs, D]
        W, _, hkv, _, d = g.shape
        return g.transpose(0, 2, 1, 3, 4).reshape(W, hkv, mb * bs, d)

    cache = jax.tree_util.tree_map(leaf, pools)
    cache["idx"] = positions
    return cache


def _decode_step(dm, sample, mb, bs, params, pools, tables, positions,
                 toks, rng):
    """One iteration: gather pages -> model decode (every lane writes
    its slot ``positions[w]`` and attends slots <= it) -> scatter the
    fresh K/V row of each lane back to its page -> sample."""
    cache = _gather_cache(mb, bs, pools, tables, positions)
    logits, vars_ = dm.apply(
        {"params": params, "cache": cache}, toks[:, None],
        train=False, mutable=["cache"],
    )
    newc = vars_["cache"]
    newc.pop("idx", None)
    bidx = positions // bs
    phys = jnp.take_along_axis(tables, bidx[:, None], axis=1)[:, 0]
    off = positions % bs

    def scatter(pool, cache_leaf):
        # cache_leaf [W, Hkv, S, D]: pull each lane's just-written row.
        new = jnp.take_along_axis(
            cache_leaf, positions[:, None, None, None], axis=2
        )[:, :, 0, :]
        return pool.at[phys, :, off, :].set(new)

    pools = jax.tree_util.tree_map(scatter, pools, newc)
    rng, r = jax.random.split(rng)
    nxt = sample(logits[:, -1], r)
    return pools, nxt


def _prefill(dm, sample, nb, bs, params, pools, table_row, prompt, rng):
    """Prefill one prompt ([1, Lp]) into its ``nb`` allocated pool
    blocks and sample the first generated token."""
    sp = nb * bs
    shapes = jax.eval_shape(
        lambda: dm.init(
            jax.random.PRNGKey(0), jnp.zeros((1, sp), jnp.int32),
            train=False,
        )
    )["cache"]
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )
    logits, vars_ = dm.apply(
        {"params": params, "cache": cache}, prompt, train=False,
        mutable=["cache"],
    )
    newc = vars_["cache"]
    newc.pop("idx", None)

    def scatter(pool, cache_leaf):
        # [1, Hkv, Sp, D] -> [nb, Hkv, bs, D] page rows.
        hkv, d = cache_leaf.shape[1], cache_leaf.shape[3]
        pages = cache_leaf[0].reshape(hkv, nb, bs, d).transpose(1, 0, 2, 3)
        return pool.at[table_row].set(pages)

    pools = jax.tree_util.tree_map(scatter, pools, newc)
    rng, r = jax.random.split(rng)
    tok = sample(logits[:, -1], r)
    return pools, tok[0]


class ContinuousEngine:
    """One replica's iteration-level serving loop.

    ``submit()`` queues requests (any thread); ``step()`` (the owning
    worker thread) advances the world by one decode iteration and
    returns the requests that finished.  Construction compiles
    nothing — prefill programs trace per distinct prompt length, the
    decode program once per (lever) — so a replica is serving-warm
    after its first few requests.
    """

    def __init__(self, model, params, cfg: EngineConfig | None = None, *,
                 registry=None, scheduler=None, name: str = "engine",
                 version=None, rng=None):
        self.cfg = cfg = cfg or EngineConfig()
        if model.kv_cache_dtype is not None:
            raise ValueError(
                "paged pools hold compute-dtype KV; int8 caches are the "
                "batch-static path's lever (kv_cache_dtype must be None)"
            )
        self._by = name
        self._scheduler = scheduler
        self._hint: str | None = None
        self.version = version
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._mb = blocks_needed(cfg.max_len, cfg.block_size)
        self._trash = cfg.num_blocks  # scratch page for idle lanes
        self.allocator = BlockAllocator(cfg.num_blocks, cfg.block_size)
        self._dm = {}
        self._params = {}
        self._model = model
        self._base_params = params
        for lever in cfg.levers:
            quant = "int8" if lever == THROUGHPUT else None
            self._dm[lever] = model.clone(
                attn_impl="dense", decode=True, weight_quant=quant,
                decode_batched_frontier=True,
            )
        self._set_params(params)
        sample = partial(_sample, temperature=cfg.temperature,
                         top_k=cfg.top_k, top_p=cfg.top_p)
        self._decode_jit = {
            lever: jax.jit(partial(_decode_step, self._dm[lever], sample,
                                   self._mb, cfg.block_size))
            for lever in cfg.levers
        }
        self._prefill_jit = {}   # (lever, nb, Lp) -> jitted fn
        self._sample = sample
        # The pool tree: the decode cache structure minus "idx", one
        # leading page axis replacing the batch axis.  Built from a
        # one-block eval_shape so layout/dtype can never drift from
        # the model's own cache variables.
        shapes = jax.eval_shape(
            lambda: self._dm[cfg.levers[0]].init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, cfg.block_size), jnp.int32), train=False,
            )
        )["cache"]
        shapes.pop("idx")
        self.pools = jax.tree_util.tree_map(
            lambda s: jnp.zeros((cfg.num_blocks + 1,) + s.shape[1:],
                                s.dtype),
            shapes,
        )
        self._lanes: list[_Lane | None] = [None] * cfg.max_lanes
        self._waiting: list[_Lane] = []
        self._paused = False
        self.steps = 0
        self.completed_total = 0
        self._metrics = None
        if registry is not None:
            lat = default_latency_buckets()
            self._metrics = {
                "lanes": registry.gauge("engine_active_lanes"),
                "queue": registry.gauge("engine_queue_depth"),
                "free": registry.gauge("kv_free_blocks"),
                "avail": registry.gauge("kv_available_blocks"),
                "tokens": registry.counter("engine_tokens_total"),
                "done": registry.counter("engine_requests_total"),
                "prefill": registry.histogram(
                    "engine_prefill_s", buckets=lat),
                "decode": registry.histogram(
                    "engine_decode_s", buckets=lat),
                "e2e": registry.histogram("engine_e2e_s", buckets=lat),
            }

    # -- params / levers ------------------------------------------------

    def _set_params(self, params):
        self._base_params = params
        self._params = {}
        for lever in self.cfg.levers:
            if lever == THROUGHPUT:
                from distributed_machine_learning_tpu.ops.quant import (
                    quantize_lm_params,
                )

                self._params[lever] = quantize_lm_params(params)
            else:
                self._params[lever] = params

    def swap_params(self, params, version=None) -> None:
        """Install new weights — the hot-swap fence.  Refuses while any
        sequence is in flight: the worker drains (keeps stepping with
        admission paused until ``in_flight() == 0``) first, so no
        sequence ever mixes weight versions mid-stream."""
        if self.in_flight():
            raise RuntimeError(
                f"swap_params with {self.in_flight()} sequences in "
                "flight — drain the engine first (pause_admission + "
                "step until empty)"
            )
        self._set_params(params)
        if version is not None:
            self.version = version

    def warmup(self, prompt_lens=(4,)) -> None:
        """Compile ahead of serving: run one dummy request per distinct
        prompt length through every lever's prefill + decode program
        and drain it.  A fleet replica warms up BEFORE it starts
        heartbeating — XLA compilation inside the first live ``step()``
        would otherwise starve the beat channel long enough for the
        router's staleness eviction to fire on a healthy replica."""
        hint = self._hint
        eos = self.cfg.eos_id
        # EOS off for the dummies (frozen-dataclass override, restored
        # below): an instant EOS out of prefill would retire the lane
        # before the decode program ever traced.
        object.__setattr__(self.cfg, "eos_id", None)
        try:
            for lever in self.cfg.levers:
                self._hint = lever
                for lp in prompt_lens:
                    lp = int(lp)
                    if lp + 2 > self.cfg.max_len:
                        raise ValueError(
                            f"warmup prompt_len {lp} + 2 exceeds "
                            f"max_len={self.cfg.max_len}")
                    # max_new=2: the first token retires at max_new=1
                    # straight out of prefill and the decode program
                    # would never trace.
                    self.submit(("__warmup__", lever, lp),
                                [1] * lp, max_new=2)
                self.drain()
        finally:
            self._hint = hint
            object.__setattr__(self.cfg, "eos_id", eos)

    def note_lever(self, lever: str | None) -> None:
        """Router-stamped fleet-wide regime hint; overrides the local
        scheduler until cleared with ``None``."""
        if lever is not None and lever not in (LATENCY, THROUGHPUT):
            raise ValueError(f"unknown lever {lever!r}")
        self._hint = lever

    def _pick_lever(self) -> str:
        lever = self._hint
        if lever is None and self._scheduler is not None:
            lever = self._scheduler.observe(len(self._waiting),
                                            self.in_flight())
        if lever is None:
            lever = LATENCY
        if lever not in self._dm:   # single-lever engines ignore regime
            lever = self.cfg.levers[0]
        return lever

    # -- admission ------------------------------------------------------

    def submit(self, rid, prompt, *, max_new: int | None = None,
               request: dict | None = None) -> None:
        """Queue one request.  ``prompt`` is a python token list;
        ``request`` is the fleet's request record (stage events are
        stamped onto it).  Raises ``ValueError`` if the request can
        never fit (admission control handles the *transient* full-pool
        case by leaving it queued)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        mn = self.cfg.max_new if max_new is None else int(max_new)
        if mn < 1:
            raise ValueError(f"max_new must be >= 1: {mn}")
        if len(prompt) + mn > self.cfg.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new ({mn}) exceeds "
                f"max_len={self.cfg.max_len}"
            )
        self._waiting.append(_Lane(
            rid=rid, prompt_len=len(prompt), max_new=mn, tokens=prompt,
            request=request, version=None, lever=LATENCY,
            t_submit=time.perf_counter(), t_ready=0.0, prefill_s=0.0,
        ))

    def pause_admission(self) -> None:
        self._paused = True

    def resume_admission(self) -> None:
        self._paused = False

    def abort_all(self) -> list:
        """Drop every queued and in-flight request WITHOUT completing
        it — the retired-replica path.  When the router retires this
        replica it atomically requeues everything the replica owned
        for survivors, so emitting results here would race the epoch
        fence (they would post as fenced no-ops anyway).  Frees all
        pool blocks; returns the dropped rids for the worker's audit
        trail."""
        dropped = [l.rid for l in self._lanes if l is not None]
        dropped += [l.rid for l in self._waiting]
        for lane in self._lanes:
            if lane is not None:
                self.allocator.free(lane.rid)
        self._lanes = [None] * self.cfg.max_lanes
        self._waiting.clear()
        return dropped

    # -- introspection --------------------------------------------------

    def in_flight(self) -> int:
        return sum(1 for l in self._lanes if l is not None)

    def queued(self) -> int:
        return len(self._waiting)

    def has_work(self) -> bool:
        return self.in_flight() > 0 or (
            not self._paused and bool(self._waiting)
        )

    # -- the iteration loop ---------------------------------------------

    def _stamp(self, lane: _Lane, stage: str, **extra) -> None:
        if lane.request is not None and isinstance(
            lane.request.get("events"), list
        ):
            from distributed_machine_learning_tpu.runtime.transport import (
                stamp_stage,
            )

            stamp_stage(lane.request, stage, self._by, **extra)

    def _admit(self, lever: str, completed: list) -> None:
        """Move waiting requests into free lanes while the allocator
        admits them (prefill runs here — the admitted prompt joins the
        next decode dispatch)."""
        while self._waiting and not self._paused:
            free = [i for i, l in enumerate(self._lanes) if l is None]
            if not free:
                return
            lane = self._waiting[0]
            try:
                table = self.allocator.admit(
                    lane.rid, lane.prompt_len, lane.max_new
                )
            except CacheExhausted:
                return  # head-of-line waits for a retirement
            except ValueError:
                self._waiting.pop(0)
                raise
            self._waiting.pop(0)
            nb = len(table)
            key = (lever, nb, lane.prompt_len)
            fn = self._prefill_jit.get(key)
            if fn is None:
                fn = self._prefill_jit[key] = jax.jit(partial(
                    _prefill, self._dm[lever], self._sample, nb,
                    self.cfg.block_size,
                ))
            t0 = time.perf_counter()
            self._rng, r = jax.random.split(self._rng)
            prompt = jnp.asarray([lane.tokens], jnp.int32)
            row = jnp.asarray(table, jnp.int32)
            self.pools, tok = fn(self._params[lever], self.pools, row,
                                 prompt, r)
            tok = int(jax.device_get(tok))
            lane.t_ready = time.perf_counter()
            lane.prefill_s = lane.t_ready - t0
            lane.version = self.version
            lane.lever = lever
            lane.tokens.append(tok)
            self._stamp(lane, "prefill", lever=lever)
            if self._metrics is not None:
                self._metrics["prefill"].observe(lane.prefill_s)
                self._metrics["tokens"].inc()
            if self._finished(lane, tok):
                self._retire(lane, completed)
            else:
                self._lanes[free[0]] = lane

    def _finished(self, lane: _Lane, tok: int) -> bool:
        if self.cfg.eos_id is not None and tok == self.cfg.eos_id:
            return True
        return len(lane.tokens) - lane.prompt_len >= lane.max_new

    def _retire(self, lane: _Lane, completed: list) -> None:
        self.allocator.free(lane.rid)
        now = time.perf_counter()
        decode_s = now - lane.t_ready
        e2e_s = now - lane.t_submit
        gen = len(lane.tokens) - lane.prompt_len
        eos = (self.cfg.eos_id is not None
               and lane.tokens[-1] == self.cfg.eos_id)
        self._stamp(lane, "decode", tokens=gen, lever=lane.lever)
        if self._metrics is not None:
            self._metrics["decode"].observe(decode_s)
            self._metrics["e2e"].observe(e2e_s)
            self._metrics["done"].inc()
        self.completed_total += 1
        completed.append({
            "rid": lane.rid,
            "tokens": list(lane.tokens),
            "prompt_len": lane.prompt_len,
            "generated": gen,
            "finish": "eos" if eos else "length",
            "lever": lane.lever,
            "version": lane.version,
            "prefill_s": lane.prefill_s,
            "decode_s": decode_s,
            "e2e_s": e2e_s,
            "request": lane.request,
        })

    def step(self) -> list[dict]:
        """One engine iteration; returns the requests that completed
        during it.  Safe to call with nothing in flight (admission
        still runs); a no-work step returns []."""
        completed: list[dict] = []
        lever = self._pick_lever()
        self._admit(lever, completed)
        active = [(i, l) for i, l in enumerate(self._lanes)
                  if l is not None]
        if active:
            W, mb = self.cfg.max_lanes, self._mb
            tables = np.full((W, mb), self._trash, np.int32)
            positions = np.zeros((W,), np.int32)
            toks = np.zeros((W,), np.int32)
            for i, lane in active:
                pos = self.allocator.append(lane.rid)
                tbl = self.allocator.table(lane.rid)
                tables[i, :len(tbl)] = tbl
                positions[i] = pos
                toks[i] = lane.tokens[-1]
            self._rng, r = jax.random.split(self._rng)
            self.pools, nxt = self._decode_jit[lever](
                self._params[lever], self.pools,
                jnp.asarray(tables), jnp.asarray(positions),
                jnp.asarray(toks), r,
            )
            nxt = np.asarray(jax.device_get(nxt))
            for i, lane in active:
                tok = int(nxt[i])
                lane.tokens.append(tok)
                if self._metrics is not None:
                    self._metrics["tokens"].inc()
                if self._finished(lane, tok):
                    self._lanes[i] = None
                    self._retire(lane, completed)
            # Backfill freed lanes the same step: the next admitted
            # prompt prefills NOW and decodes from the next iteration.
            if completed:
                self._admit(lever, completed)
        self.steps += 1
        if self._metrics is not None:
            st = self.allocator.stats()
            self._metrics["lanes"].set(float(self.in_flight()))
            self._metrics["queue"].set(float(len(self._waiting)))
            self._metrics["free"].set(float(st["free"]))
            self._metrics["avail"].set(float(st["available"]))
        return completed

    def drain(self, max_steps: int = 100000) -> list[dict]:
        """Step until nothing is queued or in flight (admission stays
        as-is; pause first for a swap-style drain of in-flight only)."""
        out: list[dict] = []
        for _ in range(max_steps):
            if not (self.in_flight()
                    or (not self._paused and self._waiting)):
                break
            out.extend(self.step())
        return out
