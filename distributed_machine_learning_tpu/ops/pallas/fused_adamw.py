"""Fused AdamW update as a Pallas TPU kernel — the update-phase lever.

The round-9 per-phase spans put the optimizer update on the critical
path once the weight-update all-gather was overlapped (docs/PERF.md:
the update phase is what remains between the backward and the next
step's dispatch).  The XLA spelling of AdamW
(``train/adamw.py::adamw_update``) is a chain of elementwise ops over
four full-size vectors (p, mu, nu, g) whose intermediates (the decayed
moments, the bias-corrected terms, the adam step) XLA may or may not
keep fused; this kernel pins the whole update — moment update, bias
correction, weight decay, parameter update, and the output cast back
to the parameter dtype (bf16 params stay bf16) — to ONE pass: each
tile is read once, updated entirely in-register, and written once.
Memory traffic is the floor: 4 reads + 3 writes of the parameter
vector, nothing else.

Update rule (bit-for-bit the expressions of ``adamw_update``; torch
``optim.AdamW`` semantics, ``t = step + 1``)::

    mu  = b1·mu + (1−b1)·g
    nu  = b2·nu + (1−b2)·g²
    p  −= lr · ( (mu/bc1) / (√(nu/bc2) + eps) + wd·p )

``lr`` and the bias corrections ``bc1 = 1−b1ᵗ`` / ``bc2 = 1−b2ᵗ`` are
traced scalars (schedules and the step counter stay dynamic — no
recompile per step), shipped to the kernel through one SMEM row.

Parity contract (the documented ulp bound, measured on the CPU CI
backend and gated in ``tests/test_pallas_fusion.py``): a SINGLE update
from identical state stays within **8 ulp** on params and moments in
any fusion context — the FMA-contraction freedom of the fused
expression chain vs XLA's fusion of the reference (zero-moment first
steps are exact: contraction has nothing to perturb; the measured
worst case from nonzero state is 5 ulp on params).  Multi-step
TRAJECTORIES compound that last-bit freedom through re-evaluated
gradients like any numeric perturbation, so the 3-step fixed-seed gate
is relative: ≤ 5e-6 on the parameter vector (measured 6e-8 on the
ZeRO-1 keystone — two orders of headroom).  This freedom is
irreducible without deoptimizing the reference (pinning its fusion),
which is why AdamW's contract is a bound where the ring codec's is
bitwise (its exact-product construction removes the freedom).

Consumed via ``AdamWConfig(fused=True)`` (CLI ``--fused-update``):
``train/adamw.py::adamw_update`` dispatches here per leaf, which makes
every step builder — the replicated step, ZeRO-1, ZeRO-3/FSDP and
their overlap builds, the LM/pipeline steps — pick the kernel up
through the optimizer registry with no step-builder changes.  The
flat-shard builds (zero1/fsdp) are the marquee case: one leaf, the
whole padded parameter vector, in one kernel launch inside the update
program XLA can least afford to bloat.

Leaves are flattened to [L] and viewed as [rows, 128] lanes,
zero-padded to the f32 tile quantum; a zero-padded row updates to
exactly zero (g=0, p=0 → mu=nu=0, adam term 0, decay 0) and is sliced
off.  Grid is 1-D over row blocks, all parallel (no cross-block
state); the three outputs alias their input buffers (p, mu, nu) so the
update is genuinely in place, matching the donation story the zero1
audit asserts through the kernel boundary (dmlcheck DML101).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributed_machine_learning_tpu.ops.pallas.common import (
    LANES as _LANES,
    _interpret,
    lane_tiles,
    padded_lane_rows,
    pick_block,
    pltpu,
    tile_compiler_params,
)

# f32 tiles need (8, 128); bf16 params need (16, 128) — pad rows to 16
# so one layout serves both parameter dtypes.
_ROW_QUANTUM = 16
_BLOCK_ROWS = 512


def _adamw_kernel(s_ref, p_ref, m_ref, v_ref, g_ref, po_ref, mo_ref, vo_ref,
                  *, beta1, beta2, eps, weight_decay):
    lr = s_ref[0]
    bc1 = s_ref[1]
    bc2 = s_ref[2]
    g32 = g_ref[...].astype(jnp.float32)
    p32 = p_ref[...].astype(jnp.float32)
    m = beta1 * m_ref[...] + (1.0 - beta1) * g32
    v = beta2 * v_ref[...] + (1.0 - beta2) * jnp.square(g32)
    adam_term = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    p32 = p32 - lr * (adam_term + weight_decay * p32)
    po_ref[...] = p32.astype(po_ref.dtype)
    mo_ref[...] = m
    vo_ref[...] = v


_tiles = lane_tiles


def fused_adamw_leaf(
    p: jax.Array,
    mu: jax.Array,
    nu: jax.Array,
    g: jax.Array,
    lr,
    bc1,
    bc2,
    *,
    beta1: float,
    beta2: float,
    eps: float,
    weight_decay: float,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One leaf's fused update: ``(new_p, new_mu, new_nu)`` with
    ``new_p`` in ``p.dtype`` (the bf16 cast happens in-register) and
    the moments in fp32.  ``lr``/``bc1``/``bc2`` may be traced scalars.
    """
    shape, out_dtype = p.shape, p.dtype
    length = int(p.size)
    if length == 0:
        return p, mu, nu
    rows = padded_lane_rows(length, _ROW_QUANTUM)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(bc1, jnp.float32),
        jnp.asarray(bc2, jnp.float32),
    ])
    p_t = _tiles(p.reshape(-1), rows, out_dtype)
    m_t = _tiles(mu.reshape(-1), rows, jnp.float32)
    v_t = _tiles(nu.reshape(-1), rows, jnp.float32)
    g_t = _tiles(g.reshape(-1), rows, g.dtype)
    br = pick_block(rows, _BLOCK_ROWS, _ROW_QUANTUM) or rows
    tile = pl.BlockSpec((br, _LANES), lambda b: (b, 0))
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(
            _adamw_kernel, beta1=beta1, beta2=beta2, eps=eps,
            weight_decay=weight_decay,
        ),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((3,), lambda b: (0,), memory_space=pltpu.SMEM),
            tile, tile, tile, tile,
        ],
        out_specs=(tile, tile, tile),
        out_shape=(
            jax.ShapeDtypeStruct((rows, _LANES), out_dtype),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ),
        # In-place update: params/moments alias their updated twins —
        # the donation the step builders take on the state buffers
        # stays real through the kernel boundary.
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=_interpret(),
        **tile_compiler_params(("parallel",)),
    )(scalars, p_t, m_t, v_t, g_t)
    unpack = lambda a, dt: a.reshape(-1)[:length].reshape(shape).astype(dt)
    return (
        unpack(new_p, out_dtype),
        unpack(new_m, jnp.float32),
        unpack(new_v, jnp.float32),
    )
