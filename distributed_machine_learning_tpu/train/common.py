"""Shared pieces of the train-step implementations.

Both the replicated-DP step (``train/step.py``) and the ZeRO-3/FSDP step
(``parallel/fsdp.py``) need the same forward/loss/mutable-BatchNorm
plumbing and the same per-step, per-mesh-position RNG keying — factored
here (dependency-free of ``parallel/``) so the two cannot drift apart and
break the FSDP-vs-replicated-DP equivalence the tests assert.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from distributed_machine_learning_tpu.train.losses import cross_entropy_loss


def step_rng(rng, step_ctr, axis_name: str | None):
    """Per-step augmentation key; folds in the mesh position so each data
    shard draws independent crops/flips the way each reference node draws
    from its own torch RNG (``part2/2a/main.py:199``)."""
    r = jax.random.fold_in(rng, step_ctr)
    if axis_name is not None:
        r = jax.random.fold_in(r, lax.axis_index(axis_name))
    return r


def make_loss_fn(model, batch_stats, x, labels, train: bool):
    """Build ``loss_fn(params) -> (loss, (logits, new_batch_stats))``.

    Handles the three BatchNorm cases: BN model in train mode (mutable
    running stats), BN model in eval mode, BN-free model (empty stats).
    """

    def run(params):
        variables: dict[str, Any] = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            if train:
                logits, mutated = model.apply(
                    variables, x, train=True, mutable=["batch_stats"]
                )
                return logits, mutated["batch_stats"]
            logits = model.apply(variables, x, train=False)
            return logits, batch_stats
        logits = model.apply(variables, x, train=train)
        return logits, {}

    def loss_fn(params):
        logits, new_stats = run(params)
        return cross_entropy_loss(logits, labels), (logits, new_stats)

    return loss_fn
