# dmlcheck-virtual-path: tests/test_fixture.py
"""DML006 clean case: the gang chaos test is marked, and an ordinary
8-device test needs no marker."""
import subprocess
import sys

import pytest


def _run_gang(root):
    return subprocess.run(
        [sys.executable, "-m", "distributed_machine_learning_tpu.cli.gang",
         "--workers", "4", "--gang-dir", root],
        capture_output=True, timeout=120,
    )


@pytest.mark.slow
@pytest.mark.faultinject
def test_gang_survives_chaos(tmp_path):
    assert _run_gang(str(tmp_path)).returncode == 0


def test_small_mesh(make_mesh):
    mesh = make_mesh(8)
    assert mesh is not None
