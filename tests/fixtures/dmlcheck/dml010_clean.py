# dmlcheck-virtual-path: distributed_machine_learning_tpu/telemetry/fixture.py
"""DML010 clean case: JSONL streams append; truncate-mode is fine for
non-stream artifacts (a rendered report)."""


def start_metrics(path):
    return open(path + "/metrics.jsonl", "a")


def write_report(path, text):
    with open(path + "/report.txt", "w") as f:
        f.write(text)
