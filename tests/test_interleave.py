"""dmlcheck layer 3 (ISSUE 15): the deterministic interleaving
explorer over the gang control plane.

Tier-1 keystones: ``test_quick_sweep_is_clean_and_bounded`` (the
fixed tree survives exhaustive-small-config exploration — the layer-3
analogue of ``test_package_is_clean``) and the mutation gates
(with a known bug re-introduced the explorer MUST rediscover it
deterministically, and its reproducer must replay to the same failure
twice).  The scaled-up full sweep rides behind ``slow``.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from distributed_machine_learning_tpu.analysis.interleave import (
    MUTATIONS,
    SCENARIOS,
    _run_schedule,
    _Scenario,
    apply_mutations,
    explore,
    format_trace,
    replay_file,
    run_layer3,
)
from distributed_machine_learning_tpu.runtime import coordinator as _coord
from distributed_machine_learning_tpu.runtime.transport import (
    InProcTransport,
    TcpGangServer,
)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DMLCHECK = os.path.join(REPO, "tools", "dmlcheck.py")


# ---------------------------------------------------------------------------
# Scheduler mechanics
# ---------------------------------------------------------------------------

def test_seam_is_noop_without_scheduler():
    # The runtime must be oblivious to layer 3 when nothing is
    # installed: points vanish, blocking waits fall back to real ones.
    _coord._sched_point("hub:beats:w")
    assert _coord._sched_block("tcp:inflight:wait", lambda: True) is False


def test_identical_choices_give_identical_traces():
    build = SCENARIOS["abort_race"]["quick"]
    first = _run_schedule(build, ())
    again = _run_schedule(build, ())
    assert first.choices == again.choices
    assert first.trace == again.trace
    assert first.violations == again.violations == []
    replayed = _run_schedule(build, first.choices)
    assert replayed.trace == first.trace


def test_explore_is_deterministic():
    build = SCENARIOS["epoch_fence"]["quick"]
    a = explore(build, max_schedules=500)
    b = explore(build, max_schedules=500)
    assert a.schedules == b.schedules > 1
    assert not a.capped and a.violation is None


def test_scheduler_detects_deadlock():
    # Two threads each blocked on a predicate only the other could
    # satisfy — but neither ever does: the scheduler must call it a
    # deadlock, not hang.
    flags = {"a": False, "b": False}

    def build():
        def left():
            _coord._sched_block("test:left:wait", lambda: flags["a"])

        def right():
            _coord._sched_block("test:right:wait", lambda: flags["b"])

        return _Scenario([("left", left), ("right", right)],
                         check=lambda: [])

    res = _run_schedule(build, ())
    assert res.deadlock
    assert any("deadlock" in v for v in res.violations)


def test_blocked_thread_resumes_when_predicate_turns_true():
    state = {"ready": False, "resumed": False}

    def build():
        def waiter():
            _coord._sched_block("test:chan:wait",
                                lambda: state["ready"])
            state["resumed"] = True

        def setter():
            _coord._sched_point("test:chan:w")
            state["ready"] = True

        return _Scenario([("waiter", waiter), ("setter", setter)],
                         check=lambda: [])

    res = _run_schedule(build, ())
    assert not res.violations and not res.deadlock
    assert state["resumed"]


def test_scenario_thread_errors_become_violations():
    def build():
        def boom():
            raise RuntimeError("seeded failure")

        return _Scenario([("boom", boom)], check=lambda: [])

    res = _run_schedule(build, ())
    assert any("seeded failure" in v for v in res.violations)


def test_chooser_survives_stale_prefix():
    # A reproducer replayed against an edited scenario must degrade to
    # defaults, not crash the scheduler.
    build = SCENARIOS["epoch_fence"]["quick"]
    res = _run_schedule(build, (99, 99, 99))
    assert res.violations == []


# ---------------------------------------------------------------------------
# The tier-1 gate: the fixed tree is clean, quickly
# ---------------------------------------------------------------------------

def test_quick_sweep_is_clean_and_bounded(tmp_path):
    t0 = time.monotonic()
    findings, stats = run_layer3(quick=True,
                                 repro_dir=str(tmp_path / "repros"))
    elapsed = time.monotonic() - t0
    assert findings == [], [f.message for f in findings]
    assert elapsed < 30.0, (
        f"--layer3 --quick took {elapsed:.1f}s (budget 30s): "
        f"{stats}")
    assert set(stats["scenarios"]) == set(SCENARIOS)
    for name, entry in stats["scenarios"].items():
        assert entry["violations"] == 0, (name, entry)
        assert entry["schedules"] >= 1


# ---------------------------------------------------------------------------
# Mutation gates: re-introduced bugs MUST be rediscovered
# ---------------------------------------------------------------------------

def _gate(tmp_path, scenario, mutation):
    findings, stats = run_layer3(
        quick=True, scenarios=[scenario], mutate=(mutation,),
        repro_dir=str(tmp_path))
    assert len(findings) == 1, (
        f"{mutation} not rediscovered: {stats}")
    f = findings[0]
    assert f.rule == "DML301" and f.layer == 3
    assert f.file == f"layer3:{scenario}"
    repro = stats["scenarios"][scenario]["reproducer"]
    assert os.path.exists(repro)
    assert repro in f.message  # the finding carries its reproducer
    return f, repro


def test_dedup_eviction_bug_is_rediscovered(tmp_path):
    f, repro = _gate(tmp_path, "dedup_inflight", "dedup-evict")
    assert "in-flight" in f.message
    # The reproducer replays to the SAME failure twice — a CI failure
    # is a deterministic test case, not a flake.
    r1 = replay_file(repro)
    r2 = replay_file(repro)
    assert r1 == r2
    assert r1["reproduced"] and r1["violations"]
    assert r1["violations"] == json.load(open(repro))["violations"]


def test_epoch_fence_bug_is_rediscovered(tmp_path):
    f, repro = _gate(tmp_path, "epoch_fence", "epoch-unlocked")
    assert "drained" in f.message
    r1 = replay_file(repro)
    r2 = replay_file(repro)
    assert r1 == r2 and r1["reproduced"]
    # The minimized trace names the actual TOCTOU window.
    trace = format_trace(r1["trace"])
    assert "zombie" in trace and "hub:epoch:gap" in trace


def test_drain_promote_bug_is_rediscovered(tmp_path):
    # ISSUE 16: the serving drain/promote handoff.  With the result
    # fence's epoch check hoisted outside the lock, a retiring
    # replica's late post parks in the TOCTOU window through the
    # epoch bump and lands AFTER the handoff.
    f, repro = _gate(tmp_path, "drain_promote", "result-unfenced")
    assert "late result" in f.message
    r1 = replay_file(repro)
    r2 = replay_file(repro)
    assert r1 == r2 and r1["reproduced"]
    trace = format_trace(r1["trace"])
    assert "zombie" in trace and "hub:sepoch:gap" in trace


def test_weight_swap_bug_is_rediscovered(tmp_path):
    # ISSUE 18: the continuous-deployment hot-swap.  With the post
    # fence's weights-version check hoisted outside the lock, an
    # old-version compute's post parks in the TOCTOU window through
    # commit_weights' version flip and lands AFTER the swap committed
    # — a duplicate completion for a request the post-swap compute
    # already answered.
    f, repro = _gate(tmp_path, "weight_swap", "swap-unfenced")
    assert "old-version post" in f.message
    r1 = replay_file(repro)
    r2 = replay_file(repro)
    assert r1 == r2 and r1["reproduced"]
    trace = format_trace(r1["trace"])
    assert "zombie" in trace and "hub:swv:gap" in trace


def test_continuous_batching_bug_is_rediscovered(tmp_path):
    # ISSUE 19: the paged-KV admission race.  With the allocator's
    # capacity check hoisted outside the lock that binds the blocks,
    # two admitters park in the TOCTOU window, both pass against the
    # same headroom, and the pool overcommits — the reserve-on-admit
    # guarantee breaks while decodes are in flight.
    f, repro = _gate(tmp_path, "continuous_batching", "admit-unlocked")
    r1 = replay_file(repro)
    r2 = replay_file(repro)
    assert r1 == r2 and r1["reproduced"]
    assert any("overcommitted" in v or "pop from empty" in v
               for v in r1["violations"]), r1["violations"]
    # The minimized trace names the actual TOCTOU window.
    trace = format_trace(r1["trace"])
    assert "admit-" in trace and "kvb:admit:gap" in trace


def test_mutations_restore_the_fixed_methods(tmp_path):
    orig_evict = TcpGangServer.__dict__["_evict_seen_locked"]
    orig_locked = InProcTransport.__dict__["_locked"]
    with apply_mutations(("dedup-evict", "epoch-unlocked")):
        assert TcpGangServer.__dict__["_evict_seen_locked"] \
            is not orig_evict
        assert InProcTransport.__dict__["_locked"] is not orig_locked
    assert TcpGangServer.__dict__["_evict_seen_locked"] is orig_evict
    assert InProcTransport.__dict__["_locked"] is orig_locked
    # And the fixed tree stays clean on the gate scenarios afterwards.
    findings, _ = run_layer3(
        quick=True, scenarios=["dedup_inflight", "epoch_fence"],
        repro_dir=str(tmp_path))
    assert findings == []


def test_unknown_mutation_and_scenario_are_loud():
    with pytest.raises(ValueError, match="unknown mutation"):
        with apply_mutations(("no-such-bug",)):
            pass
    with pytest.raises(ValueError, match="unknown scenario"):
        run_layer3(quick=True, scenarios=["no_such_protocol"])
    assert set(MUTATIONS) == {"dedup-evict", "epoch-unlocked",
                              "result-unfenced", "swap-unfenced",
                              "admit-unlocked"}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _run_tool(*args):
    return subprocess.run(
        [sys.executable, "-S", "-E", DMLCHECK, *args],
        capture_output=True, text=True, timeout=180,
    )


def test_cli_layer3_quick_json_is_clean():
    res = _run_tool("--layer3", "--quick", "--json")
    assert res.returncode == 0, res.stdout + res.stderr
    verdict = json.loads(res.stdout)
    assert verdict["clean"] is True
    # Per-layer / per-rule timing for CI budget regressions.
    timing = verdict["timing"]
    assert {"layer1_s", "layer2_s", "layer3_s", "rules"} <= set(timing)
    assert timing["layer3_s"] > 0 and timing["layer2_s"] == 0
    assert any(k.startswith("layer3:") for k in timing["rules"])
    assert "DML013" in timing["rules"] and "DML014" in timing["rules"]
    assert verdict["layer3"]["size"] == "quick"


def test_cli_replay_fails_the_same_way_twice(tmp_path):
    _, stats = run_layer3(
        quick=True, scenarios=["epoch_fence"],
        mutate=("epoch-unlocked",), repro_dir=str(tmp_path))
    repro = stats["scenarios"]["epoch_fence"]["reproducer"]
    r1 = _run_tool("--replay", repro)
    r2 = _run_tool("--replay", repro)
    assert r1.returncode == r2.returncode == 1
    assert r1.stdout == r2.stdout
    assert "VIOLATION" in r1.stdout
    assert "schedule point" in r1.stdout  # the annotated trace header
    bad = _run_tool("--replay", str(tmp_path / "missing.json"))
    assert bad.returncode == 2


def test_cli_layer3_rules_require_the_flag():
    res = _run_tool("--rules", "DML301")
    assert res.returncode == 2
    assert "layer-3" in res.stderr.lower()


# ---------------------------------------------------------------------------
# The full sweep (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_full_sweep_is_clean(tmp_path):
    findings, stats = run_layer3(quick=False,
                                 repro_dir=str(tmp_path / "repros"))
    assert findings == [], [f.message for f in findings]
    # Full mode explores at least as much as quick everywhere.
    _, qstats = run_layer3(quick=True,
                           repro_dir=str(tmp_path / "qrepros"))
    for name in SCENARIOS:
        assert (stats["scenarios"][name]["schedules"]
                >= min(qstats["scenarios"][name]["schedules"], 100))


@pytest.mark.slow
def test_full_sweep_rediscovers_dedup_bug(tmp_path):
    findings, _ = run_layer3(
        quick=False, scenarios=["dedup_inflight"],
        mutate=("dedup-evict",), repro_dir=str(tmp_path))
    assert len(findings) == 1 and findings[0].rule == "DML301"
