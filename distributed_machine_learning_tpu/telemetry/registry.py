"""Process-wide metrics registry: named counters, gauges, histograms.

The reference's only numbers are end-of-run totals transcribed by hand
(SURVEY.md §5); production-scale runs diagnose stragglers from live
counters and tail latencies ("Massively Distributed SGD", arxiv
1811.05233, attributes its wins to exactly this per-phase accounting).
This registry is the in-process half of that story: every robustness
event, queue depth, and phase duration lands in a named instrument the
moment it happens, and the sink layer (``telemetry/sink.py``) makes the
result crash-safe on disk.

Semantics follow the Prometheus data model, minimally:

- :class:`Counter` — monotonically non-decreasing; ``inc(n)``.
- :class:`Gauge` — last-write-wins; ``set(v)``.
- :class:`Histogram` — FIXED buckets chosen at creation (no rebinning,
  so merge/export is trivial) plus exact count/sum/min/max, exposing
  p50/p95/p99 by linear interpolation inside the owning bucket.

Instruments are keyed by ``(name, sorted(labels))`` — repeated
``registry.counter("x", kind="y")`` calls return the same object, so
call sites never need to cache handles.  Creation takes a lock;
updates are plain attribute writes (GIL-atomic, same contract as
``runtime/faults.FaultEvents``), cheap enough for per-step use.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterable


def default_time_buckets() -> tuple[float, ...]:
    """Exponential seconds buckets, 100 µs .. ~2 min — wide enough for a
    CPU-host step AND a checkpoint serialize in the same histogram."""
    out = []
    b = 1e-4
    while b < 120.0:
        out.append(b)
        b *= 2.0
    return tuple(out)


def default_latency_buckets() -> tuple[float, ...]:
    """Request-latency seconds buckets, 0.5 ms .. ~16 s at √2 steps —
    the ISSUE 16 bugfix preset.  Histogram buckets are fixed at
    construction, and :func:`default_time_buckets`' doubling grid
    (tuned for multi-second train steps) puts an entire
    millisecond-scale serving distribution inside one or two buckets,
    flattening p50/p95/p99 into the same interpolated value.  The √2
    ratio doubles the resolution exactly where per-request latencies
    live while still reaching tail-amplification territory."""
    out = []
    b = 5e-4
    while b < 16.0:
        out.append(b)
        b *= 2.0 ** 0.5
    return tuple(out)


class Counter:
    """Monotonic counter.  ``inc`` with a negative amount is an error —
    a decreasing "counter" is a gauge wearing the wrong name."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int | float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: tuple):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Histogram:
    """Fixed-bucket histogram with exact count/sum/min/max.

    ``buckets`` are upper bounds (ascending); an implicit +inf bucket
    catches the overflow.  Quantiles interpolate linearly inside the
    bucket that crosses the target rank — the standard fixed-bucket
    estimate — except the +inf bucket, which reports the exact observed
    max (unbounded interpolation would be fiction).
    """

    __slots__ = ("name", "labels", "bounds", "counts", "count", "sum",
                 "min", "max")

    def __init__(self, name: str, labels: tuple,
                 buckets: Iterable[float] | None = None):
        self.name = name
        self.labels = labels
        bounds = tuple(sorted(buckets)) if buckets else default_time_buckets()
        if not bounds:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: the +inf bucket
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Estimated ``q``-quantile (``q`` in [0, 1]) from the buckets."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                if i == len(self.bounds):  # +inf bucket: report exact max
                    return self.max
                lo = self.bounds[i - 1] if i > 0 else min(self.min, 0.0)
                hi = self.bounds[i]
                frac = (rank - seen) / c
                # Clamp into the observed range so a single-bucket
                # histogram never reports below its own min / above max.
                return min(max(lo + frac * (hi - lo), self.min), self.max)
            seen += c
        return self.max

    def quantiles(self) -> dict:
        return {
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "max": self.max if self.count else 0.0,
        }


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Get-or-create home for every instrument in the process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[tuple, object] = {}

    def _get(self, cls, name: str, labels: dict, **kw):
        key = (cls.__name__, name, _label_key(labels))
        inst = self._instruments.get(key)
        if inst is None:
            with self._lock:
                inst = self._instruments.get(key)
                if inst is None:
                    inst = cls(name, _label_key(labels), **kw)
                    self._instruments[key] = inst
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, buckets: Iterable[float] | None = None,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    # -- export ----------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready dump of every instrument (quantiles included for
        histograms) — the ``registry.json`` payload."""
        out: dict = {"counters": [], "gauges": [], "histograms": []}
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            entry: dict = {"name": inst.name, "labels": dict(inst.labels)}
            if isinstance(inst, Counter):
                entry["value"] = inst.value
                out["counters"].append(entry)
            elif isinstance(inst, Gauge):
                entry["value"] = inst.value
                out["gauges"].append(entry)
            else:
                entry.update(
                    count=inst.count, sum=inst.sum, mean=inst.mean,
                    **inst.quantiles(),
                )
                out["histograms"].append(entry)
        return out

    def to_prometheus(self) -> str:
        """Prometheus textfile-collector format (final values — the
        node-exporter textfile pattern, not a live scrape endpoint).

        One ``# TYPE`` line per metric FAMILY (name), with every label
        series grouped under it — the exposition format allows at most
        one TYPE per family, and promtool rejects duplicates.

        Label VALUES are escaped per the exposition format (backslash,
        double-quote, newline): an abort reason or fault spec carried
        as a label would otherwise break the line grammar and take the
        whole textfile down with it — the scrape that fails is exactly
        the post-mortem one.
        """

        def esc(v) -> str:
            return (str(v).replace("\\", r"\\").replace('"', r"\"")
                    .replace("\n", r"\n"))

        def fmt(name, labels, value, extra_labels=()):
            pairs = [*labels, *extra_labels]
            lab = ("{" + ",".join(f'{k}="{esc(v)}"' for k, v in pairs)
                   + "}" if pairs else "")
            return f"{name}{lab} {value}"

        with self._lock:
            instruments = list(self._instruments.values())
        families: dict[str, tuple[str, list]] = {}
        for inst in instruments:
            kind = ("counter" if isinstance(inst, Counter)
                    else "gauge" if isinstance(inst, Gauge)
                    else "histogram")
            families.setdefault(inst.name, (kind, []))[1].append(inst)
        lines = []
        for name, (kind, insts) in families.items():
            lines.append(f"# TYPE {name} {kind}")
            for inst in insts:
                if kind in ("counter", "gauge"):
                    lines.append(fmt(name, inst.labels, inst.value))
                    continue
                cum = 0
                for bound, c in zip(inst.bounds, inst.counts):
                    cum += c
                    lines.append(fmt(f"{name}_bucket", inst.labels, cum,
                                     (("le", repr(bound)),)))
                lines.append(fmt(f"{name}_bucket", inst.labels, inst.count,
                                 (("le", "+Inf"),)))
                lines.append(fmt(f"{name}_sum", inst.labels, inst.sum))
                lines.append(fmt(f"{name}_count", inst.labels, inst.count))
        return "\n".join(lines) + "\n" if lines else ""
