"""Weight-only int8 serving quantization: module + checkpoint converter.

Two pieces on top of the Pallas kernel (``ops/pallas/quant_matmul.py``):

- :class:`QuantDenseGeneral` — the drop-in projection module the decode
  model uses when ``weight_quant="int8"``: params are ``w_q`` (int8,
  [D_in_flat, K_out_flat]), ``scale`` (f32, [K]), ``bias`` (original
  shape), and the matmul is the int8-reading kernel.  Input/output axis
  grouping mirrors ``nn.DenseGeneral`` so activations are bit-shaped
  identically to the unquantized model.
- :func:`quantize_lm_params` — walks a trained ``TransformerLM`` params
  tree and rewrites every ``kernel``-bearing projection to that layout
  (per-output-channel symmetric int8, ``quantize_int8``).  Embeddings
  and LayerNorms pass through untouched (a gather and O(D) vectors —
  no bandwidth to win), as does anything else without a ``kernel``.

Why serving-only: quantized weights are constants of the decode
program; training keeps full-precision master weights (the usual
weight-only recipe).  The reference has no inference path at all
(part1/main.py:62-77 is classification eval) — this is beyond-parity
capability, measured in docs/PERF.md.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_machine_learning_tpu.ops.pallas.quant_matmul import (
    int8_matmul,
    quantize_int8,
)


class QuantDenseGeneral(nn.Module):
    """``nn.DenseGeneral``-shaped projection over int8 weights.

    ``out_features``: the output axis shape appended to the input's
    leading axes (e.g. ``(3, H, Dh)`` for the fused qkv, ``(V,)`` for
    the head); ``n_in_axes``: trailing input axes contracted (2 for the
    attention out-projection's [H, Dh]).  The flattened kernel lives as
    ``w_q``/``scale``; ``bias`` keeps the unquantized module's shape so
    :func:`quantize_lm_params` can pass it through unchanged.
    """

    out_features: tuple[int, ...]
    n_in_axes: int = 1
    compute_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_shape = x.shape[-self.n_in_axes:]
        d_in = math.prod(in_shape)
        k_out = math.prod(self.out_features)
        w_q = self.param(
            "w_q", nn.initializers.zeros, (d_in, k_out), jnp.int8
        )
        scale = self.param(
            "scale", nn.initializers.ones, (k_out,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, self.out_features, jnp.float32
        )
        lead = x.shape[: x.ndim - self.n_in_axes]
        rows = math.prod(lead) if lead else 1
        y = int8_matmul(x.reshape(rows, d_in), w_q, scale)
        y = y.reshape(*lead, *self.out_features).astype(self.compute_dtype)
        return y + bias.astype(self.compute_dtype)


# Module names whose kernels contract TWO trailing input axes (the
# attention out-projection's [H, Dh] — nn.DenseGeneral(axis=(-2, -1))).
_TWO_AXIS_MODULES = frozenset({"out"})


def _quantize_module(name: str, leaves: dict) -> dict:
    kernel = leaves["kernel"]
    n_in = 2 if name in _TWO_AXIS_MODULES else 1
    if name in _TWO_AXIS_MODULES and kernel.ndim != 3:
        # The two-input-axis flatten is keyed on the module NAME alone,
        # so validate the structure it assumes: the attention
        # out-projection's kernel is [H, Dh, E].  Any other module that
        # happens to be named 'out' would otherwise be silently
        # mis-flattened into wrong serving weights.
        raise ValueError(
            f"module {name!r} is flattened over two input axes "
            f"(attention out-projection, kernel rank 3) but its kernel "
            f"has rank {kernel.ndim} {kernel.shape}; rename the module "
            "or extend _TWO_AXIS_MODULES' rule"
        )
    if kernel.ndim < n_in + 1:
        raise ValueError(
            f"module {name!r}: kernel rank {kernel.ndim} leaves no "
            f"output axis after {n_in} input axes"
        )
    d_in = math.prod(kernel.shape[:n_in])
    q, scale = quantize_int8(jnp.reshape(kernel, (d_in, -1)))
    out = {"w_q": q, "scale": scale}
    if "bias" in leaves:
        out["bias"] = leaves["bias"]
    return out


def _quantize_expert_module(leaves: dict) -> dict:
    """A ``MoEMLP`` module's params → its ``weight_quant="int8"``
    layout: the [E, D_in, D_out] expert kernels quantize per-expert
    per-output-channel (a vmapped :func:`quantize_int8` over the expert
    axis), biases pass through, and the ROUTER stays f32 — its [D, E]
    matmul has no bandwidth to win and its argmax decides the routing
    (``models/moe.py::MoEMLP``)."""
    qi, si = jax.vmap(quantize_int8)(leaves["w_in"])
    qo, so = jax.vmap(quantize_int8)(leaves["w_out"])
    return {
        "router": leaves["router"],
        "w_in_q": qi, "w_in_scale": si, "b_in": leaves["b_in"],
        "w_out_q": qo, "w_out_scale": so, "b_out": leaves["b_out"],
    }


def quantize_lm_params(params) -> dict:
    """Trained ``TransformerLM`` / ``MoETransformerLM`` params → the
    ``weight_quant="int8"`` decode model's structure.  Dense projections
    (any module with a ``kernel``) go per-output-channel int8; MoE
    expert modules (the ``w_in``/``w_out`` leaves) go per-expert
    per-output-channel with the router left f32.  Pure function of
    arrays — jit-safe, and cheap enough to run once at serving setup."""

    def walk(name: str, node):
        if isinstance(node, dict) or hasattr(node, "items"):
            node = dict(node)
            if "kernel" in node:
                return _quantize_module(name, node)
            if "w_in" in node and "w_out" in node:
                return _quantize_expert_module(node)
            return {k: walk(k, v) for k, v in node.items()}
        return node

    return walk("", params)
