"""dmlcheck — static analysis for distributed-correctness invariants.

Every hard bug this repo has shipped or fixed belongs to a recurring,
mechanically detectable class: the restore-then-donate heap corruption
(ISSUE 1), cross-host wall-clock comparisons the heartbeat sampler had
to ban (ISSUE 6), ledgers that must fsync before ``os._exit`` (ISSUE 3),
and the critical-path all-gather in the zero1 weight update that
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arxiv 2004.13336) exists to eliminate.  This package turns
that tribal knowledge into a checker:

- **Layer 1** (:mod:`.ast_rules`): stdlib-only AST rules over the
  package source — importable and runnable WITHOUT jax, fast enough for
  tier-1 (``tests/test_dmlcheck.py::test_package_is_clean``).
- **Layer 2** (:mod:`.program_audit`): jaxpr/HLO audit passes that lower
  real train steps and assert structural properties of the COMPILED
  program (donation actually taken, no sync all-gather on the weight-
  update critical path, collective wire bytes equal to the static
  accounting).  Imports jax lazily, inside the audit functions.

Front door: ``tools/dmlcheck.py`` (``--json`` for machine-readable
verdicts, consistent with ``ckpt_verify --json``).  Justified
suppressions live in the checked-in ``dmlcheck_baseline.json``.
"""

from distributed_machine_learning_tpu.analysis.findings import (  # noqa: F401
    BaselineError,
    Finding,
    apply_baseline,
    findings_to_json,
    load_baseline,
)
