"""Tracing / profiling + structured per-step metrics.

The reference's observability is a hand-rolled wall-clock harness
(``part1/main.py:36,53-58``) plus out-of-band dstat plots in its report
(group25.pdf p.4,7) — SURVEY.md §5.  TPU-native equivalents:

- :func:`trace` — context manager around ``jax.profiler`` producing an
  XPlane/Perfetto trace directory (the principled replacement for the
  report's external CPU/network plots: the trace shows MXU occupancy,
  HBM traffic, and ICI collective time per step).
- :class:`MetricsLogger` — per-step structured metrics (step, loss,
  wall-clock) accumulated in memory and flushed to CSV and/or JSONL,
  rank-0 gated; feeds the scaling-sweep harness.
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` wrapper so driver
  phases (train/eval/checkpoint) show up as named spans in the trace.
"""

from __future__ import annotations

import contextlib
import csv
import json
import os
import time
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(log_dir: str | os.PathLike | None):
    """Profile the enclosed block with ``jax.profiler`` into `log_dir`.

    No-op when `log_dir` is falsy, so call sites can thread a CLI flag
    straight through.  View the result with TensorBoard's profile plugin
    or Perfetto (the trace directory contains ``*.xplane.pb``).
    """
    if not log_dir:
        yield
        return
    log_dir = os.fspath(log_dir)
    os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span in the profiler timeline (host side)."""
    return jax.profiler.TraceAnnotation(name)


@dataclass
class MetricsLogger:
    """Per-step metric rows; flush to CSV / JSONL, rank-0 gated.

    Rows are plain dicts; the column set is the union over rows (missing
    keys serialize empty in CSV, absent in JSONL).

    Two modes.  **Buffered** (``path=None``, the historical default):
    rows accumulate in memory and ``save()`` writes the whole file —
    fine for benches that exit cleanly.  **Streaming** (``path=`` a
    non-CSV target): rows are ALSO appended to the file as they land,
    through a crash-safe sink (``telemetry/sink.py``: flush+fsync every
    ``flush_every`` rows, rank-0 gated) — a crash keeps every flushed
    row, and with ``append=True`` (the CLI sets it for ``--resume``
    runs) a restart into the same path APPENDS to the survivor rows
    instead of truncating them; fresh runs truncate, the historical
    semantics.  ``save()``
    to the streaming path is then just a final flush.  CSV cannot
    stream (the header is the union of columns, unknowable until the
    end), so ``.csv`` targets stay buffered.

    In streaming mode ``rows`` stays EMPTY — the disk is the buffer
    (duplicating a long run's history in host memory is the design the
    sink replaces); ``count`` tracks rows logged in both modes, and
    ``save()`` accepts only the streamed path.
    """

    rows: list[dict] = field(default_factory=list)
    path: str | os.PathLike | None = None
    flush_every: int = 20
    append: bool = False
    count: int = field(default=0, init=False)
    _sink: object = field(default=None, init=False, repr=False)

    def __post_init__(self):
        if self.path is not None and not os.fspath(self.path).endswith(
            ".csv"
        ):
            from distributed_machine_learning_tpu.telemetry.sink import (
                JsonlSink,
            )

            # append=False (default) keeps the historical fresh-file
            # semantics for unrelated reruns; the CLI passes append=True
            # for resumed runs, where truncating would destroy the
            # survivor rows the streaming mode exists to protect.
            self._sink = JsonlSink(self.path, flush_every=self.flush_every,
                                   append=self.append)

    def log(self, step: int, **metrics) -> None:
        row = {"step": step, "time": time.time(), **metrics}
        if self._sink is not None and "attempt" not in row:
            # Streamed files append across runs (by design — restarts
            # must not truncate history), so rows need a separator tag:
            # borrow the telemetry attempt when one is installed, the
            # same tag metrics.jsonl uses.
            from distributed_machine_learning_tpu.telemetry import (
                get_telemetry,
            )

            tel = get_telemetry()
            if tel is not None:
                row["attempt"] = tel.attempt
        self.count += 1
        if self._sink is not None:
            self._sink.write(row)
        else:
            self.rows.append(row)

    def save(self, path: str | os.PathLike) -> None:
        """Write rows to `path`, format chosen by extension: ``.csv`` for
        CSV, anything else JSONL.  The single dispatch point for every
        caller (CLI, bench, sweep).  In streaming mode a save to the
        streamed path flushes (the rows are already on disk) instead of
        rewriting — rewriting would truncate prior attempts' appended
        history, the exact loss this logger was rebuilt to prevent."""
        if self._sink is not None:
            if os.path.abspath(os.fspath(path)) != os.path.abspath(
                os.fspath(self.path)
            ):
                raise ValueError(
                    f"streaming MetricsLogger bound to {self.path}; "
                    f"cannot save to {os.fspath(path)} (rows are on "
                    "disk, not buffered)"
                )
            self._sink.touch()  # zero rows still leaves the file
            self._sink.close()
            return
        if os.fspath(path).endswith(".csv"):
            self.to_csv(path)
        else:
            self.to_jsonl(path)

    def to_csv(self, path: str | os.PathLike) -> None:
        if jax.process_index() != 0:
            return
        columns: list[str] = []
        for row in self.rows:
            for key in row:
                if key not in columns:
                    columns.append(key)
        os.makedirs(os.path.dirname(os.path.abspath(os.fspath(path))),
                    exist_ok=True)
        # Zero rows still writes the (possibly header-only) file, so a
        # reported path always exists.
        with open(path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=columns)
            if columns:
                writer.writeheader()
            writer.writerows(self.rows)

    def to_jsonl(self, path: str | os.PathLike) -> None:
        if jax.process_index() != 0:
            return
        os.makedirs(os.path.dirname(os.path.abspath(os.fspath(path))),
                    exist_ok=True)
        with open(path, "w") as f:
            for row in self.rows:
                f.write(json.dumps(row) + "\n")
