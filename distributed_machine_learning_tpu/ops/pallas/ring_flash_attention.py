"""Ring FLASH attention: context parallelism with Pallas chunk kernels.

``ops/ring_attention.py`` rotates K/V chunks around the mesh ring and
merges each visiting chunk into an online-softmax running state — but
computes every chunk pair densely, materializing a [B, H, Lc, Lc] score
tensor in HBM per ring step.  This module keeps the identical ring
orchestration (same ``lax.ppermute`` schedule, same online recurrence)
and replaces the per-pair math with the flash kernels: the running
(m, l, acc) triple lives in HBM between steps as O(Lc) state, each ring
step runs one ``pallas_call`` whose score blocks never leave VMEM, and
per-device attention memory drops from O(Lc²) to O(block) — on top of
the O(L/n) sharding win the ring already provides.

The per-tile arithmetic (scores, the online-softmax update, the
backward's ``p``/``ds`` recompute) is imported from
``flash_attention.py`` — ONE source of truth shared with the
single-chunk kernels; only the carry scaffolding (load/store of the
running state across pallas_calls) lives here.

Chunk relationships are resolved OUTSIDE the kernels with ``lax.cond``
on the (dynamic, per-device) visiting rank, so each branch stays a
statically-shaped kernel:

- visiting chunk == own chunk → the diagonal: causal masking, with the
  same DMA-eliding clamped index maps as single-chunk flash;
- visiting chunk strictly earlier → full attention, mask-free variants;
- visiting chunk strictly later → identity on the carry (no kernel).

Backward is the standard ring-flash second pass: Δ = rowsum(dO∘O) and
the forward's per-row logsumexp stay resident with Q; K/V rotate again,
each step adding this device's contribution to the VISITING chunk's
dK/dV (which travel the ring alongside K/V and arrive home after n
steps) and accumulating local dQ.

Grouped-query attention is native end to end: pass k/v with Hkv < H
heads and the NARROW chunks rotate on the ring (ICI traffic and the
traveling dK/dV both shrink by the group factor); the kernels' K/V tile
index maps divide by the group factor exactly like single-chunk flash,
and each step's per-query-head dK/dV contribution is group-summed
before joining the traveling narrow accumulators.

Runs in interpreter mode off-TPU, so the CPU-mesh tests exercise the
exact code path the TPU compiles.  Reference baseline: the einsum ring
(``ops/ring_attention.py``), itself property-tested against dense
attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from distributed_machine_learning_tpu.ops.pallas.flash_attention import (
    _HAS_PLTPU,
    _LANES,
    LOG2E,
    NEG_INF,
    _compiler_params,
    _dkv_blocks,
    _dispatch_tiles,
    _dkv_contrib,
    _dq_contrib,
    _first_qi,
    _fold,
    _fwd_blocks,
    _interpret,
    _kv_groups,
    _last_kb,
    _online_update,
    _tile_scores,
    _unfold,
)

if _HAS_PLTPU:
    from jax.experimental.pallas import tpu as pltpu


def _require_pltpu():
    if not _HAS_PLTPU:  # pragma: no cover — pltpu ships with jax cpu/tpu
        raise RuntimeError(
            "pallas TPU support (jax.experimental.pallas.tpu) is "
            "unavailable; use attn_impl='ring'"
        )


# ---------------------------------------------------------------------------
# Forward: one ring step = one carry-threaded chunk kernel.
# ---------------------------------------------------------------------------


def _chunk_fwd_kernel(
    q_ref, k_ref, v_ref, m_in, l_in, acc_in, m_out, l_out, acc_out,
    m_s, l_s, acc_s, *, block_q, block_k, scale, causal,
):
    """Merge one visiting K/V chunk into the online (m, l, acc) carry.

    Unlike the single-chunk flash kernel, the running triple is carried
    ACROSS calls: read from HBM at the first K step, updated in VMEM
    scratch, written back at the last K step.
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _load():
        # The HBM carry keeps m/l as exact [Lc] rows (sequence in lanes);
        # expand to the lane-replicated VMEM scratch the online update
        # wants — one relayout per Q block per ring step, in exchange for
        # 128× less carry traffic through HBM between steps.
        m_s[:] = jnp.broadcast_to(m_in[0][:, None], m_s.shape)
        l_s[:] = jnp.broadcast_to(l_in[0][:, None], l_s.shape)
        acc_s[:] = acc_in[0]

    def _do_update(tile_causal):
        v = v_ref[0]
        s = _tile_scores(q_ref[0], k_ref[0], q_start, k_start, block_q,
                         block_k, scale * LOG2E, causal=tile_causal)
        m_new, l_new, acc_new = _online_update(
            s, m_s[:, 0], l_s[:, 0], acc_s[:], v, causal=tile_causal
        )
        acc_s[:] = acc_new
        m_s[:] = jnp.broadcast_to(m_new[:, None], m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new[:, None], l_s.shape)

    _dispatch_tiles(_do_update, q_start, k_start, block_q, block_k,
                    causal=causal)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _store():
        m_out[0] = m_s[:, 0]
        l_out[0] = l_s[:, 0]
        acc_out[0] = acc_s[:]


def _chunk_fwd(q, k, v, carry, *, causal: bool, kv_groups: int = 1):
    """One ring step over folded chunks (q [BHq, Lc, D], k/v
    [BHq // kv_groups, Lc, D]); carry = (m, l, acc) with m/l
    [BHq, 1, Lc] f32 (exact rows) and acc [BHq, Lc, D] f32."""
    _require_pltpu()
    m, l, acc = carry
    BH, Lc, D = q.shape
    scale = 1.0 / (D**0.5)
    block_q, block_k = _fwd_blocks(Lc)
    grid = (BH, Lc // block_q, Lc // block_k)
    q_spec = pl.BlockSpec(
        (1, block_q, D), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
    )
    if causal:
        # Diagonal step: clamp above-diagonal K/V fetches so their DMAs
        # are elided, same as the single-chunk flash kernels.
        k_spec = pl.BlockSpec(
            (1, block_k, D),
            lambda bh, qi, kb: (
                bh // kv_groups,
                jnp.minimum(kb, _last_kb(qi, block_q, block_k)), 0,
            ),
            memory_space=pltpu.VMEM,
        )
    else:
        k_spec = pl.BlockSpec(
            (1, block_k, D),
            lambda bh, qi, kb: (bh // kv_groups, kb, 0),
            memory_space=pltpu.VMEM,
        )
    row_spec = pl.BlockSpec(
        (None, 1, block_q), lambda bh, qi, kb: (bh, 0, qi),
        memory_space=pltpu.VMEM,
    )
    acc_spec = pl.BlockSpec(
        (1, block_q, D), lambda bh, qi, kb: (bh, qi, 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        functools.partial(
            _chunk_fwd_kernel, block_q=block_q, block_k=block_k,
            scale=scale, causal=causal,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(m.shape, jnp.float32),
            jax.ShapeDtypeStruct(l.shape, jnp.float32),
            jax.ShapeDtypeStruct(acc.shape, jnp.float32),
        ),
        grid=grid,
        in_specs=[q_spec, k_spec, k_spec, row_spec, row_spec, acc_spec],
        out_specs=(row_spec, row_spec, acc_spec),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, m, l, acc)


# ---------------------------------------------------------------------------
# Backward: per-step dQ and dK/dV chunk kernels (causal + full variants).
# ---------------------------------------------------------------------------


def _chunk_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_in, dq_out, dq_s,
    *, block_q, block_k, scale, causal,
):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _load():
        dq_s[:] = dq_in[0]

    def _do_update(tile_causal):
        k = k_ref[0]
        s = _tile_scores(q_ref[0], k, q_start, k_start, block_q, block_k,
                         scale * LOG2E, causal=tile_causal)
        dq_s[:] = dq_s[:] + _dq_contrib(
            s, k, v_ref[0], do_ref[0], lse_ref[0],
            delta_ref[0], scale, causal=tile_causal,
        )

    _dispatch_tiles(_do_update, q_start, k_start, block_q, block_k,
                    causal=causal)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _store():
        dq_out[0] = dq_s[:]


def _chunk_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_in, dv_in,
    dk_out, dv_out, dk_s, dv_s, *, block_q, block_k, scale, causal,
):
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(qi == 0)
    def _load():
        dk_s[:] = dk_in[0]
        dv_s[:] = dv_in[0]

    def _do_update(tile_causal):
        q = q_ref[0]
        s = _tile_scores(q, k_ref[0], q_start, k_start, block_q, block_k,
                         scale * LOG2E, causal=tile_causal)
        dk_c, dv_c = _dkv_contrib(
            s, q, v_ref[0], do_ref[0], lse_ref[0],
            delta_ref[0], scale, causal=tile_causal,
        )
        dk_s[:] = dk_s[:] + dk_c
        dv_s[:] = dv_s[:] + dv_c

    _dispatch_tiles(_do_update, q_start, k_start, block_q, block_k,
                    causal=causal)

    @pl.when(qi == pl.num_programs(2) - 1)
    def _store():
        dk_out[0] = dk_s[:]
        dv_out[0] = dv_s[:]


def _chunk_dq(q, k, v, do, lse, delta, dq, *, causal: bool,
              kv_groups: int = 1):
    _require_pltpu()
    BH, Lc, D = q.shape
    scale = 1.0 / (D**0.5)
    block_q, block_k = _fwd_blocks(Lc)
    q_spec = pl.BlockSpec(
        (1, block_q, D), lambda bh, qi, kb: (bh, qi, 0), memory_space=pltpu.VMEM
    )
    if causal:
        k_spec = pl.BlockSpec(
            (1, block_k, D),
            lambda bh, qi, kb: (
                bh // kv_groups,
                jnp.minimum(kb, _last_kb(qi, block_q, block_k)), 0,
            ),
            memory_space=pltpu.VMEM,
        )
    else:
        k_spec = pl.BlockSpec(
            (1, block_k, D),
            lambda bh, qi, kb: (bh // kv_groups, kb, 0),
            memory_space=pltpu.VMEM,
        )
    row_spec = pl.BlockSpec(
        (None, 1, block_q), lambda bh, qi, kb: (bh, 0, qi),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        functools.partial(
            _chunk_dq_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal,
        ),
        out_shape=jax.ShapeDtypeStruct((BH, Lc, D), jnp.float32),
        grid=(BH, Lc // block_q, Lc // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                  q_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        input_output_aliases={6: 0},
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, dq)


def _chunk_dkv(q, k, v, do, lse, delta, dk, dv, *, causal: bool,
               kv_groups: int = 1):
    """dK/dV contributions of this device's Q block to one K/V chunk.

    With ``kv_groups == 1`` the in/out dk/dv are the full-width chunk
    accumulators (in-place).  With groups > 1, dk/dv must be PER QUERY
    HEAD zero buffers [BHq, Lc, D]; the caller group-sums them down to
    the narrow heads before merging into the traveling accumulators.
    """
    _require_pltpu()
    BH, Lc, D = q.shape
    scale = 1.0 / (D**0.5)
    block_q, block_k = _dkv_blocks(Lc)
    if causal:
        def _qi_map(bh, kb, qi):
            return bh, jnp.maximum(qi, _first_qi(kb, block_q, block_k)), 0

        def _qi_row_map(bh, kb, qi):
            return bh, 0, jnp.maximum(qi, _first_qi(kb, block_q, block_k))
    else:
        def _qi_map(bh, kb, qi):
            return bh, qi, 0

        def _qi_row_map(bh, kb, qi):
            return bh, 0, qi
    q_spec = pl.BlockSpec(
        (1, block_q, D), _qi_map, memory_space=pltpu.VMEM
    )
    kv_in_spec = pl.BlockSpec(
        (1, block_k, D), lambda bh, kb, qi: (bh // kv_groups, kb, 0),
        memory_space=pltpu.VMEM,
    )
    out_spec = pl.BlockSpec(
        (1, block_k, D), lambda bh, kb, qi: (bh, kb, 0),
        memory_space=pltpu.VMEM,
    )
    row_spec = pl.BlockSpec(
        (None, 1, block_q), _qi_row_map, memory_space=pltpu.VMEM
    )
    return pl.pallas_call(
        functools.partial(
            _chunk_dkv_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((BH, Lc, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, Lc, D), jnp.float32),
        ),
        grid=(BH, Lc // block_k, Lc // block_q),
        in_specs=[q_spec, kv_in_spec, kv_in_spec, q_spec, row_spec,
                  row_spec, out_spec, out_spec],
        out_specs=(out_spec, out_spec),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        input_output_aliases={6: 0, 7: 1},
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, dk, dv)


# ---------------------------------------------------------------------------
# The ring, forward + custom VJP.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_self_attention(q, k, v, axis_name: str, axis_size: int):
    """Exact causal attention over sequence chunks sharded on
    ``axis_name`` — the flash-kernel ring (see module docstring).

    Must run inside ``shard_map``; q [B, Lc, H, D] and k/v [B, Lc, Hkv,
    D] (Hkv | H — GQA rotates the narrow chunks) are the local chunks,
    global order following the mesh axis.  Per-device attention memory
    is O(block); HBM state between ring steps is O(Lc).
    """
    out, _ = _ring_fwd_impl(q, k, v, axis_name, axis_size)
    return out


def _ring_fwd_impl(q, k, v, axis_name, axis_size):
    n = axis_size
    B, Lc, H, D = q.shape
    groups = _kv_groups(q, k, v)
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    BH = qf.shape[0]
    rank = lax.axis_index(axis_name)
    carry = (
        jnp.full((BH, 1, Lc), NEG_INF, jnp.float32),
        jnp.zeros((BH, 1, Lc), jnp.float32),
        jnp.zeros((BH, Lc, D), jnp.float32),
    )
    perm = [(i, (i + 1) % n) for i in range(n)]
    kv = (kf, vf)
    for s in range(n):
        kv_rank = (rank - s) % n
        kc, vc = kv
        carry = lax.cond(
            kv_rank == rank,
            lambda c, kc=kc, vc=vc: _chunk_fwd(
                qf, kc, vc, c, causal=True, kv_groups=groups
            ),
            lambda c, kc=kc, vc=vc: lax.cond(
                kv_rank < rank,
                lambda c2: _chunk_fwd(
                    qf, kc, vc, c2, causal=False, kv_groups=groups
                ),
                lambda c2: c2,
                c,
            ),
            carry,
        )
        if s < n - 1:
            kv = lax.ppermute(kv, axis_name, perm)
    m, l, acc = carry
    l1 = jnp.maximum(l, 1e-30)  # [BH, 1, Lc]
    out = (acc / l1[:, 0, :, None]).astype(q.dtype)
    lse = m + jnp.log2(l1)  # [BH, 1, Lc] — exact rows, log2 space
    return _unfold(out, B, H), (q, k, v, out, lse)


def _ring_fwd_vjp(q, k, v, axis_name, axis_size):
    out, res = _ring_fwd_impl(q, k, v, axis_name, axis_size)
    return out, res


def _group_sum(t, B, H, groups):
    """[B·H, Lc, D] per-query-head grads → [B·Hkv, Lc, D] narrow grads
    (query heads of one KV group are contiguous after folding)."""
    BH, Lc, D = t.shape
    Hkv = H // groups
    return (
        t.reshape(B, Hkv, groups, Lc, D).sum(axis=2).reshape(B * Hkv, Lc, D)
    )


def _ring_bwd_vjp(axis_name, axis_size, res, g):
    q, k, v, out_f, lse = res  # out_f/lse already folded [BH, Lc, ...]
    n = axis_size
    B, Lc, H, D = q.shape
    groups = _kv_groups(q, k, v)
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    do = _fold(g)
    rank = lax.axis_index(axis_name)
    delta = jnp.sum(
        do.astype(jnp.float32) * out_f.astype(jnp.float32), axis=-1
    )[:, None, :]  # [BH, 1, Lc] — exact, same layout as the carried lse

    dq = jnp.zeros(qf.shape, jnp.float32)
    # dK/dV travel WITH their (narrow, under GQA) K/V chunk: after n ring
    # steps (rotating at every step including the last) the accumulated
    # grads land back on the chunk's home device.
    payload = (kf, vf, jnp.zeros(kf.shape, jnp.float32),
               jnp.zeros(vf.shape, jnp.float32))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step_dkv(kc, vc, dkc, dvc, causal):
        if groups == 1:
            return _chunk_dkv(qf, kc, vc, do, lse, delta, dkc, dvc,
                              causal=causal)
        # GQA: per-query-head contributions into zero buffers, then one
        # cheap group-sum before joining the narrow traveling grads.
        z = jnp.zeros(qf.shape, jnp.float32)
        dk_q, dv_q = _chunk_dkv(qf, kc, vc, do, lse, delta, z, z,
                                causal=causal, kv_groups=groups)
        return (dkc + _group_sum(dk_q, B, H, groups),
                dvc + _group_sum(dv_q, B, H, groups))

    for s in range(n):
        kv_rank = (rank - s) % n
        kc, vc, dkc, dvc = payload

        def diag(dq, dkc, dvc, kc=kc, vc=vc):
            dq2 = _chunk_dq(qf, kc, vc, do, lse, delta, dq, causal=True,
                            kv_groups=groups)
            dk2, dv2 = step_dkv(kc, vc, dkc, dvc, causal=True)
            return dq2, dk2, dv2

        def full(dq, dkc, dvc, kc=kc, vc=vc):
            dq2 = _chunk_dq(qf, kc, vc, do, lse, delta, dq, causal=False,
                            kv_groups=groups)
            dk2, dv2 = step_dkv(kc, vc, dkc, dvc, causal=False)
            return dq2, dk2, dv2

        dq, dkc, dvc = lax.cond(
            kv_rank == rank,
            diag,
            lambda dq, dkc, dvc: lax.cond(
                kv_rank < rank, full, lambda a, b, c: (a, b, c),
                dq, dkc, dvc,
            ),
            dq, dkc, dvc,
        )
        # Rotate on EVERY step so the traveling grads complete the full
        # circle home (n rotations == identity for k/v themselves).
        payload = lax.ppermute((kc, vc, dkc, dvc), axis_name, perm)

    _, _, dk, dv = payload
    Hkv = H // groups
    return (
        _unfold(dq, B, H).astype(q.dtype),
        _unfold(dk, B, Hkv).astype(k.dtype),
        _unfold(dv, B, Hkv).astype(v.dtype),
    )


ring_flash_self_attention.defvjp(_ring_fwd_vjp, _ring_bwd_vjp)
