"""Schedule-walker unit tests for the ring overlap audit
(bench/overlap_audit.py); the TPU AOT compile itself is exercised by
the audit's __main__ on TPU-capable hosts."""

import pytest

from distributed_machine_learning_tpu.bench.overlap_audit import audit_schedule

HLO = """\
HloModule m

ENTRY main {
  p0 = f32[8]{0} parameter(0)
  cps.1 = (f32[8]{0}, f32[8]{0}) collective-permute-start(p0), source_target_pairs={{0,1}}
  f.1 = f32[8]{0} fusion(p0), kind=kLoop, calls=fused_add
  cpd.1 = f32[8]{0} collective-permute-done(cps.1)
  cps.2 = (f32[8]{0}, f32[8]{0}) collective-permute-start(cpd.1), source_target_pairs={{0,1}}
  cpd.2 = f32[8]{0} collective-permute-done(cps.2)
  ROOT r = f32[8]{0} add(cpd.1, cpd.2)
}
"""


def test_audit_counts_windows_and_overlap():
    s = audit_schedule(HLO)
    assert s["async_ppermute_pairs"] == 2
    assert s["pairs_with_compute_in_window"] == 1  # f.1 inside window 1
    assert s["distinct_compute_ops_in_windows"] == 1
    assert s["op_kinds_in_windows"] == {"fusion": 1}
    assert s["max_concurrent_in_flight"] == 1


def test_audit_rejects_entryless_text():
    with pytest.raises(ValueError, match="ENTRY"):
        audit_schedule("HloModule empty")
