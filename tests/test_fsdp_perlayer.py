"""Per-layer (GSPMD) FSDP: numerical equivalence vs replicated DP,
per-leaf shard accounting, and the guard rails.

Same bar as the flat-vector scheme's tests (test_fsdp.py): ZeRO-3 is a
*placement* change — the per-layer step must reproduce the replicated
LM step's updates exactly, while each big leaf materializes only 1/N
per device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.fsdp_perlayer import (
    fsdp_pl_sharded_fraction,
    fsdp_pl_spec_for,
    make_fsdp_pl_lm_train_step,
    shard_fsdp_pl_state,
)
from distributed_machine_learning_tpu.train.adamw import AdamWConfig
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
    shard_lm_batch,
)
from distributed_machine_learning_tpu.train.sgd import SGDConfig


def _model(**kw):
    return TransformerLM(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                         attn_impl="dense", **kw)


def _tokens(steps=3, batch=8, seq=16):
    rng = np.random.default_rng(3)
    toks = rng.integers(0, 64, (steps, batch, seq + 1)).astype(np.int32)
    return jnp.asarray(toks[:, :, :-1]), jnp.asarray(toks[:, :, 1:])


@pytest.mark.parametrize("config", [SGDConfig(), AdamWConfig()],
                         ids=["sgd", "adamw"])
def test_fsdp_pl_matches_replicated_dp(mesh8, config):
    model = _model()
    xs, ys = _tokens()

    # Replicated DP reference (the 2-D dp mesh with a trivial seq axis).
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    dp_mesh = make_mesh(8, ("batch", "seq"), (8, 1))
    ref_state = init_lm_state(model, config=config)
    ref_step = make_lm_train_step(model, mesh=dp_mesh)

    pl_state = shard_fsdp_pl_state(init_lm_state(model, config=config), mesh8)
    pl_step = make_fsdp_pl_lm_train_step(model, mesh8)

    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        shard_tp_batch,
    )

    for i in range(xs.shape[0]):
        rx, ry = shard_lm_batch(dp_mesh, xs[i], ys[i])
        ref_state, ref_loss = ref_step(ref_state, rx, ry)
        px, py = shard_tp_batch(mesh8, xs[i], ys[i])
        pl_state, pl_loss = pl_step(pl_state, px, py)
        np.testing.assert_allclose(float(pl_loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)

    for a, b in zip(jax.tree_util.tree_leaves(pl_state.params),
                    jax.tree_util.tree_leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_fsdp_pl_shards_leaves_one_nth(mesh8):
    state = shard_fsdp_pl_state(init_lm_state(_model()), mesh8)
    rule = fsdp_pl_spec_for(8)
    checked = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(state.params):
        keys = tuple(getattr(k, "key", str(k)) for k in path)
        spec = rule(keys, tuple(leaf.shape))
        if any(a is not None for a in spec):
            dim = next(i for i, a in enumerate(spec) if a is not None)
            for shard in leaf.addressable_shards:
                assert shard.data.shape[dim] == leaf.shape[dim] // 8, keys
            checked += 1
    assert checked > 0
    # Nearly all parameter MEMORY must shard — only odd-width biases
    # may replicate.
    assert fsdp_pl_sharded_fraction(init_lm_state(_model()), mesh8) > 0.9


def test_fsdp_pl_rule_picks_largest_divisible_dim():
    rule = fsdp_pl_spec_for(8, "batch")
    assert tuple(rule((), (64, 8))) == ("batch", None)
    assert tuple(rule((), (8, 64))) == (None, "batch")
    assert tuple(rule((), (3, 64))) == (None, "batch")
    assert tuple(rule((), (7,))) == (None,)  # nothing divisible: replicate
    assert tuple(rule((), ())) == ()  # scalar


def test_fsdp_pl_guards(mesh8):
    from distributed_machine_learning_tpu.train.lars import LARSConfig

    with pytest.raises(ValueError, match="LARS"):
        shard_fsdp_pl_state(init_lm_state(_model(), config=LARSConfig()),
                            mesh8)
    with pytest.raises(ValueError, match="second mesh axis"):
        make_fsdp_pl_lm_train_step(
            TransformerLM(vocab_size=64, d_model=32, n_layers=1, n_heads=4,
                          attn_impl="ring"),
            mesh8,
        )


def test_fsdp_pl_flash_matches_plain_flash(mesh8):
    """Flash under the GSPMD step (shard_map-wrapped kernel) must equal
    the plain single-program flash step — the wrap changes placement,
    not math."""
    model = TransformerLM(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                          attn_impl="flash")
    xs, ys = _tokens(steps=2)

    ref_state = init_lm_state(model)
    ref_step = make_lm_train_step(model, mesh=None)

    pl_state = shard_fsdp_pl_state(init_lm_state(model), mesh8)
    pl_step = make_fsdp_pl_lm_train_step(model, mesh8)

    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        shard_tp_batch,
    )

    for i in range(xs.shape[0]):
        ref_state, ref_loss = ref_step(ref_state, xs[i], ys[i])
        px, py = shard_tp_batch(mesh8, xs[i], ys[i])
        pl_state, pl_loss = pl_step(pl_state, px, py)
        np.testing.assert_allclose(float(pl_loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(pl_state.params),
                    jax.tree_util.tree_leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_tp_flash_matches_plain_flash():
    """Head-sharded flash under TP (shard_map-wrapped kernel) must equal
    the plain flash step — with genuinely GROUPED K/V (Hkv < H), so the
    claim that each model-axis shard keeps its GQA groups aligned
    (H_local = groups · Hkv_local) is what the test exercises."""
    from distributed_machine_learning_tpu.parallel.tensor_parallel import (
        make_tp_lm_train_step,
        shard_tp_batch,
        shard_tp_state,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    model = TransformerLM(vocab_size=64, d_model=32, n_layers=2, n_heads=8,
                          n_kv_heads=4, attn_impl="flash")
    xs, ys = _tokens(steps=2)

    ref_state = init_lm_state(model)
    ref_step = make_lm_train_step(model, mesh=None)

    # dp 2 × tp 4: narrow K/V (1 head/shard) shared by 2 query heads.
    mesh = make_mesh(8, ("batch", "model"), (2, 4))
    tp_step = make_tp_lm_train_step(model, mesh)
    tp_state = shard_tp_state(init_lm_state(model), mesh)

    for i in range(xs.shape[0]):
        ref_state, ref_loss = ref_step(ref_state, xs[i], ys[i])
        px, py = shard_tp_batch(mesh, xs[i], ys[i])
        tp_state, tp_loss = tp_step(tp_state, px, py)
        np.testing.assert_allclose(float(tp_loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(tp_state.params),
                    jax.tree_util.tree_leaves(ref_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


def test_pp_flash_matches_pp_dense():
    """Flash inside the (fully-manual) pipeline shard_map: both
    schedules train with flash spans and match their dense twins within
    kernel tolerance."""
    from distributed_machine_learning_tpu.parallel.pipeline import (
        init_pipeline_state,
        make_pp_lm_train_step,
        microbatch,
        shard_pp_state,
    )
    from distributed_machine_learning_tpu.parallel.pipeline_1f1b import (
        make_pp_1f1b_lm_train_step,
    )
    from distributed_machine_learning_tpu.runtime.mesh import make_mesh

    mesh = make_mesh(8, axis_names=("pipe",))
    xs, ys = _tokens(steps=1, batch=8)
    mx, my = microbatch(xs[0], ys[0], 2)
    results = {}
    for attn in ("dense", "flash"):
        model = TransformerLM(vocab_size=64, d_model=32, n_layers=8,
                              n_heads=4, attn_impl=attn)
        for name, builder in (("gpipe", make_pp_lm_train_step),
                              ("1f1b", make_pp_1f1b_lm_train_step)):
            st = shard_pp_state(init_pipeline_state(model), mesh)
            st, loss = builder(model, mesh, 2)(st, mx, my)
            results[(attn, name)] = (float(loss), st.params)
    for name in ("gpipe", "1f1b"):
        d_loss, d_params = results[("dense", name)]
        f_loss, f_params = results[("flash", name)]
        np.testing.assert_allclose(f_loss, d_loss, rtol=1e-4)
        for a, b in zip(jax.tree_util.tree_leaves(f_params),
                        jax.tree_util.tree_leaves(d_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-6)
