"""Pipeline-parallel LM step (parallel/pipeline.py): the P-stage ppermute
pipeline must take exactly the same training step as the dense model on a
single device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_machine_learning_tpu.models.transformer import TransformerLM
from distributed_machine_learning_tpu.parallel.pipeline import (
    init_pipeline_state,
    make_pp_lm_train_step,
    microbatch,
    shard_pp_state,
    stack_lm_params,
    unstack_lm_params,
)
from distributed_machine_learning_tpu.runtime.mesh import make_mesh
from distributed_machine_learning_tpu.train.lm_step import (
    init_lm_state,
    make_lm_train_step,
)

VOCAB, B, L, LAYERS = 64, 4, 16, 4


def tiny_lm():
    return TransformerLM(
        vocab_size=VOCAB, d_model=32, n_layers=LAYERS, n_heads=4
    )


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(23)
    toks = rng.integers(0, VOCAB, (B, L + 1))
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def test_stack_unstack_roundtrip():
    model = tiny_lm()
    params = init_lm_state(model).params
    stacked = stack_lm_params(params, LAYERS)
    assert stacked["blocks"]["attn"]["qkv"]["kernel"].shape[0] == LAYERS
    restored = unstack_lm_params(stacked, LAYERS)
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(restored)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "stages,microbatches",
    [(2, 2),
     pytest.param(4, 2, marks=pytest.mark.slow),
     pytest.param(4, 4, marks=pytest.mark.slow)],
)
def test_pp_step_equals_single_device(batch, stages, microbatches):
    tokens, targets = batch
    model = tiny_lm()

    ref_state = init_lm_state(model)
    ref_step = make_lm_train_step(model, mesh=None)
    ref_state, ref_loss = ref_step(
        ref_state, jnp.asarray(tokens), jnp.asarray(targets)
    )

    mesh = make_mesh(stages, axis_names=("pipe",))
    state = shard_pp_state(init_pipeline_state(model), mesh)
    step = make_pp_lm_train_step(model, mesh, num_microbatches=microbatches)
    x, y = microbatch(tokens, targets, microbatches)
    state, loss = step(state, x, y)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    got = unstack_lm_params(state.params, LAYERS)
    want = ref_state.params
    key = lambda kv: str(kv[0])
    for (ka, a), (kb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(got), key=key),
        sorted(jax.tree_util.tree_leaves_with_path(want), key=key),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5, err_msg=str(ka)
        )


@pytest.mark.slow
@pytest.mark.parametrize("optimizer", ["sgd", "adamw"])
def test_pp_overlap_update_parity(batch, optimizer):
    """ISSUE-9 pipeline composition: overlap_update shards the
    boundary-module (embed/ln_f/lm_head) optimizer update over the pipe
    axis and ring-gathers the slices back.  SGD is bitwise identical to
    the replicated update; AdamW agrees to ~1 ulp (the flat-vector
    update compiles with different FMA contraction than the per-leaf
    program — measured |Δ| ≤ 4e-9 on a handful of elements) — a real
    slicing/gather bug would blow past these bars on most elements."""
    from distributed_machine_learning_tpu.train.adamw import AdamWConfig

    tokens, targets = batch
    model = tiny_lm()
    cfg = AdamWConfig() if optimizer == "adamw" else None
    mesh = make_mesh(2, axis_names=("pipe",))
    x, y = microbatch(tokens, targets, 2)

    def run(overlap):
        state = shard_pp_state(init_pipeline_state(model, config=cfg),
                               mesh)
        step = make_pp_lm_train_step(model, mesh, num_microbatches=2,
                                     overlap_update=overlap)
        losses = []
        for _ in range(3):
            state, loss = step(state, x, y)
            losses.append(float(loss))
        return state, losses

    sync, sync_losses = run(False)
    ov, ov_losses = run(True)
    assert sync_losses == ov_losses
    for tree_pair in ((sync.params, ov.params),
                      (sync.momentum, ov.momentum)):
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(tree_pair[0]),
            jax.tree_util.tree_leaves_with_path(tree_pair[1]),
        ):
            if optimizer == "sgd":
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b),
                    err_msg=jax.tree_util.keystr(pa))
            else:
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=0, atol=1e-7,
                    err_msg=jax.tree_util.keystr(pa))


def test_pp_guards(batch):
    model = tiny_lm()
    mesh3 = make_mesh(3, axis_names=("pipe",))
    with pytest.raises(ValueError, match="divide evenly"):
        make_pp_lm_train_step(model, mesh3, num_microbatches=2)
    ring = TransformerLM(vocab_size=VOCAB, d_model=32, n_layers=4, n_heads=4,
                         attn_impl="ring")
    mesh2 = make_mesh(2, axis_names=("pipe",))
    with pytest.raises(ValueError, match="dense"):
        make_pp_lm_train_step(ring, mesh2, num_microbatches=2)
    with pytest.raises(ValueError, match="not divisible"):
        microbatch(np.zeros((4, 8)), np.zeros((4, 8)), 3)
