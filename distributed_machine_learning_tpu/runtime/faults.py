"""Deterministic fault injection — prove the runtime survives, don't hope.

The reference never sees a fault it can recover from: one stalled gloo
rank deadlocks the other three forever (SURVEY.md §5), and nothing in
its 908 LoC can even *produce* a controlled failure to test against.
This module is the chaos half of the self-healing runtime
(`runtime/supervisor.py` is the healing half): a seedable injector that
forces each production fault class at a chosen step, so the
skip/retry/restart ladder is exercised by tests instead of trusted on
faith.

Fault classes (spec grammar ``kind@step[:arg]``, comma-separated):

- ``nan@K``       poison batch K's input with NaN → the jitted step's
                  non-finite-gradient guard must skip the update
                  (float-input pipelines only; token streams are
                  integral and cannot carry a NaN).
- ``raise@K``     raise :class:`InjectedFault` from the data iterator at
                  batch K → the retrying data path (``data/retry.py``)
                  must recreate the iterator and resume.
- ``stall@K:S``   sleep S seconds before yielding batch K → the
                  watchdog must declare a stall; the supervisor restarts
                  from the latest checkpoint.
- ``kill_ckpt@N`` die during the N-th (1-based) checkpoint save, after
                  the state dir lands but before the config file — the
                  crash window ``_is_complete`` exists for.  Default
                  raises :class:`InjectedKill` (so an in-process
                  supervisor can catch the crash boundary); ``:exit``
                  calls ``os._exit(17)`` for external supervisors.

``K`` may be ``?``: the step is drawn deterministically from ``seed``
(same seed → same plan), so randomized chaos runs stay reproducible.

Everything is OFF by default: an injector only exists when a spec is
given (``--faults`` or the ``DML_FAULTS`` env var), and a fault fires
exactly once.  All injection is host-side — the compiled step is never
touched; faults enter through the data stream and the checkpoint path,
the same doors real faults use.
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from distributed_machine_learning_tpu.utils.logging import rank0_print

FAULTS_ENV = "DML_FAULTS"

_KIND_ALIASES = {
    "nan": "nan",
    "nan_grad": "nan",
    "raise": "raise",
    "data_raise": "raise",
    "stall": "stall",
    "kill_ckpt": "kill_ckpt",
    "kill": "kill_ckpt",
}


class InjectedFault(RuntimeError):
    """A fault deliberately raised by the injector (data-path class)."""


class InjectedKill(InjectedFault):
    """A simulated process death mid-checkpoint.

    Raised (instead of ``os._exit``) so an in-process supervisor can
    observe the crash *boundary* — the half-written checkpoint is
    already on disk when this propagates, exactly as if the process had
    died there.
    """


@dataclasses.dataclass
class FaultEvents:
    """Counters for every robustness event — the observable surface.

    A silent recovery is indistinguishable from a bug that never
    triggered; every skip/retry/stall/restart increments a counter here,
    and ``utils/summary.py::resilience_summary`` renders the table the
    run prints.  Shared mutable state between the loop, the loaders, the
    watchdog, and the supervisor (all same-thread or GIL-atomic
    ``+= 1`` updates).
    """

    skipped_steps: int = 0      # non-finite-gradient guard skipped the update
    scaler_backoffs: int = 0    # dynamic loss scale halved on overflow
    scaler_growths: int = 0     # dynamic loss scale doubled after good steps
    loader_retries: int = 0     # data iterator recreated after an exception
    skipped_batches: int = 0    # batch dropped after exhausting its attempts
    stalls: int = 0             # watchdog declared a stall episode
    restarts: int = 0           # supervisor restored a checkpoint and retried
    preemptions: int = 0        # SIGTERM turned into a clean checkpointed stop
    ckpt_kills: int = 0         # injected death mid-checkpoint-save

    def __setattr__(self, name: str, value) -> None:
        # Mirror every increment into the telemetry registry AS IT
        # HAPPENS (``fault_events{kind=...}`` counters) — the end-of-run
        # summary shows totals, but a restart wipes this object's host
        # memory while the streamed registry survives; catching the
        # write here instruments every `events.x += 1` site at once.
        prev = self.__dict__.get(name)
        object.__setattr__(self, name, value)
        if isinstance(prev, int) and isinstance(value, int) and value > prev:
            from distributed_machine_learning_tpu.telemetry import (
                get_telemetry,
            )

            tel = get_telemetry()
            if tel is not None:
                tel.registry.counter("fault_events", kind=name).inc(
                    value - prev
                )
                tel.tracer.instant(f"fault_{name}")
                # Export NOW: the next thing after some of these events
                # is a process death (kill_ckpt's os._exit mode) — a
                # counter only in host memory at that point is lost,
                # and the re-exec would rehydrate stale totals.  Fault
                # events are rare; two atomic file writes each is
                # noise.
                tel.flush()

    def as_dict(self) -> dict[str, int]:
        return dataclasses.asdict(self)

    def total(self) -> int:
        return sum(self.as_dict().values())


@dataclasses.dataclass
class _Fault:
    kind: str
    at: int            # batch index (data faults) / save ordinal (kill_ckpt)
    arg: str | None = None
    fired: bool = False


class FaultInjector:
    """Parses a fault spec and fires each fault exactly once.

    One injector instance spans a whole supervised run — restarts and
    data-path replays cross the same indices again, and the fired-once
    latch is what keeps a recovered fault from re-firing forever.
    """

    def __init__(self, faults: list[_Fault]):
        self._faults = faults
        self._saves = 0

    # -- construction ---------------------------------------------------
    @classmethod
    def parse(cls, spec: str, seed: int = 0, horizon: int = 40
              ) -> "FaultInjector":
        """``"nan@2,raise@4,stall@7:2.5,kill_ckpt@1"`` → injector.

        ``?`` steps draw from ``default_rng(seed)`` in ``[1, horizon)``,
        in spec order — deterministic per (spec, seed).
        """
        if horizon < 2:
            raise ValueError(f"horizon must be >= 2, got {horizon}")
        rng = np.random.default_rng(seed)
        faults = []
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "@" not in entry:
                raise ValueError(
                    f"bad fault entry {entry!r}: expected kind@step[:arg]"
                )
            kind, _, rest = entry.partition("@")
            kind = kind.strip()
            if kind not in _KIND_ALIASES:
                raise ValueError(
                    f"unknown fault kind {kind!r}; known: "
                    f"{sorted(set(_KIND_ALIASES))}"
                )
            kind = _KIND_ALIASES[kind]
            at_s, _, arg = rest.partition(":")
            at_s = at_s.strip()
            if at_s == "?":
                at = int(rng.integers(1, horizon))
            else:
                try:
                    at = int(at_s)
                except ValueError:
                    raise ValueError(
                        f"bad fault step {at_s!r} in {entry!r} (an integer "
                        "or '?')"
                    ) from None
            if at < 0:
                raise ValueError(f"fault step must be >= 0, got {at}")
            arg = arg.strip() or None
            if kind == "stall":
                float(arg if arg is not None else _default_stall(None))
            if kind == "kill_ckpt":
                if at < 1:
                    raise ValueError(
                        "kill_ckpt ordinal is 1-based (the first save is 1)"
                    )
                if arg not in (None, "exit"):
                    raise ValueError(
                        f"kill_ckpt arg must be 'exit' or absent, got {arg!r}"
                    )
            faults.append(_Fault(kind=kind, at=at, arg=arg))
        return cls(faults)

    @classmethod
    def from_flags(cls, spec: str | None, seed: int = 0, horizon: int = 40
                   ) -> "FaultInjector | None":
        """Injector from an explicit spec, else the ``DML_FAULTS`` env
        var, else None (the default: no injection machinery at all)."""
        spec = spec or os.environ.get(FAULTS_ENV)
        if not spec:
            return None
        return cls.parse(spec, seed=seed, horizon=horizon)

    # -- data-path faults ----------------------------------------------
    def wrap_batches(self, batches, events: FaultEvents | None = None,
                     start: int = 0):
        """Wrap a batch iterator; data faults fire at absolute index
        ``start + j``.  Replays (retry fast-forward, post-restart) cross
        fired indices without re-firing."""
        for j, batch in enumerate(batches):
            idx = start + j
            for f in self._faults:
                if f.fired or f.at != idx:
                    continue
                if f.kind == "stall":
                    f.fired = True
                    stall_s = float(f.arg) if f.arg else _default_stall(None)
                    rank0_print(
                        f"[faults] stalling {stall_s}s before batch {idx}"
                    )
                    time.sleep(stall_s)
                elif f.kind == "raise":
                    f.fired = True
                    raise InjectedFault(f"injected loader fault at batch {idx}")
                elif f.kind == "nan":
                    f.fired = True
                    rank0_print(f"[faults] poisoning batch {idx} with NaN")
                    batch = _poison(batch)
            yield batch

    # -- checkpoint faults ---------------------------------------------
    def mid_save_hook(self, events: FaultEvents | None = None):
        """Hook for ``save_checkpoint(mid_save_hook=...)`` — called after
        the state dir lands, before the config file.  Fires ``kill_ckpt``
        on its save ordinal."""

        def hook():
            self._saves += 1
            for f in self._faults:
                if f.fired or f.kind != "kill_ckpt" or f.at != self._saves:
                    continue
                f.fired = True
                if events is not None:
                    events.ckpt_kills += 1
                if f.arg == "exit":
                    rank0_print(
                        f"[faults] killing process mid-checkpoint "
                        f"(save #{self._saves})"
                    )
                    os._exit(17)
                raise InjectedKill(
                    f"injected death mid-checkpoint (save #{self._saves}; "
                    "state dir written, config file not)"
                )

        return hook

    def has_kind(self, kind: str) -> bool:
        """Whether the spec contains any fault of ``kind`` (fired or
        not) — lets callers reject configurations where that fault
        class could never fire (e.g. kill_ckpt under --async-ckpt)."""
        kind = _KIND_ALIASES.get(kind, kind)
        return any(f.kind == kind for f in self._faults)

    def pending(self) -> list[str]:
        """Human-readable unfired faults (for the run banner)."""
        return [
            f"{f.kind}@{f.at}" + (f":{f.arg}" if f.arg else "")
            for f in self._faults
            if not f.fired
        ]


def _default_stall(_) -> float:
    return 2.0


def _poison(batch):
    """Replace the float-able input of an ``(x, y)`` batch with NaN.

    The poisoned array rides the normal host→device path; ``normalize``
    accepts float input, so NaN propagates through loss and gradients —
    the blowup the guard must catch.  Integer token streams cannot carry
    a NaN; that pipeline's guard is unit-tested at the step level
    instead (``tests/test_resilience.py``).
    """
    x, *rest = batch
    x = np.asarray(x)
    if not np.issubdtype(x.dtype, np.floating) and not np.issubdtype(
        x.dtype, np.integer
    ):
        raise TypeError(f"cannot poison batch of dtype {x.dtype}")
    if np.issubdtype(x.dtype, np.integer) and x.ndim < 3:
        raise TypeError(
            "refusing to poison what looks like an integer token/label "
            "array (the model indexes with it); nan faults need a "
            "float-able input pipeline like the CNN image path"
        )
    poisoned = np.full(x.shape, np.nan, np.float32)
    return (poisoned, *rest)
