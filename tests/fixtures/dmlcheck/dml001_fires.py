# dmlcheck-virtual-path: distributed_machine_learning_tpu/runtime/fixture.py
"""DML001 firing case: wall-clock readings in staleness arithmetic."""
import os
import time

last_seen = 0.0
PEER_TIMEOUT = 30.0


def peer_is_dead(path):
    # Comparing a local wall clock to a cross-host file mtime: NFS
    # clock skew of a minute reads as instant death.
    return time.time() - os.path.getmtime(path) > PEER_TIMEOUT


def progress_age():
    now = time.time()
    return now - last_seen  # tainted-name subtraction
